"""graftlint analyzer suite + lock-order watchdog tests.

Each pass is exercised against a known-violation fixture file
(tests/graftlint_fixtures/) with EXACT finding counts asserted — a pass
that silently stops matching its hazard class fails here, not in some
future review round — plus one clean file all four passes must accept.
The suppression-baseline mechanism is tested end-to-end through the CLI
(write-baseline → suppressed run → stale entry fails), and the full-tree
run must be clean with the checked-in EMPTY baseline: the lint gate the
Makefile enforces is also a unit test.
"""

import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GRAFTLINT_DIR = os.path.join(REPO, "scripts", "graftlint")
if GRAFTLINT_DIR not in sys.path:
    sys.path.append(GRAFTLINT_DIR)

import blocking  # noqa: E402
import config as gl_config  # noqa: E402
import core  # noqa: E402
import degraded  # noqa: E402
import donation  # noqa: E402
import fenceseam  # noqa: E402
import guardedby  # noqa: E402
import metrics_contract  # noqa: E402
import pragmas as gl_pragmas  # noqa: E402
import threads as gl_threads  # noqa: E402
import tracingpass as gl_tracing  # noqa: E402

FIXTURES = "tests/graftlint_fixtures"
FIXTURE_DOC = os.path.join(REPO, FIXTURES, "fixtures_metrics.md")


def _tree(*names):
    return core.Tree(REPO, [f"{FIXTURES}/{n}" for n in names])


def _keys(findings):
    return sorted(f.key for f in findings)


# -- pass 1: donation safety -------------------------------------------------


def test_donation_fixture_exact_findings():
    found = donation.run(_tree("viol_donation.py"))
    assert _keys(found) == [
        "alias-safe-contradiction:_lying_safe",
        "retired-device-lock:legacy_locked",
        "unlocked-donation:legacy_locked:_don",
        "unlocked-donation:unlocked_call:_don",
        "unmarked-handoff:seam:_don",
    ]


def test_donation_discovers_through_factory_and_alias():
    src = _tree("viol_donation.py")
    per_mod, factories = donation.discover(src)
    mod = src.modules[0]
    assert "_don" in per_mod[mod].module_level
    assert "_lying_safe" in per_mod[mod].module_level


def test_generation_lease_fixture_exact_findings():
    """The generation-lease discipline that replaced device_lock: a
    holds-generation-lease function's callers carry the obligation, a
    retired-lock with-region is flagged wherever it appears, and a bare
    donation site is still a finding — while lease-held call-form
    with-regions and alias-safe variants stay clean."""
    found = donation.run(_tree("viol_generation.py"))
    assert _keys(found) == [
        "retired-device-lock:old_style_reader",
        "unlocked-caller:caller_outside:advance",
        "unlocked-donation:chunk_no_marker:_scatter",
    ]


def test_fastpath_fixture_exact_findings():
    """Split-phase fast-path readback discipline: a copy_to_host_async
    fired after the launching donation lease released (and outside any
    pin_generation region) is a finding — while the same call inside
    the lease or inside an explicit generation pin stays clean."""
    found = donation.run(_tree("viol_fastpath.py"))
    assert _keys(found) == [
        "fastpath-escape:escaped_readback:res.chosen",
    ]


# -- pass 2: dispatch-thread blocking calls ----------------------------------


def test_blocking_fixture_exact_findings():
    found = blocking.run(_tree("viol_blocking.py"))
    msgs = sorted(f.message for f in found)
    assert len(found) == 5, msgs
    assert sum("queue.put" in m for m in msgs) == 1
    assert sum(".join()" in m for m in msgs) == 1
    assert sum("store RPC" in m for m in msgs) == 1
    assert sum("time.sleep under a hot lock" in m for m in msgs) == 1
    assert sum("without a reason" in m for m in msgs) == 1


# -- pass 3: metrics contract ------------------------------------------------


def test_metrics_fixture_exact_findings():
    found = metrics_contract.run(
        _tree("viol_metrics.py"), REPO, doc_path=FIXTURE_DOC
    )
    by_kind = {}
    for f in found:
        by_kind.setdefault(f.key.split(":")[0], []).append(f.key)
    assert by_kind.pop("counter-suffix") == ["counter-suffix:fixture_bad_count"]
    assert by_kind.pop("label-drift") == ["label-drift:fixture_drift_total"]
    assert by_kind.pop("kind-conflict") == ["kind-conflict:fixture_kind_total"]
    assert len(by_kind.pop("dynamic-name")) == 1
    assert sorted(by_kind.pop("undocumented")) == [
        "undocumented:fixture_bad_count",
        "undocumented:fixture_drift_total",
        "undocumented:fixture_kind_total",
    ]
    assert not by_kind, f"unexpected finding kinds: {by_kind}"


# -- pass 4: degraded-write handling -----------------------------------------


def test_degraded_fixture_exact_findings():
    found = degraded.run(_tree("viol_degraded.py"), dirs=(FIXTURES,))
    assert _keys(found) == [
        "no-reason:lazy_marker:create",
        "unguarded-write:flip:guaranteed_update",
        "unguarded-write:naked_create:create",
    ]


# -- pass 5: bind-fence seam --------------------------------------------------


def test_fenceseam_fixture_exact_findings():
    found = fenceseam.run(_tree("viol_fenceseam.py"), dirs=(FIXTURES,))
    assert _keys(found) == [
        "no-reason:lazy_exempt:bind_pod",
        "unfenced-bind:rogue_batch:bind_pods",
        "unfenced-bind:rogue_single:bind_pod",
    ]


def test_fenceseam_production_scheduler_is_clean():
    """The production scheduler tree routes every bind write through
    _bind_pods_fenced (or carries a reasoned fence-exempt marker on the
    injected-surface call) — the gap ISSUE-10 closed stays closed."""
    rels = core.discover(REPO, ("kubernetes_tpu",), ())
    tree = core.Tree(REPO, rels)
    assert fenceseam.run(tree) == []


# -- pass 6: guarded-by inference ---------------------------------------------


def test_guardedby_fixture_exact_findings():
    """The lockset contract, statically: a majority-guarded dict with a
    minority bare access, a declared guard violated, a reasonless
    unguarded pragma, and a shared module global written outside its
    lock — while the call-graph-inherited helper, the attr-level
    unguarded override, and the reasoned pragma stay silent."""
    found = guardedby.run(_tree("viol_guardedby.py"), classes=("FixtureCache",))
    assert _keys(found) == [
        "no-reason:FixtureCache._hits:lazy_read",
        "unguarded:FixtureCache._era:bump_era",
        "unguarded:FixtureCache._items:bad_peek",
        "unguarded:viol_guardedby._epoch:racy_bump",
    ]


def test_guardedby_finding_message_shape():
    found = guardedby.run(_tree("viol_guardedby.py"), classes=("FixtureCache",))
    msg = next(f for f in found if f.key.endswith("_items:bad_peek")).message
    assert "guarded by 'FixtureCache._lock' at 3 sites, unguarded here" in msg


def test_guardedby_inference_map():
    guards, _f, _a = guardedby.infer(
        _tree("viol_guardedby.py"), classes=("FixtureCache",)
    )
    by = {(g.owner, g.attr): g for g in guards}
    assert by[("FixtureCache", "_items")].lock == "FixtureCache._lock"
    assert by[("FixtureCache", "_era")].declared
    assert by[("FixtureCache", "_solo")].exempt
    # the lock attribute itself is never written post-init: no guard row
    assert by[("FixtureCache", "_lock")].lock is None


def test_guardedby_production_tree_clean_and_documented():
    """THE tentpole gate: every shared attribute of the concurrency-
    critical classes has a consistent lockset (minority accesses fixed
    in ISSUE 12, not baselined), and the inferred attr→lock map is
    documented in the README table."""
    rels = core.discover(REPO, ("kubernetes_tpu",), ())
    tree = core.Tree(REPO, rels)
    assert guardedby.run(tree, REPO) == []


# -- thread-hygiene pass -------------------------------------------------------


def test_threads_fixture_exact_findings():
    found = gl_threads.run(_tree("viol_threads.py"))
    assert _keys(found) == [
        "implicit-daemon:spawn_implicit",
        "no-reason:spawn_lazy_marked",
        "unjoined:spawn_none_join:t2",
        "unjoined:spawn_unjoined:t",
    ]


def test_threads_production_tree_clean():
    rels = core.discover(REPO, ("kubernetes_tpu",), ())
    tree = core.Tree(REPO, rels)
    assert gl_threads.run(tree) == []


# -- stale-pragma audit --------------------------------------------------------


def test_stale_pragma_flagged_when_no_pass_consults(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        "def f():\n"
        "    return 1  # graftlint: allow-blocking(nothing blocking here)\n"
    )
    tree = core.Tree(str(tmp_path), ["mod.py"])
    blocking.run(tree)  # nothing blocking -> pragma never consulted
    found = gl_pragmas.run(tree)
    assert _keys(found) == ["stale:allow-blocking"]
    assert "no pass consults it" in found[0].message


def test_consulted_pragma_not_stale():
    """viol_blocking's allow-blocking pragma sits on a real blocking call:
    the blocking pass consults (and rejects) it, so the audit is silent."""
    tree = _tree("viol_blocking.py")
    blocking.run(tree)
    assert gl_pragmas.run(tree) == []


def test_unaudited_directives_ignored(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text("X = 1  # graftlint: metrics-exempt(not audited)\n")
    tree = core.Tree(str(tmp_path), ["mod.py"])
    assert gl_pragmas.run(tree) == []


# -- pass 7: tracing span lifecycle ------------------------------------------


def test_tracing_fixture_exact_findings():
    found = gl_tracing.run(_tree("viol_tracing.py"))
    assert _keys(found) == [
        "span-ok-no-reason",
        "unclosed-span:bare_call",
        "unclosed-span:leaked_assignment",
    ]


def test_tracing_with_statement_and_add_span_clean():
    found = gl_tracing.run(_tree("viol_tracing.py"))
    bad_lines = {f.line for f in found}
    src = _tree("viol_tracing.py").modules[0].source.splitlines()
    for i, line in enumerate(src, 1):
        if "_ok_" in line and "def " in line:
            # nothing inside the _ok_* functions may be flagged
            assert all(abs(b - i) > 2 for b in bad_lines), (i, bad_lines)


def test_tracing_production_tree_clean():
    rels = core.discover(REPO, gl_config.PACKAGES, gl_config.EXCLUDE_DIRS)
    tree = core.Tree(REPO, rels)
    assert gl_tracing.run(tree) == []


# -- the clean fixture passes every pass -------------------------------------


def test_clean_fixture_no_findings():
    src = _tree("clean.py")
    assert donation.run(src) == []
    assert blocking.run(src) == []
    assert metrics_contract.run(src, REPO, doc_path=FIXTURE_DOC) == []
    assert degraded.run(src, dirs=(FIXTURES,)) == []
    assert fenceseam.run(src, dirs=(FIXTURES,)) == []
    assert guardedby.run(src) == []
    assert gl_tracing.run(src) == []
    assert gl_threads.run(src) == []
    # every pragma in clean.py is consulted by the passes above
    assert gl_pragmas.run(src) == []


# -- runner CLI: exit codes + suppression baseline ---------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join("scripts", "graftlint"), *args],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_full_tree_clean_with_empty_baseline():
    """THE gate: the shipped tree is lint-clean and the checked-in
    baseline is empty (ISSUE 7 acceptance)."""
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
    with open(os.path.join(GRAFTLINT_DIR, "baseline.txt")) as fh:
        entries = [
            ln
            for ln in fh.read().splitlines()
            if ln.strip() and not ln.startswith("#")
        ]
    assert entries == [], f"baseline must stay empty, has: {entries}"


def test_violation_file_exits_nonzero_with_file_line_findings():
    proc = _run_cli(f"{FIXTURES}/viol_donation.py")
    assert proc.returncode == 1
    # file:line: [pass] message
    assert f"{FIXTURES}/viol_donation.py:" in proc.stdout
    assert "[donation]" in proc.stdout


def test_suppression_baseline_roundtrip(tmp_path):
    baseline = str(tmp_path / "baseline.txt")
    wrote = _run_cli(
        f"{FIXTURES}/viol_donation.py", "--write-baseline",
        "--baseline", baseline,
    )
    assert wrote.returncode == 0
    # every finding suppressed -> clean exit
    proc = _run_cli(
        f"{FIXTURES}/viol_donation.py", "--baseline", baseline
    )
    assert proc.returncode == 0, proc.stdout
    assert "suppressed=5" in proc.stdout
    # a stale entry (matches nothing) must FAIL the run
    with open(baseline, "a") as fh:
        fh.write("gone/file.py::donation::unlocked-donation:ghost:fn\n")
    proc = _run_cli(
        f"{FIXTURES}/viol_donation.py", "--baseline", baseline
    )
    assert proc.returncode == 1
    assert "STALE" in proc.stdout


# -- lock-order watchdog (runtime companion) ---------------------------------


from kubernetes_tpu.testing import lockgraph  # noqa: E402


@pytest.fixture()
def fresh_lockgraph():
    lockgraph.disable()
    lockgraph.reset()
    yield lockgraph
    lockgraph.disable()
    lockgraph.reset()


def test_lockgraph_records_edges_and_stays_acyclic(fresh_lockgraph):
    lg = fresh_lockgraph
    lg.enable()
    a, b = lg.named_lock("A"), lg.named_lock("B")
    with a:
        with b:
            pass
    assert lg.edges() == {"A": {"B"}}
    lg.assert_acyclic()  # consistent order: no violation


def test_lockgraph_detects_inversion(fresh_lockgraph):
    lg = fresh_lockgraph
    lg.enable()
    a, b = lg.named_lock("A"), lg.named_lock("B")
    with a:
        with b:
            pass
    with b:
        with a:  # ABBA inversion — never deadlocks single-threaded,
            pass  # still MUST be flagged
    assert lg.violations()
    with pytest.raises(AssertionError, match="ORDER INVERSION"):
        lg.assert_acyclic()


def test_lockgraph_reentrant_acquire_is_not_a_cycle(fresh_lockgraph):
    lg = fresh_lockgraph
    lg.enable()
    a = lg.named_lock("A")
    with a:
        with a:  # RLock re-entrancy: no self-edge, no violation
            pass
    assert lg.edges() == {}
    lg.assert_acyclic()


def test_lockgraph_disabled_records_nothing(fresh_lockgraph):
    lg = fresh_lockgraph
    a, b = lg.named_lock("A"), lg.named_lock("B")
    with a:
        with b:
            pass
    assert lg.edges() == {}
    assert lg.acquire_count() == 0


def test_lockgraph_condition_wait_stays_consistent(fresh_lockgraph):
    lg = fresh_lockgraph
    lg.enable()
    cond = threading.Condition(lg.named_lock("C"))
    woke = []

    def waiter():
        with cond:
            cond.wait(timeout=2.0)
            woke.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.05)
    with cond:
        cond.notify_all()
    t.join(timeout=3.0)
    assert woke == [True]
    lg.assert_acyclic()


def test_lockgraph_stale_held_state_does_not_leak_across_enable(
    fresh_lockgraph,
):
    """A thread that acquired while enabled but released after disable()
    keeps the name on its thread-local stack; the next enable() (same
    process, e.g. the second chaos module in one pytest run) must not
    inherit it as a phantom held lock fabricating false edges."""
    lg = fresh_lockgraph
    lg.enable()
    a, b = lg.named_lock("A"), lg.named_lock("B")
    a.acquire()
    lg.disable()  # release below records nothing: "A" goes stale
    a.release()
    lg.enable()
    with b:  # with stale state this thread would record A -> B
        pass
    assert lg.edges() == {}
    lg.assert_acyclic()


# -- lockset sanitizer (Eraser mode) ------------------------------------------


class _TrackedBox:
    pass


lockgraph.track_attrs(_TrackedBox, "val")


def test_eraser_two_thread_unguarded_write_detected(fresh_lockgraph):
    """The deliberately injected unguarded write (ISSUE 12 acceptance):
    two threads, no lock, MUST produce an empty-lockset race report with
    both stack tips."""
    lg = fresh_lockgraph
    lg.enable(eraser=True)
    box = _TrackedBox()
    box.val = 1
    t = threading.Thread(target=lambda: setattr(box, "val", 2))
    t.start()
    t.join(timeout=5.0)
    got = lg.races()
    assert got and got[0]["attr"] == "_TrackedBox.val"
    assert got[0]["prev_site"] and got[0]["site"]
    with pytest.raises(AssertionError, match="EMPTY-LOCKSET RACE"):
        lg.assert_clean()


def test_eraser_consistently_guarded_attr_silent(fresh_lockgraph):
    lg = fresh_lockgraph
    lg.enable(eraser=True)
    lock = lockgraph.named_lock("box.lock")
    box = _TrackedBox()
    with lock:
        box.val = 1

    def writer():
        with lock:
            box.val = box.val + 1

    t = threading.Thread(target=writer)
    t.start()
    t.join(timeout=5.0)
    assert lg.races() == []
    lg.assert_clean()
    assert lg.tracked_access_count() > 0


def test_eraser_disabled_mode_is_a_noop(fresh_lockgraph):
    lg = fresh_lockgraph  # never enabled
    box = _TrackedBox()
    box.val = 1
    t = threading.Thread(target=lambda: setattr(box, "val", 2))
    t.start()
    t.join(timeout=5.0)
    assert lg.races() == []
    assert lg.tracked_access_count() == 0
    assert box.val == 2  # the descriptor still stores/loads faithfully


def test_eraser_watchdog_only_mode_records_no_attrs(fresh_lockgraph):
    """enable() without eraser=True keeps the pre-ISSUE-12 behavior: the
    order graph records, attribute accesses don't."""
    lg = fresh_lockgraph
    lg.enable()
    box = _TrackedBox()
    box.val = 1
    t = threading.Thread(target=lambda: setattr(box, "val", 2))
    t.start()
    t.join(timeout=5.0)
    assert lg.races() == []
    assert lg.tracked_access_count() == 0


def test_eraser_epoch_reset_across_suites(fresh_lockgraph):
    """A race recorded in one suite's epoch must not leak into the next
    enable() in the same process (the chaos suites share a pytest run),
    and per-attribute exclusive/shared state starts over."""
    lg = fresh_lockgraph
    lg.enable(eraser=True)
    box = _TrackedBox()
    box.val = 1
    t = threading.Thread(target=lambda: setattr(box, "val", 2))
    t.start()
    t.join(timeout=5.0)
    assert lg.races()
    lg.enable(eraser=True)  # next suite
    assert lg.races() == []
    box.val = 3  # same thread only: exclusive, still silent
    assert lg.races() == []
    lg.assert_clean()


def test_eraser_per_instance_state(fresh_lockgraph):
    """Constructor writes are an INSTANCE's exclusive phase: building a
    second object on a second thread must not poison the first object's
    lockset (the first armed chaos run caught exactly this aggregation
    bug)."""
    lg = fresh_lockgraph
    lg.enable(eraser=True)
    lock = lockgraph.named_lock("box.lock")
    a = _TrackedBox()
    with lock:
        a.val = 1

    def other():
        b = _TrackedBox()
        b.val = 99  # different instance, no lock — NOT a race on `a`
        with lock:
            a.val = a.val + 1

    t = threading.Thread(target=other)
    t.start()
    t.join(timeout=5.0)
    assert lg.races() == []


# -- CLI: --changed and --list-guards -----------------------------------------


def test_cli_list_guards_table_shape():
    proc = _run_cli("--list-guards")
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.strip().splitlines()
    assert lines[0] == "| attribute | guarded by | guarded sites |"
    assert any("`SchedulerCache._nodes`" in ln and "`scheduler.cache`" in ln
               for ln in lines)


def test_cli_changed_mode_clean_tree():
    """--changed on a clean checkout lints nothing (or only already-clean
    modified files) and exits 0 — the `make lint-fast` pre-commit loop."""
    proc = _run_cli("--changed")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_lockgraph_cross_thread_inversion(fresh_lockgraph):
    """The real deadlock shape: two threads, opposite order, timed so
    both complete (no actual deadlock) — the graph still convicts."""
    lg = fresh_lockgraph
    lg.enable()
    a, b = lg.named_lock("A"), lg.named_lock("B")
    gate = threading.Barrier(2, timeout=5.0)

    def t1():
        with a:
            with b:
                pass
        gate.wait()

    def t2():
        gate.wait()  # strictly after t1 released both: no deadlock
        with b:
            with a:
                pass

    th1, th2 = threading.Thread(target=t1), threading.Thread(target=t2)
    th1.start()
    th2.start()
    th1.join(timeout=5.0)
    th2.join(timeout=5.0)
    assert lg.violations(), "cross-thread ABBA must be recorded"
