"""Scheduler-HA chaos: leader-elected warm standby with snapshot handoff.

The scheduler was the last single process in the stack: PR 1 made the
store survive leader death, PR 3 taught the control plane to ride out a
degraded store, PR 4 taught the data plane to heal itself — but a dead
scheduler still cost a full HBM-snapshot rebuild plus a compile storm.
These scenarios prove the warm-standby design closes that gap:

  * kill the leader MID-WAVE (binds parked assumed-but-unbound) → the
    standby adopts from store read-back and binds every in-flight pod,
    ZERO double-binds on the ChaosStore ledger, time-to-first-bind after
    the kill under one autoscaler period;
  * a paused ex-leader resuming after the standby promoted gets its late
    binds REJECTED by the leadership fence, never applied twice;
  * graceful stop releases the lease (rolling upgrade: handoff well under
    lease_duration);
  * N scheduler replicas on the shared watch cache cost ONE store watch
    per kind;
  * leader-election edge cases: expired-lease takeover, single grant per
    transition under concurrency, renew-deadline loss is fatal, degraded
    renews are counted skips that keep the holder leading, clock-jittered
    renew races never let a challenger steal a live lease.
"""

import threading
import time

import numpy as np
import pytest

from test_chaos_pipeline import (
    ChaosStore,
    _bound_count,
    assert_bind_invariants,
    make_pod,
    wait_until,
)

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.objects import Binding
from kubernetes_tpu.apiserver.cacher import Cacher
from kubernetes_tpu.client.apiserver import APIServer, LeaderFenced
from kubernetes_tpu.client.leaderelection import (
    LeaderElectionConfig,
    LeaderElector,
)
from kubernetes_tpu.kubelet.kubelet import NodeAgentPool
from kubernetes_tpu.runtime.consensus import DegradedWrites
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler
from kubernetes_tpu.testing import lockgraph
from kubernetes_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True, scope="module")
def lock_order_watchdog():
    """Lock-order watchdog + lockset sanitizer over the HA suite (ISSUE
    12): leader, standby, and zombie replicas share one store and one
    watch cache from different threads — exactly the concurrency the
    guarded-by contract exists for. Any lock-order cycle or any tracked
    attribute whose lockset goes empty across threads fails the suite,
    even when the interleaving happened to be benign."""
    lockgraph.enable(eraser=True)
    yield
    try:
        lockgraph.assert_clean()
        assert lockgraph.acquire_count() > 0, (
            "watchdog observed no named-lock acquisitions: the named "
            "locks are not instrumented"
        )
        assert lockgraph.tracked_access_count() > 0, (
            "lockset sanitizer observed no tracked-attribute accesses: "
            "the production classes are not instrumented"
        )
    finally:
        lockgraph.disable()

# The acceptance budget for "the standby starts binding fast": ONE
# autoscaler period. The PR-5 autoscaler's what-if simulation alone costs
# 2.2-6.6 s on the CPU backend (PERFORMANCE.md round-9), so a CPU
# deployment runs multi-second scan periods; 5 s is the tight end of
# that range and comfortably covers lease expiry + takeover + adoption +
# the first warm wave — but NOT a snapshot rebuild + compile storm.
AUTOSCALER_PERIOD_S = 5.0

# fast-failover lease: expiry well inside the bind budget. Invariants
# still hold: lease(1.5) > renew(1.0) > retry(0.2)*1.2
def _lease_cfg(identity: str) -> LeaderElectionConfig:
    return LeaderElectionConfig(
        identity=identity,
        lease_duration=1.5,
        renew_deadline=1.0,
        retry_period=0.2,
    )


class _Replica:
    """One scheduler replica: a Scheduler standing by + its elector,
    wired the way cmd/scheduler.py wires them (standby first, the
    election winner promotes with the fence)."""

    def __init__(self, store, cacher, identity, lease_cfg=None):
        self.identity = identity
        self.sched = Scheduler(cacher, KubeSchedulerConfiguration())
        self.sched.start_standby(identity=identity)
        self.promoted = threading.Event()
        self.deposed = threading.Event()

        def on_started():
            self.sched.promote(fence=self.elector.fence())
            self.promoted.set()

        self.elector = LeaderElector(
            store,
            lease_cfg or _lease_cfg(identity),
            on_started_leading=on_started,
            on_stopped_leading=self.deposed.set,
        )
        self._thread = threading.Thread(
            target=self.elector.run, daemon=True, name=f"elector-{identity}"
        )
        self._thread.start()

    def stop(self):
        self.elector.stop()
        self.sched.stop()

    def crash(self):
        """Leader death: no lease release, scheduling threads stopped hard
        with whatever was mid-flight left dangling in the store."""
        self.elector.crash()
        self.sched.stop()


def _cluster(n_nodes=6):
    store = ChaosStore()
    cacher = Cacher(store)
    pool = NodeAgentPool(store, housekeeping_interval=0.1)
    for i in range(n_nodes):
        pool.add_node(f"ha-{i}")
    pool.start()
    return store, cacher, pool


# -- warm-up absorber (lint-exempt; see scripts/check_slow_markers.py) --------


def test_warmup_compile_ha_absorber():
    """Absorb this process's standby/leader kernel compiles at the suite's
    shapes (6 nodes, ≤256-pod small-bucket waves + the serial variant):
    the standby pre-warm path compiles the same programs the promoted
    leader launches, so every later test in this file runs at steady
    state. Asserts liveness only."""
    store, cacher, pool = _cluster()
    w0 = metrics.counter("scheduler_ha_standby_warmups_total")
    sched = Scheduler(cacher, KubeSchedulerConfiguration())
    sched.start_standby(identity="warmup")
    try:
        assert metrics.counter("scheduler_ha_standby_warmups_total") > w0
        for i in range(30):
            store.create("pods", make_pod(f"wu-{i}"))
        sched.promote()
        assert wait_until(lambda: _bound_count(store) == 30, 60)
    finally:
        sched.stop()
        pool.stop()
        cacher.stop()


# -- scenario 1: kill the leader mid-wave; the standby adopts -----------------


@pytest.mark.slow
def test_kill_leader_mid_wave_standby_adopts_and_binds():
    """Acceptance scenario. The leader dies with a wave ASSUMED but
    unbound (its binds parked in the ride-through buffer during a store
    blip — in-memory state that dies with it). The warm standby takes the
    lease, adopts the in-flight pods from store read-back, and binds
    every one of them: zero double-binds on the ledger, first bind after
    the kill in well under one autoscaler period (no snapshot rebuild,
    no compile storm)."""
    store, cacher, pool = _cluster()
    a = _Replica(store, cacher, "ha-leader-a")
    assert wait_until(a.promoted.is_set, 15), "first replica never led"
    b = _Replica(store, cacher, "ha-standby-b")
    try:
        n1 = 30
        for i in range(n1):
            store.create("pods", make_pod(f"pre-{i}"))
        assert wait_until(lambda: _bound_count(store) == n1, 30)

        # mid-wave: the next wave's bulk bind is refused (degraded store)
        # so the leader parks the whole wave assumed-but-unbound, then DIES
        # before the buffer can ever drain
        store.fail_next_bind = "degraded"
        n2 = 30
        for i in range(n2):
            store.create("pods", make_pod(f"wave-{i}"))
        assert wait_until(lambda: a.sched._ridethrough.depth > 0, 15), (
            "leader never parked the mid-flight wave"
        )
        # NOTE: the trickling burst may split into several bind calls and
        # some wave pods can ack BEFORE the injected failure lands — the
        # invariant is the parked remainder, not an exact bound count
        bound_at_kill = _bound_count(store)
        assert bound_at_kill < n1 + n2
        adopt0 = metrics.counter(
            "scheduler_ha_adoptions_total", {"outcome": "pending"}
        )
        t_kill = time.monotonic()
        a.crash()
        store.recover()

        # the standby takes over and starts binding the adopted wave
        assert wait_until(b.promoted.is_set, 15), "standby never promoted"
        assert wait_until(lambda: _bound_count(store) > bound_at_kill, 15), (
            "no bind ever landed after the kill"
        )
        t_first = time.monotonic() - t_kill
        assert t_first < AUTOSCALER_PERIOD_S, (
            f"time-to-first-bind after the kill {t_first:.2f}s >= one "
            f"autoscaler period ({AUTOSCALER_PERIOD_S}s)"
        )
        assert wait_until(lambda: _bound_count(store) == n1 + n2, 30), (
            f"only {_bound_count(store)}/{n1 + n2} bound after failover"
        )
        print(
            f"\n[chaos-ha] leader killed mid-wave: standby adopted and "
            f"first-bound in {t_first:.2f}s (< {AUTOSCALER_PERIOD_S}s), "
            f"all {n1 + n2} pods bound",
            flush=True,
        )
        # the adoption pass actually saw the in-flight wave
        assert (
            metrics.counter(
                "scheduler_ha_adoptions_total", {"outcome": "pending"}
            )
            > adopt0
        ), "promotion ran no adoption pass over the in-flight wave"
        # THE ledger gate: every acked bind intact, no bind applied twice
        assert_bind_invariants(store)
        assert all(c == 1 for c in store.applied_binds.values())
    finally:
        b.stop()
        a.stop()
        pool.stop()
        cacher.stop()


# -- scenario 2: zombie ex-leader's late binds are fenced ---------------------


@pytest.mark.slow
def test_zombie_ex_leader_late_binds_are_fenced():
    """The leader PAUSES (stops renewing — GC pause / partition / SIGSTOP)
    but its scheduling threads keep running. The standby takes the
    expired lease. When the zombie's binds arrive they carry the stale
    fencing token and the store rejects them — racing the new leader over
    a burst of pods never applies a bind twice."""
    store, cacher, pool = _cluster()
    a = _Replica(store, cacher, "zombie-a")
    assert wait_until(a.promoted.is_set, 15)
    b = _Replica(store, cacher, "fresh-b")
    try:
        for i in range(10):
            store.create("pods", make_pod(f"pre-z-{i}"))
        assert wait_until(lambda: _bound_count(store) == 10, 30)

        # pause: the elector stops renewing WITHOUT releasing, but the
        # zombie's scheduler keeps running (no on_stopped teardown)
        a.elector.crash()
        assert wait_until(b.promoted.is_set, 15), "standby never took over"

        # deterministic fence check on the zombie's own bind funnel: a pod
        # no profile owns (so neither scheduler races us for it)
        zp = v1.Pod(
            metadata=v1.ObjectMeta(name="zombie-target"),
            spec=v1.PodSpec(
                scheduler_name="nobody",
                containers=[v1.Container(requests={"cpu": "100m"})],
            ),
        )
        zp = store.create("pods", zp)
        with pytest.raises(LeaderFenced):
            a.sched._bind_pods_fenced(
                [
                    Binding(
                        pod_name="zombie-target",
                        pod_namespace="default",
                        pod_uid=zp.metadata.uid,
                        target_node="ha-0",
                    )
                ]
            )
        assert not store.get("pods", "default", "zombie-target").spec.node_name

        # the race: both the zombie and the new leader see this burst.
        # Fencing (plus the store's bound/uid checks) makes a double-apply
        # structurally impossible; the new leader binds everything.
        for i in range(20):
            store.create("pods", make_pod(f"race-{i}"))
        assert wait_until(
            lambda: store.count(
                "pods",
                lambda p: p.metadata.name.startswith("race-")
                and bool(p.spec.node_name),
            )
            == 20,
            30,
        ), "racing burst never fully bound after the takeover"
        assert_bind_invariants(store)
        assert all(c == 1 for c in store.applied_binds.values()), (
            "a zombie bind applied twice"
        )
    finally:
        b.stop()
        a.stop()
        pool.stop()
        cacher.stop()


# -- scenario 3: graceful stop releases the lease (rolling upgrade) ------------


@pytest.mark.slow
def test_graceful_stop_releases_lease_fast_handoff():
    """stop() clears holder_identity and bumps lease_transitions
    (ReleaseOnCancel), so the standby promotes in a few retry periods —
    NOT after waiting out lease_duration. The zero-downtime rolling
    upgrade path."""
    store, cacher, pool = _cluster()
    # a deliberately LONG lease: if the handoff were expiry-driven it
    # could not beat the assertion below
    long_lease = LeaderElectionConfig(
        identity="old", lease_duration=8.0, renew_deadline=5.0,
        retry_period=0.3,
    )
    a = _Replica(store, cacher, "old", lease_cfg=long_lease)
    assert wait_until(a.promoted.is_set, 15)
    new_lease = LeaderElectionConfig(
        identity="new", lease_duration=8.0, renew_deadline=5.0,
        retry_period=0.3,
    )
    b = _Replica(store, cacher, "new", lease_cfg=new_lease)
    try:
        rel0 = metrics.counter("leader_election_releases_total")
        t0 = time.monotonic()
        a.stop()  # graceful: releases the lease
        assert wait_until(b.promoted.is_set, 15), "standby never promoted"
        elapsed = time.monotonic() - t0
        assert metrics.counter("leader_election_releases_total") > rel0
        assert elapsed < 3.0, (
            f"handoff took {elapsed:.2f}s — the release was not honored "
            f"(lease_duration is 8s)"
        )
        lease = store.get("leases", "kube-system", "kube-scheduler")
        assert lease.holder_identity == "new"
        # new leader schedules normally
        for i in range(10):
            store.create("pods", make_pod(f"rolled-{i}"))
        assert wait_until(lambda: _bound_count(store) == 10, 30)
        assert_bind_invariants(store)
    finally:
        b.stop()
        a.stop()
        pool.stop()
        cacher.stop()


# -- scenario 4: standby death leaves the leader untouched --------------------


@pytest.mark.slow
def test_standby_killed_leader_unaffected():
    store, cacher, pool = _cluster()
    a = _Replica(store, cacher, "solo-leader")
    assert wait_until(a.promoted.is_set, 15)
    b = _Replica(store, cacher, "doomed-standby")
    try:
        b.crash()
        for i in range(15):
            store.create("pods", make_pod(f"after-sb-{i}"))
        assert wait_until(lambda: _bound_count(store) == 15, 30)
        assert a.elector.is_leader and not b.promoted.is_set()
        assert_bind_invariants(store)
    finally:
        a.stop()
        b.stop()
        cacher.stop()
        pool.stop()
        cacher.stop()


# -- scenario 5: N replicas, ONE store watch per kind -------------------------


def test_ha_replicas_share_one_store_watch_per_kind():
    """The standby's informer stream rides the shared watch cache
    (ROADMAP item-2 follow-up): leader + standby together add exactly ONE
    store watch per kind — the Cacher's — however many replicas stand by."""
    store = ChaosStore()
    cacher = Cacher(store)
    for i in range(3):
        store.create(
            "nodes",
            v1.Node(
                metadata=v1.ObjectMeta(name=f"w-{i}"),
                status=v1.NodeStatus(
                    capacity={"cpu": "8", "memory": "16Gi", "pods": "110"},
                    allocatable={"cpu": "8", "memory": "16Gi", "pods": "110"},
                ),
            ),
        )
    base = {k: store.watcher_count(k) for k in ("pods", "nodes", "services")}
    a = Scheduler(cacher, KubeSchedulerConfiguration())
    b = Scheduler(cacher, KubeSchedulerConfiguration())
    try:
        a.start_standby(identity="watch-a")
        b.start_standby(identity="watch-b")
        for kind in ("pods", "nodes", "services"):
            added = store.watcher_count(kind) - base[kind]
            assert added == 1, (
                f"{kind}: {added} store watches for 2 replicas — informers "
                f"are not riding the shared cache"
            )
            # both replicas really are tailing that one watch
            assert cacher.cache_for(kind).fanout_clients() >= 2
    finally:
        a.stop()
        b.stop()
        cacher.stop()


# -- cmd wiring: run() with election = standby → promote ----------------------


def test_cmd_run_with_election_standby_promotes_and_binds():
    """cmd/scheduler.run with leader election configured starts the
    process as a warm standby behind a shared Cacher and promotes on the
    (instant) first-replica win; the SIGUSR2 dump carries the HA
    section."""
    from kubernetes_tpu.cmd import scheduler as cmd_scheduler
    from kubernetes_tpu.scheduler.cache.debugger import CacheDebugger

    store = ChaosStore()
    pool = NodeAgentPool(store, housekeeping_interval=0.1)
    for i in range(4):
        pool.add_node(f"cmd-{i}")
    pool.start()
    cfg = KubeSchedulerConfiguration()
    cfg.leader_election = _lease_cfg("cmd-replica-0")
    sched = cmd_scheduler.run(
        server=store, config=cfg, healthz_port=0, block=False
    )
    try:
        assert type(sched.server).__name__ == "Cacher"
        assert wait_until(
            lambda: sched._elector.is_leader and sched._sched_thread is not None,
            15,
        ), "run() never promoted the first replica"
        assert sched._bind_fence is not None, "promotion armed no fence"
        for i in range(10):
            store.create("pods", make_pod(f"cmd-p-{i}"))
        assert wait_until(lambda: _bound_count(store) == 10, 30)
        dump = CacheDebugger(sched).dump()
        assert "scheduler-HA / leader-election state" in dump
        assert "scheduler_ha_role" in dump
        assert_bind_invariants(store)
    finally:
        sched._elector.stop()
        sched.stop()  # also tears down the run()-owned Cacher
        pool.stop()


# -- leader-election edge cases (fake clocks, no scheduler) -------------------


def _edge_cfg(identity, **kw):
    kw.setdefault("lease_duration", 3.0)
    kw.setdefault("renew_deadline", 2.0)
    kw.setdefault("retry_period", 0.05)
    return LeaderElectionConfig(identity=identity, **kw)


def test_expired_lease_takeover_bumps_transitions_once():
    s = APIServer()
    now = [0.0]
    clock = lambda: now[0]
    e1 = LeaderElector(s, _edge_cfg("one"), lambda: None, clock=clock)
    e2 = LeaderElector(s, _edge_cfg("two"), lambda: None, clock=clock)
    assert e1._try_acquire_or_renew()
    assert s.get("leases", "kube-system", "kube-scheduler").lease_transitions == 0
    assert not e2._try_acquire_or_renew(), "takeover of a live lease"
    now[0] += 10.0  # past lease_duration: expired
    assert e2._try_acquire_or_renew()
    lease = s.get("leases", "kube-system", "kube-scheduler")
    assert lease.holder_identity == "two"
    assert lease.lease_transitions == 1, "takeover must bump exactly once"
    # the old holder's next renew fails (its fence is stale too)
    assert not e1._try_acquire_or_renew()


def test_concurrent_candidates_single_grant_per_transition():
    """Two (or N) candidates racing an expired lease: optimistic
    concurrency on the lease record guarantees exactly ONE grant — split
    leadership is structurally impossible."""
    s = APIServer()
    now = [0.0]
    clock = lambda: now[0]
    seed = LeaderElector(s, _edge_cfg("seed"), lambda: None, clock=clock)
    assert seed._try_acquire_or_renew()
    now[0] += 10.0  # expire it
    n = 8
    electors = [
        LeaderElector(s, _edge_cfg(f"cand-{i}"), lambda: None, clock=clock)
        for i in range(n)
    ]
    results = [None] * n
    barrier = threading.Barrier(n)

    def race(i):
        barrier.wait()
        results[i] = electors[i]._try_acquire_or_renew()

    threads = [threading.Thread(target=race, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5.0)
    assert sum(1 for r in results if r) == 1, f"grants: {results}"
    lease = s.get("leases", "kube-system", "kube-scheduler")
    assert lease.lease_transitions == 1, (
        "one transition may grant at most once"
    )
    assert lease.holder_identity.startswith("cand-")


def test_same_identity_reacquire_after_expiry_mints_fresh_fence():
    """A replacement process reusing a STATIC identity (pod name via
    --leader-elect-identity) that re-acquires the expired lease is a NEW
    grant: transitions must bump so the paused old incarnation's fence
    token goes stale — otherwise its late binds would pass the zombie
    fence unchallenged."""
    s = APIServer()
    now = [0.0]
    clock = lambda: now[0]
    old = LeaderElector(s, _edge_cfg("static-id"), lambda: None, clock=clock)
    assert old._try_acquire_or_renew()
    stale_fence = old.fence()
    # the old incarnation pauses; its lease expires; a replacement with
    # the SAME identity acquires
    now[0] += 10.0
    new = LeaderElector(s, _edge_cfg("static-id"), lambda: None, clock=clock)
    assert new._try_acquire_or_renew()
    lease = s.get("leases", "kube-system", "kube-scheduler")
    assert lease.lease_transitions == 1, (
        "same-identity re-acquire after expiry must mint a new grant"
    )
    assert new.fence().transitions == 1
    # the zombie's token no longer validates
    s.create(
        "nodes",
        v1.Node(
            metadata=v1.ObjectMeta(name="fz-1"),
            status=v1.NodeStatus(
                capacity={"cpu": "8", "memory": "16Gi", "pods": "110"},
                allocatable={"cpu": "8", "memory": "16Gi", "pods": "110"},
            ),
        ),
    )
    p = s.create("pods", make_pod("fz-pod"))
    binding = Binding(
        pod_name="fz-pod", pod_namespace="default",
        pod_uid=p.metadata.uid, target_node="fz-1",
    )
    with pytest.raises(LeaderFenced):
        s.bind_pods([binding], fence=stale_fence)
    assert s.bind_pods([binding], fence=new.fence()) == [None]


def test_renew_deadline_loss_is_fatal():
    """A store degraded for longer than renew_deadline deposes the leader
    (on_stopped fires) — exactly the reference's fatal-loss contract —
    while every refused renew is a counted skip, not an exception."""
    store = ChaosStore()
    stopped = threading.Event()
    cfg = LeaderElectionConfig(
        identity="fatal", lease_duration=1.2, renew_deadline=0.8,
        retry_period=0.15,
    )
    el = LeaderElector(
        store, cfg, on_started_leading=lambda: None,
        on_stopped_leading=stopped.set,
    )
    t = threading.Thread(target=el.run, daemon=True)
    t.start()
    assert wait_until(lambda: el.is_leader, 5)
    skips0 = metrics.counter("leader_election_degraded_renew_skips_total")
    store.degrade()
    assert stopped.wait(5.0), "renew-deadline loss never deposed the leader"
    assert not el.is_leader
    assert (
        metrics.counter("leader_election_degraded_renew_skips_total") > skips0
    ), "degraded renews were not counted as skips"
    store.recover()


def test_degraded_renew_within_deadline_keeps_leading():
    """A degraded-store window SHORTER than renew_deadline must not cost
    leadership: refused renews are counted skips and the next healthy
    renew re-arms the deadline (PR-3 ride-through discipline applied to
    the lease path)."""
    store = ChaosStore()
    stopped = threading.Event()
    cfg = LeaderElectionConfig(
        identity="rider", lease_duration=3.0, renew_deadline=2.0,
        retry_period=0.1,
    )
    el = LeaderElector(
        store, cfg, on_started_leading=lambda: None,
        on_stopped_leading=stopped.set,
    )
    t = threading.Thread(target=el.run, daemon=True)
    t.start()
    try:
        assert wait_until(lambda: el.is_leader, 5)
        skips0 = metrics.counter("leader_election_degraded_renew_skips_total")
        store.degrade()
        time.sleep(0.5)  # several refused renews, well inside the deadline
        store.recover()
        assert (
            metrics.counter("leader_election_degraded_renew_skips_total")
            > skips0
        )
        time.sleep(0.4)  # a healthy renew lands
        assert el.is_leader, "a sub-deadline outage deposed the leader"
        assert not stopped.is_set()
    finally:
        el.stop()
        t.join(5.0)


def test_clock_jittered_renew_races_never_steal_a_live_lease():
    """The holder renews at jittered intervals (always inside
    lease_duration); a challenger probing after every renew must never
    acquire. Once renewals stop and the lease ages out, the challenger
    takes over with exactly one transition bump."""
    import random

    s = APIServer()
    now = [100.0]
    clock = lambda: now[0]
    rng = random.Random(42)
    holder = LeaderElector(s, _edge_cfg("holder"), lambda: None, clock=clock)
    chall = LeaderElector(s, _edge_cfg("chall"), lambda: None, clock=clock)
    assert holder._try_acquire_or_renew()
    for _ in range(40):
        # jittered renewal gap, always < lease_duration (3.0)
        now[0] += rng.uniform(0.2, 2.8)
        assert not chall._try_acquire_or_renew(), (
            f"challenger stole a live lease at t={now[0]:.2f}"
        )
        assert holder._try_acquire_or_renew(), "holder failed to renew"
    assert s.get("leases", "kube-system", "kube-scheduler").lease_transitions == 0
    # holder goes silent: the challenger wins after expiry, once
    now[0] += 3.5
    assert chall._try_acquire_or_renew()
    lease = s.get("leases", "kube-system", "kube-scheduler")
    assert lease.holder_identity == "chall"
    assert lease.lease_transitions == 1


# -- scenario: the plugin-bearing per-pod bind path is fenced too --------------


def test_plugin_per_pod_bind_path_is_fenced():
    """ISSUE-10 acceptance: the plugin-bearing per-pod path (DefaultBinder
    through the framework's bind surface, and the async binding cycle
    around it) funnels through the same fence-attaching seam as batch
    binds — a deposed replica's per-pod bind raises LeaderFenced, the
    placement is dropped (never applied, never requeued), and the fenced
    counter carries the transport label."""
    from kubernetes_tpu.scheduler.framework.interface import CycleState
    from kubernetes_tpu.scheduler.queue.scheduling_queue import QueuedPodInfo

    store, cacher, pool = _cluster()
    a = _Replica(store, cacher, "plugin-zombie-a")
    assert wait_until(a.promoted.is_set, 15)
    b = _Replica(store, cacher, "plugin-fresh-b")
    try:
        # depose a: pause its elector (no release), b takes the lease
        a.elector.crash()
        assert wait_until(b.promoted.is_set, 15), "standby never took over"

        zp = v1.Pod(
            metadata=v1.ObjectMeta(name="plugin-zombie-target"),
            spec=v1.PodSpec(
                # unsatisfiable selector: neither live scheduler can PLACE
                # it (stays pending), but the default profile still owns
                # it — the direct bind writes below target ha-0 explicitly
                node_selector={"no-such-label": "nowhere"},
                containers=[v1.Container(requests={"cpu": "100m"})],
            ),
        )
        zp = store.create("pods", zp)

        # (1) DefaultBinder through the framework context's bind surface:
        # the plugin's write funnels into _bind_pods_fenced and the store
        # rejects it with the zombie's stale token
        prof = a.sched.profiles.for_pod(zp)
        with pytest.raises(LeaderFenced):
            prof.framework.run_bind_plugins(CycleState(), zp, "ha-0")
        assert not store.get(
            "pods", "default", "plugin-zombie-target"
        ).spec.node_name, "a fenced plugin bind reached the store"

        # (2) the whole async binding cycle: LeaderFenced is handled (not
        # an unhandled thread exception), the placement dropped and
        # counted under the transport label
        before = metrics.dump().get(
            "scheduler_ha_fenced_binds_total{'path': 'local'}", 0.0
        )
        a.sched.cache.assume_pod(zp, "ha-0", device_synced=False)
        pi = QueuedPodInfo(pod=zp)
        a.sched._bind_async(pi, "ha-0", CycleState(), time.monotonic())
        assert not store.get(
            "pods", "default", "plugin-zombie-target"
        ).spec.node_name
        after = metrics.dump().get(
            "scheduler_ha_fenced_binds_total{'path': 'local'}", 0.0
        )
        assert after == before + 1, (before, after)

        # (3) the extender pre-check seam rejects a deposed replica
        with pytest.raises(LeaderFenced):
            a.sched._check_fence_live()

        assert_bind_invariants(store)
    finally:
        b.stop()
        a.stop()
        pool.stop()
        cacher.stop()


# -- scenario: failover adopts the persisted tuned score policy ---------------


@pytest.mark.slow
def test_failover_adopts_persisted_score_policy():
    """The policy gym persists its promoted vector as the singleton
    ScorePolicy object precisely so a promotion survives its promoter.
    Regression: a tuned vector is in the store; replica A wins the
    election and adopts it at promote(); A crashes; the standby that
    takes over MUST come up running the tuned vector — NOT revert to
    ``default``, which would silently undo the promotion on every
    failover. Adoption is read-only on the store object (the promotions
    ledger must not move)."""
    from kubernetes_tpu.ops.lattice import (
        DEFAULT_WEIGHTS,
        SC_COST,
        WEIGHT_PROFILES,
    )
    from kubernetes_tpu.tuner import ACTIVE_POLICY_NAME, persist_active_policy

    store, cacher, pool = _cluster(n_nodes=2)
    vec = DEFAULT_WEIGHTS.copy()
    vec[SC_COST] = 21.0
    assert persist_active_policy(store, "t-ha-tuned", vec, identity="gym")
    a = b = None
    try:
        a = _Replica(store, cacher, "adopt-a")
        assert a.promoted.wait(20), "replica A never won the election"
        assert a.sched._score_policy_name == "t-ha-tuned"
        assert np.allclose(np.asarray(a.sched._weights), vec)

        b = _Replica(store, cacher, "adopt-b")
        adopted0 = metrics.dump().get(
            "tuner_policy_adoptions_total{'outcome': 'adopted'}", 0.0
        )
        a.crash()
        assert b.promoted.wait(30), "standby never took over the lease"

        # the failover winner runs the tuned vector, not `default`
        assert b.sched._score_policy_name == "t-ha-tuned"
        assert np.allclose(np.asarray(b.sched._weights), vec)
        adopted1 = metrics.dump().get(
            "tuner_policy_adoptions_total{'outcome': 'adopted'}", 0.0
        )
        assert adopted1 >= adopted0 + 1, (adopted0, adopted1)

        # adoption reads, never writes: the persisted object is untouched
        obj = store.get("scorepolicies", "", ACTIVE_POLICY_NAME)
        assert obj.policy_name == "t-ha-tuned"
        assert int(obj.promotions) == 1
        assert [float(x) for x in obj.weights] == [float(x) for x in vec]
        assert_bind_invariants(store)
    finally:
        if b is not None:
            b.stop()
        if a is not None:
            a.stop()
        pool.stop()
        cacher.stop()
        WEIGHT_PROFILES.pop("t-ha-tuned", None)
