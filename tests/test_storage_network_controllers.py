"""EndpointSlice, NodeIPAM, attach-detach, PV binder controllers
(reference: pkg/controller/{endpointslice,nodeipam,volume/attachdetach,
volume/persistentvolume})."""

import time

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client import APIServer
from kubernetes_tpu.controller.attachdetach import AttachDetachController
from kubernetes_tpu.controller.endpointslice import (
    SERVICE_NAME_LABEL,
    EndpointSliceController,
)
from kubernetes_tpu.controller.nodeipam import NodeIpamController
from kubernetes_tpu.controller.pv_binder import PVBinderController


def wait_until(fn, timeout=25.0, period=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(period)
    return False


def _running_pod(name, labels, ip, node="n0"):
    p = v1.Pod(
        metadata=v1.ObjectMeta(name=name, labels=labels),
        spec=v1.PodSpec(
            containers=[v1.Container(requests={"cpu": "100m"})], node_name=node
        ),
    )
    p.status.phase = v1.POD_RUNNING
    p.status.pod_ip = ip
    return p


def test_endpointslice_slicing_and_cleanup():
    server = APIServer()
    server.create(
        "services",
        v1.Service(
            metadata=v1.ObjectMeta(name="web"),
            spec=v1.ServiceSpec(selector={"app": "web"}, ports=[("http", 80)]),
        ),
    )
    for i in range(5):
        server.create(
            "pods", _running_pod(f"w{i}", {"app": "web"}, f"10.0.0.{i+1}")
        )
    ctrl = EndpointSliceController(server, max_endpoints_per_slice=2)
    ctrl.start()
    try:
        def sliced():
            slices, _ = server.list("endpointslices")
            mine = [
                s
                for s in slices
                if s.metadata.labels.get(SERVICE_NAME_LABEL) == "web"
            ]
            total = sum(len(s.endpoints) for s in mine)
            return len(mine) == 3 and total == 5 and all(
                len(s.endpoints) <= 2 for s in mine
            )

        assert wait_until(sliced), "5 endpoints must split into 3 slices of <=2"
        # shrink the pod set -> surplus slices deleted
        for i in range(4):
            server.delete("pods", "default", f"w{i}")
        def shrunk():
            slices, _ = server.list("endpointslices")
            mine = [
                s
                for s in slices
                if s.metadata.labels.get(SERVICE_NAME_LABEL) == "web"
            ]
            return len(mine) == 1 and len(mine[0].endpoints) == 1

        assert wait_until(shrunk), "slices must shrink with the pod set"
    finally:
        ctrl.stop()


def test_nodeipam_allocates_unique_cidrs():
    server = APIServer()
    for i in range(5):
        server.create(
            "nodes", v1.Node(metadata=v1.ObjectMeta(name=f"n{i}"), spec=v1.NodeSpec())
        )
    ctrl = NodeIpamController(server, cluster_cidr="10.244.0.0/20", node_mask_size=24)
    ctrl.start()
    try:
        def all_allocated():
            nodes, _ = server.list("nodes")
            cidrs = [n.spec.pod_cidr for n in nodes]
            return all(cidrs) and len(set(cidrs)) == 5

        assert wait_until(all_allocated), "every node needs a distinct pod CIDR"
        nodes, _ = server.list("nodes")
        assert all(n.spec.pod_cidr.startswith("10.244.") for n in nodes)
    finally:
        ctrl.stop()


def _pv(name, size="10Gi", sc=""):
    return v1.PersistentVolume(
        metadata=v1.ObjectMeta(name=name, namespace=""),
        spec=v1.PersistentVolumeSpec(
            capacity={"storage": size},
            access_modes=["ReadWriteOnce"],
            storage_class_name=sc,
        ),
    )


def _pvc(name, size="5Gi", sc=None):
    return v1.PersistentVolumeClaim(
        metadata=v1.ObjectMeta(name=name),
        spec=v1.PersistentVolumeClaimSpec(
            access_modes=["ReadWriteOnce"],
            resources={"storage": size},
            storage_class_name=sc,
        ),
    )


def test_pv_binder_matches_smallest_fit():
    server = APIServer()
    server.create("persistentvolumes", _pv("big", "100Gi"))
    server.create("persistentvolumes", _pv("small", "10Gi"))
    server.create("persistentvolumeclaims", _pvc("claim", "5Gi"))
    ctrl = PVBinderController(server)
    ctrl.start()
    try:
        def bound():
            c = server.get("persistentvolumeclaims", "default", "claim")
            return c.spec.volume_name == "small" and c.status.phase == "Bound"

        assert wait_until(bound), "binder must pick the smallest satisfying PV"
        pv = server.get("persistentvolumes", "", "small")
        assert pv.spec.claim_ref == "default/claim"
        assert pv.status.phase == "Bound"
        # deleting the claim releases the volume
        server.delete("persistentvolumeclaims", "default", "claim")
        assert wait_until(
            lambda: server.get("persistentvolumes", "", "small").status.phase
            == "Released"
        )
    finally:
        ctrl.stop()


def test_pv_binder_dynamic_provisioning():
    server = APIServer()
    server.create(
        "storageclasses",
        v1.StorageClass(
            metadata=v1.ObjectMeta(name="fast", namespace=""),
            provisioner="csi.example.com",
        ),
    )
    server.create("persistentvolumeclaims", _pvc("dyn", "2Gi", sc="fast"))
    ctrl = PVBinderController(server)
    ctrl.start()
    try:
        def provisioned():
            c = server.get("persistentvolumeclaims", "default", "dyn")
            if not c.spec.volume_name:
                return False
            pv = server.get("persistentvolumes", "", c.spec.volume_name)
            return (
                pv.spec.csi is not None
                and pv.spec.csi.driver == "csi.example.com"
                and pv.spec.storage_class_name == "fast"
            )

        assert wait_until(provisioned), "provisioner class must create + bind a PV"
    finally:
        ctrl.stop()


def test_attach_detach_follows_pod_placement():
    server = APIServer()
    pv = _pv("disk-1", "10Gi")
    pv.spec.claim_ref = "default/data"
    pv.status.phase = "Bound"
    server.create("persistentvolumes", pv)
    pvc = _pvc("data", "5Gi")
    pvc.spec.volume_name = "disk-1"
    pvc.status.phase = "Bound"
    server.create("persistentvolumeclaims", pvc)

    pod = v1.Pod(
        metadata=v1.ObjectMeta(name="db"),
        spec=v1.PodSpec(
            containers=[v1.Container(requests={"cpu": "100m"})],
            node_name="n3",
            volumes=[v1.Volume(name="data", persistent_volume_claim="data")],
        ),
    )
    server.create("pods", pod)
    ctrl = AttachDetachController(server)
    ctrl.start()
    try:
        def attached():
            vas, _ = server.list("volumeattachments")
            return any(
                a.spec.pv_name == "disk-1"
                and a.spec.node_name == "n3"
                and a.status.attached
                for a in vas
            )

        assert wait_until(attached), "placed pod's PV must attach to its node"
        server.delete("pods", "default", "db")
        assert wait_until(
            lambda: not server.list("volumeattachments")[0]
        ), "attachment must detach when no pod uses it"
    finally:
        ctrl.stop()
