"""Workload controllers end-to-end: Deployment rolling update, Job
completion/backoff, DaemonSet per-node placement, StatefulSet ordered
rollout, Endpoints publication, PDB disruption accounting — driven with the
real scheduler + hollow nodes (the reference's integration-test topology:
real controllers, no real kubelets)."""

import time

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client import APIServer
from kubernetes_tpu.controller.daemonset import DaemonSetController
from kubernetes_tpu.controller.deployment import DeploymentController, template_hash
from kubernetes_tpu.controller.disruption import DisruptionController
from kubernetes_tpu.controller.endpoints import EndpointsController
from kubernetes_tpu.controller.job import JobController
from kubernetes_tpu.controller.replicaset import ReplicaSetController
from kubernetes_tpu.controller.statefulset import StatefulSetController
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler


def wait_until(fn, timeout=25.0, period=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(period)
    return False


def _template(labels, cpu="100m", image="app:v1"):
    return v1.PodTemplateSpec(
        metadata=v1.ObjectMeta(labels=dict(labels)),
        spec=v1.PodSpec(
            containers=[v1.Container(image=image, requests={"cpu": cpu})]
        ),
    )


class _Cluster:
    """Scheduler + hollow nodes + a chosen set of controllers."""

    def __init__(self, num_nodes=4, controllers=()):
        self.server = APIServer()
        self.hollow = HollowCluster(self.server, num_nodes=num_nodes)
        self.sched = Scheduler(self.server, KubeSchedulerConfiguration())
        self.controllers = list(controllers)

    def __enter__(self):
        self.hollow.start()
        self.sched.start()
        for c in self.controllers:
            c.start()
        return self

    def __exit__(self, *exc):
        for c in self.controllers:
            c.stop()
        self.sched.stop()
        self.hollow.stop()


def test_deployment_rollout_and_rolling_update():
    server = APIServer()
    cl = _Cluster(num_nodes=4)
    cl.controllers = [
        DeploymentController(cl.server),
        ReplicaSetController(cl.server),
    ]
    with cl:
        server = cl.server
        dep = v1.Deployment(
            metadata=v1.ObjectMeta(name="web"),
            spec=v1.DeploymentSpec(
                replicas=3,
                selector={"app": "web"},
                template=_template({"app": "web"}),
                strategy=v1.DeploymentStrategy(max_surge=1, max_unavailable=1),
            ),
        )
        server.create("deployments", dep)
        assert wait_until(
            lambda: sum(
                1
                for p in server.list("pods")[0]
                if p.status.phase == "Running"
            )
            == 3
        ), [(p.metadata.name, p.status.phase) for p in server.list("pods")[0]]
        rss, _ = server.list("replicasets")
        assert len(rss) == 1
        h1 = template_hash(dep.spec.template)
        assert rss[0].metadata.labels["pod-template-hash"] == h1

        # rolling update: change the image
        def bump(cur):
            cur.spec.template.spec.containers[0].image = "app:v2"
            return cur

        server.guaranteed_update("deployments", "default", "web", bump)

        def updated():
            pods, _ = server.list("pods")
            v2 = [
                p
                for p in pods
                if p.status.phase == "Running"
                and p.spec.containers[0].image == "app:v2"
            ]
            v1_pods = [
                p for p in pods if p.spec.containers[0].image == "app:v1"
            ]
            return len(v2) == 3 and not v1_pods

        assert wait_until(updated, timeout=30), [
            (p.metadata.name, p.spec.containers[0].image, p.status.phase)
            for p in server.list("pods")[0]
        ]
        dep2 = server.get("deployments", "default", "web")
        assert wait_until(
            lambda: server.get("deployments", "default", "web").status.ready_replicas
            == 3
        )
        assert dep2.spec.replicas == 3

        # scale down: the (sole) new RS must shrink too
        def shrink(cur):
            cur.spec.replicas = 1
            return cur

        server.guaranteed_update("deployments", "default", "web", shrink)
        assert wait_until(
            lambda: sum(
                1 for p in server.list("pods")[0] if p.status.phase == "Running"
            )
            == 1,
            timeout=30,
        ), [(p.metadata.name, p.status.phase) for p in server.list("pods")[0]]


def test_job_runs_to_completion():
    server = APIServer()
    ctrl = JobController(server)
    ctrl.start()
    try:
        job = v1.Job(
            metadata=v1.ObjectMeta(name="crunch"),
            spec=v1.JobSpec(
                parallelism=2,
                completions=3,
                template=_template({"job": "crunch"}),
            ),
        )
        server.create("jobs", job)
        assert wait_until(lambda: len(server.list("pods")[0]) == 2)

        # succeed pods as they appear until the job completes
        def succeed_all():
            for p in server.list("pods")[0]:
                if p.status.phase not in ("Succeeded", "Failed"):
                    def fin(cur):
                        cur.status.phase = "Succeeded"
                        return cur

                    server.guaranteed_update(
                        "pods", p.metadata.namespace, p.metadata.name, fin
                    )
            j = server.get("jobs", "default", "crunch")
            return any(
                c.type == "Complete" and c.status == "True"
                for c in j.status.conditions
            )

        assert wait_until(succeed_all, timeout=20)
        j = server.get("jobs", "default", "crunch")
        assert j.status.succeeded == 3
        assert j.status.completion_time is not None
    finally:
        ctrl.stop()


def test_job_backoff_limit_fails_job():
    server = APIServer()
    ctrl = JobController(server)
    ctrl.start()
    try:
        job = v1.Job(
            metadata=v1.ObjectMeta(name="flaky"),
            spec=v1.JobSpec(
                parallelism=1,
                completions=1,
                backoff_limit=1,
                template=_template({"job": "flaky"}),
            ),
        )
        server.create("jobs", job)

        def fail_active():
            for p in server.list("pods")[0]:
                if p.status.phase not in ("Succeeded", "Failed"):
                    def fin(cur):
                        cur.status.phase = "Failed"
                        return cur

                    server.guaranteed_update(
                        "pods", p.metadata.namespace, p.metadata.name, fin
                    )
            j = server.get("jobs", "default", "flaky")
            return any(
                c.type == "Failed" and c.status == "True"
                for c in j.status.conditions
            )

        assert wait_until(fail_active, timeout=20)
    finally:
        ctrl.stop()


def test_daemonset_places_one_pod_per_eligible_node():
    cl = _Cluster(num_nodes=3)
    cl.controllers = [DaemonSetController(cl.server)]
    with cl:
        server = cl.server
        # taint one node; the DS template has no toleration for it
        def taint(cur):
            cur.spec.taints = [v1.Taint("dedicated", "infra", "NoSchedule")]
            return cur

        server.guaranteed_update("nodes", "", "hollow-node-2", taint)
        ds = v1.DaemonSet(
            metadata=v1.ObjectMeta(name="agent"),
            spec=v1.DaemonSetSpec(
                selector={"app": "agent"},
                template=_template({"app": "agent"}, cpu="10m"),
            ),
        )
        server.create("daemonsets", ds)

        def placed():
            pods, _ = server.list("pods")
            nodes = {p.spec.node_name for p in pods if p.spec.node_name}
            return len(pods) == 2 and nodes == {
                "hollow-node-0",
                "hollow-node-1",
            }

        assert wait_until(placed), [
            (p.metadata.name, p.spec.node_name)
            for p in server.list("pods")[0]
        ]
        # a new node grows the daemon set
        cl.hollow.add_node("hollow-node-3")
        assert wait_until(
            lambda: any(
                p.spec.node_name == "hollow-node-3"
                for p in server.list("pods")[0]
            )
        )
        st = server.get("daemonsets", "default", "agent")
        assert wait_until(
            lambda: server.get(
                "daemonsets", "default", "agent"
            ).status.desired_number_scheduled
            == 3
        )


def test_statefulset_ordered_rollout_and_scale_down():
    cl = _Cluster(num_nodes=3)
    cl.controllers = [StatefulSetController(cl.server)]
    with cl:
        server = cl.server
        st = v1.StatefulSet(
            metadata=v1.ObjectMeta(name="db"),
            spec=v1.StatefulSetSpec(
                replicas=3,
                selector={"app": "db"},
                template=_template({"app": "db"}),
                service_name="db",
            ),
        )
        server.create("statefulsets", st)
        assert wait_until(
            lambda: sorted(
                p.metadata.name
                for p in server.list("pods")[0]
                if p.status.phase == "Running"
            )
            == ["db-0", "db-1", "db-2"],
            timeout=30,
        ), [
            (p.metadata.name, p.status.phase)
            for p in server.list("pods")[0]
        ]

        def shrink(cur):
            cur.spec.replicas = 1
            return cur

        server.guaranteed_update("statefulsets", "default", "db", shrink)
        assert wait_until(
            lambda: sorted(
                p.metadata.name for p in server.list("pods")[0]
            )
            == ["db-0"],
            timeout=30,
        )


def test_endpoints_publishes_ready_pod_addresses():
    cl = _Cluster(num_nodes=2)
    cl.controllers = [
        EndpointsController(cl.server),
        ReplicaSetController(cl.server),
    ]
    with cl:
        server = cl.server
        server.create(
            "services",
            v1.Service(
                metadata=v1.ObjectMeta(name="web"),
                spec=v1.ServiceSpec(
                    selector={"app": "web"}, ports=[("http", 80)]
                ),
            ),
        )
        rs = v1.ReplicaSet(
            metadata=v1.ObjectMeta(name="web"),
            spec=v1.ReplicaSetSpec(
                replicas=2,
                selector={"app": "web"},
                template=_template({"app": "web"}),
            ),
        )
        server.create("replicasets", rs)

        def published():
            try:
                ep = server.get("endpoints", "default", "web")
            except KeyError:
                return False
            return (
                len(ep.subsets) == 1
                and len(ep.subsets[0].addresses) == 2
                and all(a.ip for a in ep.subsets[0].addresses)
                and ep.subsets[0].ports == [("http", 80)]
            )

        assert wait_until(published), server.list("endpoints")[0]


def test_disruption_controller_budget_accounting():
    cl = _Cluster(num_nodes=3)
    cl.controllers = [
        DisruptionController(cl.server),
        ReplicaSetController(cl.server),
    ]
    with cl:
        server = cl.server
        rs = v1.ReplicaSet(
            metadata=v1.ObjectMeta(name="quorum"),
            spec=v1.ReplicaSetSpec(
                replicas=3,
                selector={"app": "quorum"},
                template=_template({"app": "quorum"}),
            ),
        )
        server.create("replicasets", rs)
        pdb = v1.PodDisruptionBudget(
            metadata=v1.ObjectMeta(name="quorum-pdb"),
            spec=v1.PodDisruptionBudgetSpec(
                min_available=2, selector={"app": "quorum"}
            ),
        )
        server.create("poddisruptionbudgets", pdb)

        def budgeted():
            p = server.get("poddisruptionbudgets", "default", "quorum-pdb")
            return (
                p.status.current_healthy == 3
                and p.status.desired_healthy == 2
                and p.status.disruptions_allowed == 1
                and p.status.expected_pods == 3
            )

        assert wait_until(budgeted), server.get(
            "poddisruptionbudgets", "default", "quorum-pdb"
        ).status
