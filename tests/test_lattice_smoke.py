"""Smoke tests: encoder + lattice kernel end-to-end on tiny clusters."""

import numpy as np
import jax

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from kubernetes_tpu.api.selectors import LabelSelector
from kubernetes_tpu.ops.batch import encode_pod_batch
from kubernetes_tpu.ops.encoding import SnapshotEncoder
from kubernetes_tpu.ops.lattice import DEFAULT_WEIGHTS, make_schedule_batch
import jax.numpy as jnp


def make_node(name, cpu="4", mem="32Gi", labels=None, taints=None, unsched=False):
    return Node(
        metadata=ObjectMeta(name=name, namespace="", labels=labels or {}),
        spec=NodeSpec(unschedulable=unsched, taints=taints or []),
        status=NodeStatus(allocatable={"cpu": cpu, "memory": mem, "pods": 110}),
    )


def make_pod(name, cpu="1", mem="1Gi", ns="default", labels=None, **spec_kw):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {}),
        spec=PodSpec(
            containers=[Container(requests={"cpu": cpu, "memory": mem})], **spec_kw
        ),
    )


def run(enc, pods, weights=None):
    # order matters: encoding may intern new predicates (back-filling counts),
    # so the device flush must come after batch encoding.
    eb = encode_pod_batch(enc, pods)
    snap = enc.flush()
    kern = make_schedule_batch(enc.cfg.v_cap)
    w = jnp.asarray(weights if weights is not None else DEFAULT_WEIGHTS)
    return kern(snap, eb.batch, w, jax.random.PRNGKey(0))


def test_basic_fit_and_least_allocated():
    enc = SnapshotEncoder()
    for i in range(4):
        enc.add_node(make_node(f"n{i}", cpu="4"))
    # n0 is loaded: 3 cpu used
    enc.add_pod("n0", make_pod("existing", cpu="3"))
    res = run(enc, [make_pod("p", cpu="2")])
    chosen = int(res.chosen[0])
    assert chosen != -1
    assert enc.row_names[chosen] != "n0"  # least-allocated avoids loaded node
    assert int(res.feasible_count[0]) == 3  # n0 has only 1 cpu free

def test_resources_infeasible():
    enc = SnapshotEncoder()
    enc.add_node(make_node("n0", cpu="2"))
    enc.add_pod("n0", make_pod("existing", cpu="1500m"))
    res = run(enc, [make_pod("p", cpu="1")])
    assert int(res.chosen[0]) == -1
    assert int(res.feasible_count[0]) == 0
    assert bool(res.resolvable[0][0])  # preemption might help


def test_in_batch_resource_conflict():
    """Two pods that both fit an empty node, but not together — the scan
    carry must route the second elsewhere."""
    enc = SnapshotEncoder()
    enc.add_node(make_node("n0", cpu="3"))
    enc.add_node(make_node("n1", cpu="3"))
    res = run(enc, [make_pod("a", cpu="2"), make_pod("b", cpu="2")])
    rows = {int(res.chosen[0]), int(res.chosen[1])}
    assert rows == {0, 1}


def test_node_selector_and_affinity():
    enc = SnapshotEncoder()
    enc.add_node(make_node("gpu", labels={"accel": "gpu", "zone": "z1"}))
    enc.add_node(make_node("cpu", labels={"zone": "z2"}))
    res = run(enc, [make_pod("p", node_selector={"accel": "gpu"})])
    assert enc.row_names[int(res.chosen[0])] == "gpu"
    aff = Affinity(
        node_affinity=NodeAffinity(
            required=NodeSelector(
                terms=(
                    NodeSelectorTerm(
                        match_expressions=(
                            NodeSelectorRequirement("zone", "In", ("z2",)),
                        )
                    ),
                )
            )
        )
    )
    res = run(enc, [make_pod("q", affinity=aff)])
    assert enc.row_names[int(res.chosen[0])] == "cpu"


def test_taints_and_tolerations():
    enc = SnapshotEncoder()
    enc.add_node(make_node("tainted", taints=[Taint("dedicated", "db", "NoSchedule")]))
    enc.add_node(make_node("open"))
    res = run(enc, [make_pod("p")])
    assert enc.row_names[int(res.chosen[0])] == "open"
    res = run(
        enc,
        [
            make_pod(
                "q",
                tolerations=[
                    Toleration(key="dedicated", operator="Equal", value="db", effect="NoSchedule")
                ],
            )
        ],
    )
    assert int(res.feasible_count[0]) == 2


def test_unschedulable_node():
    enc = SnapshotEncoder()
    enc.add_node(make_node("off", unsched=True))
    enc.add_node(make_node("on"))
    res = run(enc, [make_pod("p")])
    assert enc.row_names[int(res.chosen[0])] == "on"
    assert int(res.feasible_count[0]) == 1


def test_node_name_pinned():
    enc = SnapshotEncoder()
    enc.add_node(make_node("n0"))
    enc.add_node(make_node("n1"))
    res = run(enc, [make_pod("p", node_name="n1")])
    assert enc.row_names[int(res.chosen[0])] == "n1"


def test_pod_anti_affinity_existing():
    """Existing pod with anti-affinity keeps matching pods off its zone."""
    enc = SnapshotEncoder()
    enc.add_node(make_node("a1", labels={"zone": "z1"}))
    enc.add_node(make_node("b1", labels={"zone": "z2"}))
    anti = Affinity(
        pod_anti_affinity=PodAntiAffinity(
            required=(
                PodAffinityTerm(
                    label_selector=LabelSelector.make(match_labels={"app": "web"}),
                    topology_key="zone",
                ),
            )
        )
    )
    holder = make_pod("holder", labels={"app": "db"}, affinity=anti)
    enc.add_pod("a1", holder)
    res = run(enc, [make_pod("p", labels={"app": "web"})])
    assert enc.row_names[int(res.chosen[0])] == "b1"
    # non-matching pod can go anywhere
    res = run(enc, [make_pod("q", labels={"app": "cache"})])
    assert int(res.feasible_count[0]) == 2


def test_incoming_pod_affinity():
    enc = SnapshotEncoder()
    enc.add_node(make_node("a1", labels={"zone": "z1"}))
    enc.add_node(make_node("b1", labels={"zone": "z2"}))
    enc.add_pod("a1", make_pod("web-1", labels={"app": "web"}))
    aff = Affinity(
        pod_affinity=PodAffinity(
            required=(
                PodAffinityTerm(
                    label_selector=LabelSelector.make(match_labels={"app": "web"}),
                    topology_key="zone",
                ),
            )
        )
    )
    res = run(enc, [make_pod("p", affinity=aff)])
    assert enc.row_names[int(res.chosen[0])] == "a1"
    # anti-affinity on incoming pod avoids z1
    anti = Affinity(
        pod_anti_affinity=PodAntiAffinity(
            required=(
                PodAffinityTerm(
                    label_selector=LabelSelector.make(match_labels={"app": "web"}),
                    topology_key="zone",
                ),
            )
        )
    )
    res = run(enc, [make_pod("q", affinity=anti)])
    assert enc.row_names[int(res.chosen[0])] == "b1"


def test_affinity_first_pod_carveout():
    """First pod of a group: affinity to itself is allowed when nothing matches."""
    enc = SnapshotEncoder()
    enc.add_node(make_node("a1", labels={"zone": "z1"}))
    aff = Affinity(
        pod_affinity=PodAffinity(
            required=(
                PodAffinityTerm(
                    label_selector=LabelSelector.make(match_labels={"app": "solo"}),
                    topology_key="zone",
                ),
            )
        )
    )
    res = run(enc, [make_pod("p", labels={"app": "solo"}, affinity=aff)])
    assert int(res.chosen[0]) == 0
    # but a pod NOT matching its own selector stays pending
    res = run(enc, [make_pod("q", labels={"app": "other"}, affinity=aff)])
    assert int(res.chosen[0]) == -1


def test_topology_spread_hard():
    enc = SnapshotEncoder()
    enc.add_node(make_node("a1", labels={"zone": "z1"}))
    enc.add_node(make_node("a2", labels={"zone": "z1"}))
    enc.add_node(make_node("b1", labels={"zone": "z2"}))
    sel = LabelSelector.make(match_labels={"app": "web"})
    tsc = TopologySpreadConstraint(
        max_skew=1, topology_key="zone", when_unsatisfiable="DoNotSchedule",
        label_selector=sel,
    )
    enc.add_pod("a1", make_pod("w1", labels={"app": "web"}))
    # z1 has 1, z2 has 0; new web pod with maxSkew 1 must go to z2
    res = run(
        enc,
        [make_pod("p", labels={"app": "web"}, topology_spread_constraints=[tsc])],
    )
    assert enc.row_names[int(res.chosen[0])] == "b1"


def test_host_ports():
    enc = SnapshotEncoder()
    enc.add_node(make_node("n0"))
    enc.add_node(make_node("n1"))
    holder = Pod(
        metadata=ObjectMeta(name="holder"),
        spec=PodSpec(
            containers=[Container(ports=[ContainerPort(80, host_port=8080)])]
        ),
    )
    enc.add_pod("n0", holder)
    contender = Pod(
        metadata=ObjectMeta(name="contender"),
        spec=PodSpec(
            containers=[
                Container(
                    requests={"cpu": "100m"},
                    ports=[ContainerPort(80, host_port=8080)],
                )
            ]
        ),
    )
    res = run(enc, [contender])
    assert enc.row_names[int(res.chosen[0])] == "n1"
    assert int(res.feasible_count[0]) == 1


def test_batch_padding_invalid_rows():
    enc = SnapshotEncoder()
    enc.add_node(make_node("n0"))
    eb = encode_pod_batch(enc, [make_pod("p")], pad_to=4)
    kern = make_schedule_batch(enc.cfg.v_cap)
    res = kern(enc.flush(), eb.batch, jnp.asarray(DEFAULT_WEIGHTS), jax.random.PRNGKey(0))
    assert int(res.chosen[0]) == 0
    assert all(int(res.chosen[i]) == -1 for i in range(1, 4))


def test_add_pods_bulk_matches_sequential():
    """The vectorized bulk-assume scatter must leave the host masters
    byte-identical to per-pod add_pod."""
    import numpy as np

    from kubernetes_tpu.ops.encoding import SnapshotEncoder

    def build(n_pods, bulk):
        enc = SnapshotEncoder()
        for i in range(8):
            enc.add_node(make_node(f"n{i}"))
        pods = []
        for i in range(n_pods):
            p = make_pod(
                f"p{i}",
                cpu="250m" if i % 2 else "100m",
                labels={"app": "a" if i % 3 else "b"},
            )
            pods.append(p)
        # intern a predicate so match vectors are non-trivial
        from kubernetes_tpu.api.selectors import LabelSelector

        enc.intern_predicate(
            frozenset({"default"}), LabelSelector.make({"app": "a"})
        )
        items = []
        for i, p in enumerate(pods):
            p.spec.node_name = f"n{i % 8}"
            proto = enc.pod_proto(p) if i % 2 else None  # mixed proto/None
            items.append((f"n{i % 8}", p, i % 3, proto))
        if bulk:
            enc.add_pods_bulk(items)
        else:
            for node, p, band, proto in items:
                enc.add_pod(node, p, device_synced=True, prio_band=band, proto=proto)
        return enc

    a = build(24, bulk=False)
    b = build(24, bulk=True)
    for field in (
        "m_req", "m_nonzero", "m_prio_req", "m_sel_counts",
        "m_eterm_w", "m_port_counts",
    ):
        np.testing.assert_array_equal(
            getattr(a, field), getattr(b, field), err_msg=field
        )
    assert set(
        (r, k) for r, d in a._pods.items() for k in d
    ) == set((r, k) for r, d in b._pods.items() for k in d)


def test_wave_score_refresh_sees_in_batch_commits():
    """Serial-fidelity (SURVEY §7 hard part (c)): a pod committing in a
    LATER wave must score nodes with the batch's earlier commits applied.
    Setup: n1 (10 cpu) statically beats n2 (9 cpu); two 6-cpu pods and a
    1-cpu pod batch together. The 6-cpu pair forces the small pod past
    wave 1 (prefix-fit conservatism); with refresh it then prefers the
    emptier n2, without refresh it returns to the statically-best n1."""
    from kubernetes_tpu.ops.lattice import (
        NUM_SCORE_COMPONENTS,
        SC_LEAST_ALLOC,
    )
    from kubernetes_tpu.ops.templates import TemplateCache, build_pair_table
    from kubernetes_tpu.ops.wavelattice import make_wave_kernel_jit

    def build():
        enc = SnapshotEncoder()
        enc.add_node(make_node("n1", cpu="10", mem="64Gi"))
        enc.add_node(make_node("n2", cpu="9", mem="64Gi"))
        tc = TemplateCache(enc)
        pods = [
            make_pod("big-0", cpu="6"),
            make_pod("big-1", cpu="6"),
            make_pod("small", cpu="1"),
        ]
        eb = tc.encode(pods, pad_to=4)
        ptab, _ = build_pair_table(enc, eb.tpl_np, eb.num_templates)
        snap = enc.flush()
        return enc, eb, ptab, snap

    weights = np.zeros(NUM_SCORE_COMPONENTS, np.float32)
    weights[SC_LEAST_ALLOC] = 1.0

    placements = {}
    for refresh in (True, False):
        enc, eb, ptab, snap = build()
        kern = make_wave_kernel_jit(
            enc.cfg.v_cap, 8, 4, 1.0, False, refresh
        )
        _snap2, res = kern(
            snap, eb.batch, ptab, weights, jax.random.PRNGKey(0)
        )
        chosen = jax.device_get(res.chosen)
        placed = jax.device_get(res.placed)
        assert placed[:3].all(), (refresh, placed)
        placements[refresh] = {
            p.metadata.name: enc.row_names[int(chosen[i])]
            for i, p in enumerate(eb.pods[:3])
        }
    # the big pair lands one per node either way (capacity)
    for ref, pl in placements.items():
        assert {pl["big-0"], pl["big-1"]} == {"n1", "n2"}, (ref, pl)
    # the refreshed kernel steers the wave-2 small pod to the node the
    # batch left emptier; the static kernel returns to the statically-best
    # n1 — BOTH arms are pinned so a refresh no-op regression is caught
    assert placements[True]["small"] == "n2", placements
    assert placements[False]["small"] == "n1", placements
