"""Vectorized victim selection (ops/preemptlattice) vs the host oracle.

The differential corpus is the acceptance gate for ISSUE 15: ≥ 500
randomized (cluster, preemptor-pod) cases — full clusters, mixed priority
bands, taints/selectors/unschedulable statics, PDB-constrained cases with
exhausted AND positive budgets — comparing the engine's composition
(kernel top-K ranking → exact ``Preemptor`` selection on the K rows,
full-walk fallback on rejection, exactly as scheduler.py wires it)
against the unrestricted host-path ``Preemptor`` oracle. Agreement is
"same victim sets modulo documented tie-breaks" (preemptlattice module
docstring): equal-oracle-key node ties, and the band-prefix-vs-reprieve
ranking class where the oracle's winner falls outside the kernel's top-K
— in which case the engine's victim set must still be its own node's
EXACT oracle selection (wrong evictions structurally impossible).

The seeded-disagreement test drives a REAL scheduler with a corrupted
kernel seam and asserts the output guard trips, the host path takes
over, and nothing wrong is ever evicted.
"""

import random
import time

import numpy as np
import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.resources import cpu_to_millis
from kubernetes_tpu.client import APIServer
from kubernetes_tpu.ops.batch import encode_pod_batch
from kubernetes_tpu.ops.encoding import (
    LABEL_COST_PER_HOUR,
    SnapshotEncoder,
)
from kubernetes_tpu.ops.preemptlattice import (
    GUARD_PREEMPT_EMPTY,
    GUARD_PREEMPT_ROW,
    PREEMPT_TOP_K,
    preempt_select,
    validate_preempt_outputs,
)
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler
from kubernetes_tpu.scheduler.cache.nodeinfo import NodeInfo, Snapshot
from kubernetes_tpu.scheduler.framework.registry import (
    default_plugin_set,
    default_registry,
)
from kubernetes_tpu.scheduler.framework.runtime import Framework
from kubernetes_tpu.scheduler.preemption import (
    Preemptor,
    filter_pods_with_pdb_violation,
)
from kubernetes_tpu.utils.metrics import metrics

APPS = ["web", "db", "cache"]
ZONES = ["za", "zb"]


def _framework(holder):
    ps = default_plugin_set()
    ps.filter = [
        n
        for n in ps.filter
        if n
        not in (
            "VolumeRestrictions", "NodeVolumeLimits", "EBSLimits",
            "GCEPDLimits", "AzureDiskLimits", "VolumeBinding", "VolumeZone",
        )
    ]
    ctx = {
        "snapshot_getter": lambda: holder[0],
        "hard_pod_affinity_weight": 1.0,
        "ignored_extended_resources": frozenset(),
    }
    return Framework(default_registry(), ps, ctx)


def make_node(name, cpu="4", labels=None, taints=None, unschedulable=False):
    return v1.Node(
        metadata=v1.ObjectMeta(name=name, namespace="", labels=labels or {}),
        spec=v1.NodeSpec(taints=list(taints or []), unschedulable=unschedulable),
        status=v1.NodeStatus(
            allocatable={"cpu": cpu, "memory": "16Gi", "pods": 32}
        ),
    )


def make_pod(name, cpu="1", prio=0, labels=None, node_selector=None,
             tolerations=None):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, labels=labels or {}),
        spec=v1.PodSpec(
            containers=[v1.Container(requests={"cpu": cpu})],
            priority=prio,
            node_selector=dict(node_selector or {}),
            tolerations=list(tolerations or []),
        ),
    )


def _pdb(name, app, allowed):
    return v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name=name),
        spec=v1.PodDisruptionBudgetSpec(selector={"app": app}),
        status=v1.PodDisruptionBudgetStatus(disruptions_allowed=allowed),
    )


def wait_until(fn, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


# ---------------------------------------------------------------------------
# kernel unit behavior
# ---------------------------------------------------------------------------


def test_preempt_select_minimal_band_prefix_and_node_ranking():
    """Three full nodes: low-priority victims beat mid-priority ones
    (criterion 2), a node whose pods all outrank the preemptor is never
    helpful, and the victim count is the minimal fitting BAND prefix."""
    enc = SnapshotEncoder()
    for n in ("a", "b", "c"):
        enc.add_node(make_node(n))
    for i in range(4):
        enc.add_pod("a", make_pod(f"la{i}", "1", prio=0))
        enc.add_pod("b", make_pod(f"hb{i}", "1", prio=50))
        enc.add_pod("c", make_pod(f"mc{i}", "1", prio=5))
    snap = enc.flush()
    eb = encode_pod_batch(enc, [make_pod("pre", "2", prio=10)], pad_to=16)
    res = preempt_select(snap, eb.batch, eb.batch.priority)
    node = int(np.asarray(res.node)[0])
    assert enc.row_names[node] == "a"  # prio-0 victims beat prio-5
    assert int(np.asarray(res.threshold_prio)[0]) == 0
    # band granularity: the whole prio-0 band is the minimal PREFIX (the
    # host reprieve trims within it — documented division of labor)
    assert int(np.asarray(res.victims)[0]) == 4
    helpful = np.asarray(res.helpful)[0]
    by_name = {enc.row_names[r]: bool(helpful[r]) for r in range(3)}
    assert by_name == {"a": True, "b": False, "c": True}
    # ranked candidates: c follows a; b never appears
    names = [enc.row_names[int(r)] for r in np.asarray(res.cand)[0] if r >= 0]
    assert names[:2] == ["a", "c"]
    assert "b" not in names


def test_pdb_budget_column_deprioritizes_blocked_nodes():
    """Two otherwise-identical nodes; one's victims are PDB-blocked
    (exhausted budget, via update_pdb_blocked). Criterion 1 must rank the
    unblocked node first."""
    enc = SnapshotEncoder()
    enc.add_node(make_node("blocked"))
    enc.add_node(make_node("free"))
    for i in range(4):
        enc.add_pod("blocked", make_pod(f"pb{i}", "1", prio=0,
                                        labels={"app": "web"}))
        enc.add_pod("free", make_pod(f"pf{i}", "1", prio=0,
                                     labels={"app": "db"}))
    changed = enc.update_pdb_blocked([_pdb("b", "web", 0)])
    assert changed == 1  # only the blocked node's column moved
    snap = enc.flush()
    eb = encode_pod_batch(enc, [make_pod("pre", "2", prio=10)], pad_to=16)
    res = preempt_select(snap, eb.batch, eb.batch.priority)
    assert enc.row_names[int(np.asarray(res.node)[0])] == "free"
    assert int(np.asarray(res.violations)[0]) == 0
    # budget recovery flips the ranking input back
    assert enc.update_pdb_blocked([_pdb("b", "web", 2)]) == 1
    assert enc.m_pdb_blocked.sum() == 0


def test_validate_preempt_outputs_guards():
    ok = np.array([0, -1, 2], np.int32)
    vic = np.array([2, 0, 1], np.int32)
    assert validate_preempt_outputs(ok, vic, 3) is None
    assert (
        validate_preempt_outputs(np.array([5], np.int32), np.array([1]), 3)
        == GUARD_PREEMPT_ROW
    )
    assert (
        validate_preempt_outputs(np.array([-7], np.int32), np.array([1]), 3)
        == GUARD_PREEMPT_ROW
    )
    assert (
        validate_preempt_outputs(np.array([1], np.int32), np.array([0]), 3)
        == GUARD_PREEMPT_EMPTY
    )
    # candidate plane rows are validated too
    assert (
        validate_preempt_outputs(
            np.array([1], np.int32), np.array([1]), 3,
            cand=np.array([[1, 9]], np.int32),
        )
        == GUARD_PREEMPT_ROW
    )
    assert (
        validate_preempt_outputs(np.array([-1], np.int32), None, 3) is None
    )


# ---------------------------------------------------------------------------
# the differential corpus
# ---------------------------------------------------------------------------


def _random_case(seed: int):
    """One randomized FULL cluster + a batch of preemptor pods, mirroring
    production preemption preconditions (pods genuinely unschedulable on
    resources). Odd seeds add positive-budget PDBs (the countdown regime);
    even seeds exhausted (budget-0) ones."""
    rng = random.Random(seed)
    n_nodes = rng.choice([6, 8, 10])
    enc = SnapshotEncoder()
    infos = {}
    nodes = []
    for i in range(n_nodes):
        taints = (
            [v1.Taint("dedicated", "infra", "NoSchedule")]
            if rng.random() < 0.15
            else []
        )
        n = make_node(
            f"n{i}",
            cpu=str(rng.choice([2, 4])),
            labels={"zone": rng.choice(ZONES)},
            taints=taints,
            unschedulable=(rng.random() < 0.05),
        )
        nodes.append(n)
        enc.add_node(n)
        infos[n.metadata.name] = NodeInfo(n)
    j = 0
    for n in nodes:
        free = cpu_to_millis(n.status.allocatable["cpu"])
        while free >= 500:
            cpu = rng.choice(["500m", "1"]) if free >= 1000 else "500m"
            p = make_pod(
                f"pre-{j}", cpu, prio=rng.choice([0, 5, 10, 50]),
                labels={"app": rng.choice(APPS)},
            )
            p.spec.node_name = n.metadata.name
            p.status.start_time = float(j)
            enc.add_pod(n.metadata.name, p)
            infos[n.metadata.name].add_pod(p)
            free -= cpu_to_millis(cpu)
            j += 1
    pdbs = []
    if seed % 2 == 0:
        for app in rng.sample(APPS, rng.randrange(0, 3)):
            pdbs.append(_pdb(f"pdb-{app}", app, 0))
    else:
        for app in rng.sample(APPS, rng.randrange(1, 3)):
            pdbs.append(_pdb(f"pdb-{app}", app, rng.choice([0, 1, 2])))
    enc.update_pdb_blocked(pdbs)
    preemptors = [
        make_pod(
            f"hi-{k}",
            cpu=rng.choice(["1", "2", "3"]),
            prio=rng.choice([20, 100]),
            labels={"app": rng.choice(APPS)},
            node_selector=(
                {"zone": rng.choice(ZONES)} if rng.random() < 0.2 else None
            ),
            tolerations=(
                [v1.Toleration(key="dedicated", operator="Exists")]
                if rng.random() < 0.3
                else None
            ),
        )
        for k in range(8)
    ]
    return enc, infos, pdbs, preemptors


def _oracle_key(victims, pdbs):
    violating, _ = filter_pods_with_pdb_violation(list(victims), pdbs)
    return (
        len(violating),
        max((v.priority for v in victims), default=-(2 ** 31)),
        sum(v.priority for v in victims),
        len(victims),
    )


def test_differential_corpus_vs_host_oracle():
    """≥ 500 randomized cases: the engine composition (kernel top-K →
    exact Preemptor on the K rows → full-walk fallback) vs the
    unrestricted host oracle. Every case must land in a documented
    class; the strict-agreement classes must cover ≥ 500 cases on their
    own; possibility disagreements (one side finds preemption viable,
    the other doesn't) must be ZERO; and in every case the engine's
    victim set must be its chosen node's exact oracle selection."""
    strict = 0  # exact victim-set equality / equal-key tie / both-none
    ranked_refinement = 0  # oracle winner outside kernel top-K (documented)
    total = 0
    seed = 0
    while total < 560:
        enc, infos, pdbs, preemptors = _random_case(seed)
        seed += 1
        snap = enc.flush()
        holder = [Snapshot(list(infos.values()))]
        pre = Preemptor(_framework(holder), pdb_lister=lambda: pdbs)
        eb = encode_pod_batch(enc, preemptors, pad_to=16)
        res = preempt_select(snap, eb.batch, eb.batch.priority)
        cand = np.asarray(res.cand)
        n_rows = len(enc.row_names)
        assert validate_preempt_outputs(
            np.asarray(res.node), np.asarray(res.victims), n_rows, cand=cand
        ) is None
        for k, pod in enumerate(preemptors):
            if eb.fallback[k]:
                continue
            total += 1
            onode, ovic = pre.preempt(pod, holder[0], None, None)
            names = [
                enc.row_names[int(r)]
                for r in cand[k]
                if r >= 0 and enc.row_names[int(r)]
            ]
            if names:
                enode, evic = pre.preempt(pod, holder[0], None, names)
                if not enode:  # the production oracle-reject fallback
                    enode, evic = onode, ovic
            else:
                enode, evic = "", []
            # possibility agreement is unconditional
            assert bool(onode) == bool(enode), (
                f"seed {seed - 1} pod {pod.metadata.name}: oracle "
                f"{onode!r} vs engine {enode!r}"
            )
            if not onode:
                strict += 1
                continue
            # the engine's victim set is ALWAYS its node's exact oracle
            # selection (the structural zero-wrong-evictions guarantee)
            rnode, rvic = pre.preempt(pod, holder[0], None, [enode])
            assert rnode == enode
            assert {v.metadata.key for v in rvic} == {
                v.metadata.key for v in evic
            }
            if enode == onode and {v.metadata.key for v in evic} == {
                v.metadata.key for v in ovic
            }:
                strict += 1
            elif _oracle_key(evic, pdbs) == _oracle_key(ovic, pdbs):
                strict += 1  # equal-key tie: documented tie-break 1
            else:
                # documented class 2: the oracle's winner must be outside
                # the kernel's K candidates (band-prefix vs reprieve
                # refinement) — a winner INSIDE the list resolving
                # differently would be an engine bug
                assert onode not in names, (
                    f"seed {seed - 1} pod {pod.metadata.name}: oracle "
                    f"winner {onode} was in the candidate list {names} "
                    "but the engine picked a worse-keyed node"
                )
                ranked_refinement += 1
    assert total >= 560
    assert strict >= 500, (
        f"only {strict}/{total} strict agreements "
        f"({ranked_refinement} ranked-refinement cases)"
    )
    # the documented refinement class stays a small tail, not a regime
    assert ranked_refinement <= total * 0.08


# ---------------------------------------------------------------------------
# seeded disagreement: guard trip → host fallback, zero wrong evictions
# ---------------------------------------------------------------------------


def _fill_cluster(srv, n_nodes=5, per_node=4, prio=0):
    for i in range(n_nodes):
        srv.create("nodes", make_node(f"n{i}"))
    for i in range(n_nodes):
        for k in range(per_node):
            p = make_pod(f"low-{i}-{k}", "1", prio=prio,
                         labels={"app": "web"})
            srv.create("pods", p)


def _all_bound(srv, prefix, n):
    pods, _ = srv.list("pods")
    mine = [p for p in pods if p.metadata.name.startswith(prefix)]
    return len(mine) == n and all(p.spec.node_name for p in mine)


@pytest.mark.parametrize("corruption", ["row_out_of_range", "empty_victims"])
def test_seeded_disagreement_trips_guard_and_falls_back(corruption):
    """Corrupt the kernel readback seam on a live scheduler: the output
    guard must trip (counted), every pod must still preempt + bind via
    the host walk, and no eviction may touch a node the oracle wouldn't
    have chosen (here: every victim is a genuinely lower-priority pod)."""
    srv = APIServer()
    sched = Scheduler(srv, KubeSchedulerConfiguration())
    real = sched._run_preempt_kernel

    def corrupted(snap, batch, prios):
        out = real(snap, batch, prios)
        if corruption == "row_out_of_range":
            out["node"] = out["node"].copy()
            out["node"][out["node"] >= 0] = 10_000
        else:
            out["victims"] = np.zeros_like(out["victims"])
        return out

    sched._run_preempt_kernel = corrupted
    _fill_cluster(srv)
    sched.start()
    try:
        assert wait_until(lambda: _all_bound(srv, "low-", 20), 60)
        # batch of 6 > small_batch_host_max keeps the burst on the wave
        # (device) path where the vector engine lives
        for i in range(6):
            srv.create("pods", make_pod(f"hi-{i}", "2", prio=100))
        assert wait_until(lambda: _all_bound(srv, "hi-", 6), 90)
        trips = sum(
            v
            for _n, _l, v in metrics.snapshot_counters(
                "scheduler_preemption_guard_trips_total"
            )
        )
        assert trips >= 1
        # zero vector evictions: every attempt fell back to the host walk
        assert metrics.counter("scheduler_preemption_vector_hits_total") == 0
        # no high-priority pod was ever evicted (wrong-eviction check)
        pods, _ = srv.list("pods")
        assert sum(1 for p in pods if p.metadata.name.startswith("hi-")) == 6
    finally:
        sched.stop()


def test_vector_preemption_end_to_end_happy_path():
    """The ISSUE-15 happy path: a high-priority burst over a full cluster
    resolves victims through the batched pass (scheduler_preemption_
    batches_total advances, vector hits land) with zero divergences from
    the sampled differential oracle."""
    srv = APIServer()
    sched = Scheduler(srv, KubeSchedulerConfiguration())
    _fill_cluster(srv, n_nodes=6)
    sched.start()
    try:
        assert wait_until(lambda: _all_bound(srv, "low-", 24), 60)
        for i in range(6):
            srv.create("pods", make_pod(f"hi-{i}", "2", prio=100))
        assert wait_until(lambda: _all_bound(srv, "hi-", 6), 90)
        assert metrics.counter("scheduler_preemption_batches_total") >= 1
        assert metrics.counter("scheduler_preemption_vector_hits_total") >= 1
        assert (
            metrics.counter("scheduler_preemption_oracle_divergence_total")
            == 0
        )
    finally:
        sched.stop()


def test_sibling_burst_fans_out_across_distinct_nodes():
    """In-batch fan-out regression: a burst of SIBLING preemptors bigger
    than the kernel's top-K must nominate DISTINCT nodes within few
    batched passes — without the `targeted` fan-out every sibling picked
    the same node against the batch-stale snapshot and a wave freed
    exactly one node (measured at bench scale: 89/1000 pods in 25 min)."""
    srv = APIServer()
    sched = Scheduler(srv, KubeSchedulerConfiguration())
    # FULL cluster, victims PRE-BOUND, the whole burst present before the
    # scheduler starts: the first wave carries all 10 siblings in ONE
    # batch (pad bucket 16), which is the scope the fan-out guarantee
    # covers — across waves a refreshed snapshot may legitimately re-use
    # a node (evicting its remaining victims)
    for i in range(10):
        srv.create("nodes", make_node(f"n{i}"))
        for k in range(4):
            p = make_pod(f"low-{i}-{k}", "1", prio=0, labels={"app": "web"})
            p.spec.node_name = f"n{i}"
            srv.create("pods", p)
    # 10 identical 2-cpu pods need 10 nodes' victims: > top-K (4), so the
    # fan-out tail (helpful rows beyond the ranked K) must engage too
    for i in range(10):
        srv.create("pods", make_pod(f"hi-{i}", "2", prio=100))
    sched.start()
    try:
        assert wait_until(lambda: _all_bound(srv, "hi-", 10), 90)
        pods, _ = srv.list("pods")
        hi_nodes = {
            p.spec.node_name
            for p in pods
            if p.metadata.name.startswith("hi-")
        }
        assert len(hi_nodes) == 10  # one preemption per node, no pile-up
        # distinct targets per batch: the whole burst resolves in a few
        # select batches, not one-node-per-wave convergence
        assert metrics.counter("scheduler_preemption_batches_total") <= 5
    finally:
        sched.stop()


# ---------------------------------------------------------------------------
# score policies (runtime weight vectors)
# ---------------------------------------------------------------------------


def test_score_policy_cheapest_prefers_cheap_nodes():
    """The 'cheapest' policy (cost column + pack) must steer placements
    onto the cheaper node with both feasible; swapping policies at
    runtime needs no restart (the vector is a kernel input)."""
    from kubernetes_tpu.ops.lattice import weights_for_policy

    with pytest.raises(ValueError):
        weights_for_policy("no-such-policy")
    with pytest.raises(ValueError):
        weights_for_policy([1.0, 2.0])  # wrong shape

    srv = APIServer()
    sched = Scheduler(
        srv, KubeSchedulerConfiguration(score_policy="cheapest")
    )
    srv.create(
        "nodes",
        make_node("pricey", cpu="8", labels={LABEL_COST_PER_HOUR: "9.5"}),
    )
    srv.create(
        "nodes",
        make_node("cheap", cpu="8", labels={LABEL_COST_PER_HOUR: "0.4"}),
    )
    sched.start()
    try:
        for i in range(6):
            srv.create("pods", make_pod(f"p{i}", "500m"))
        assert wait_until(lambda: _all_bound(srv, "p", 6), 60)
        pods, _ = srv.list("pods")
        on_cheap = sum(1 for p in pods if p.spec.node_name == "cheap")
        assert on_cheap == 6
        # runtime swap: no exception, takes effect next wave
        sched.set_score_policy("default")
        sched.set_score_policy("energy")
    finally:
        sched.stop()
