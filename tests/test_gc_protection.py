"""Pod GC, PVC/PV protection finalizers, root-CA publisher, priority
admission, and the store's graceful-deletion core.

Reference: pkg/controller/podgc, pkg/controller/volume/{pvcprotection,
pvprotection}, pkg/controller/certificates/rootcacertpublisher,
plugin/pkg/admission/priority, and the registry's finalizer-aware
deletion."""

import time

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.apiserver.auth import (
    AdmissionChain,
    AdmissionDenied,
    PriorityAdmission,
)
from kubernetes_tpu.client import APIServer
from kubernetes_tpu.controller.podgc import (
    PVC_FINALIZER,
    PodGCController,
    PVCProtectionController,
    RootCACertPublisher,
)


def wait_until(fn, timeout=25.0, period=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(period)
    return False


def make_pod(name, phase=None, node=None):
    p = v1.Pod(
        metadata=v1.ObjectMeta(name=name),
        spec=v1.PodSpec(containers=[v1.Container(requests={"cpu": "10m"})]),
    )
    if phase:
        p.status.phase = phase
    if node:
        p.spec.node_name = node
    return p


def test_store_graceful_deletion_with_finalizers():
    server = APIServer()
    pod = make_pod("fin")
    pod.metadata.finalizers.append("example.com/hold")
    server.create("pods", pod)
    server.delete("pods", "default", "fin")
    # still present, marked deleting
    cur = server.get("pods", "default", "fin")
    assert cur.metadata.deletion_timestamp is not None
    # stripping the last finalizer completes the deletion
    def strip(p):
        p.metadata.finalizers.clear()
        return p

    server.guaranteed_update("pods", "default", "fin", strip)
    try:
        server.get("pods", "default", "fin")
        raise AssertionError("object must be gone after finalizer strip")
    except KeyError:
        pass


def test_podgc_threshold_and_orphans():
    server = APIServer()
    server.create("nodes", v1.Node(metadata=v1.ObjectMeta(name="live"), spec=v1.NodeSpec()))
    # 5 finished pods with a threshold of 2 -> 3 oldest GC'd
    for i in range(5):
        p = make_pod(f"done-{i}", phase=v1.POD_SUCCEEDED)
        p.metadata.creation_timestamp = 1000.0 + i
        server.create("pods", p)
    server.create("pods", make_pod("ghost", node="gone-node"))
    ctrl = PodGCController(server, terminated_pod_threshold=2, tick=0.2)
    ctrl.start()
    try:
        def gcd():
            names = {p.metadata.name for p in server.list("pods")[0]}
            return names == {"done-3", "done-4"}

        assert wait_until(gcd), "oldest finished + orphaned pods must be GC'd"
    finally:
        ctrl.stop()


def test_pvc_protection_defers_deletion_while_in_use():
    server = APIServer()
    pvc = v1.PersistentVolumeClaim(
        metadata=v1.ObjectMeta(name="data"),
        spec=v1.PersistentVolumeClaimSpec(resources={"storage": "1Gi"}),
    )
    server.create("persistentvolumeclaims", pvc)
    user = make_pod("user")
    user.spec.volumes.append(v1.Volume(name="data", persistent_volume_claim="data"))
    server.create("pods", user)
    ctrl = PVCProtectionController(server)
    ctrl.start()
    try:
        assert wait_until(
            lambda: PVC_FINALIZER
            in server.get("persistentvolumeclaims", "default", "data").metadata.finalizers
        )
        server.delete("persistentvolumeclaims", "default", "data")
        time.sleep(0.5)
        cur = server.get("persistentvolumeclaims", "default", "data")
        assert cur.metadata.deletion_timestamp is not None, "deletion deferred"
        # the using pod goes away -> protection releases -> claim removed
        server.delete("pods", "default", "user")
        def gone():
            try:
                server.get("persistentvolumeclaims", "default", "data")
                return False
            except KeyError:
                return True

        assert wait_until(gone), "claim must be removed once unused"
    finally:
        ctrl.stop()


def test_root_ca_published_per_namespace():
    server = APIServer()
    server.create("namespaces", v1.Namespace(metadata=v1.ObjectMeta(name="apps")))
    ctrl = RootCACertPublisher(server)
    ctrl.start()
    try:
        def published():
            try:
                cm = server.get("configmaps", "apps", "kube-root-ca.crt")
            except KeyError:
                return False
            return "ca.crt" in cm.data

        assert wait_until(published)
    finally:
        ctrl.stop()


def test_priority_admission_resolves_class():
    server = APIServer()
    server.create(
        "priorityclasses",
        v1.PriorityClass(metadata=v1.ObjectMeta(name="high"), value=1000),
    )
    server.create(
        "priorityclasses",
        v1.PriorityClass(
            metadata=v1.ObjectMeta(name="base"), value=7, global_default=True
        ),
    )
    server.admit_hooks.append(AdmissionChain(mutating=[PriorityAdmission(server)]))

    named = make_pod("named")
    named.spec.priority_class_name = "high"
    server.create("pods", named)
    assert server.get("pods", "default", "named").spec.priority == 1000

    plain = make_pod("plain")
    server.create("pods", plain)
    got = server.get("pods", "default", "plain")
    assert got.spec.priority == 7 and got.spec.priority_class_name == "base"

    bogus = make_pod("bogus")
    bogus.spec.priority_class_name = "nope"
    try:
        server.create("pods", bogus)
        raise AssertionError("unknown priority class must be denied")
    except AdmissionDenied:
        pass


def test_eviction_api_respects_pdb_and_kubectl_drain(capsys):
    """POST pods/{name}/eviction honors PDBs (429 when exhausted);
    kubectl drain cordons + evicts, retrying blocked pods until the
    disruption controller frees budget."""
    from kubernetes_tpu.apiserver.rest import serve
    from kubernetes_tpu.client.apiserver import TooManyRequests
    from kubernetes_tpu.cmd import kubectl

    server = APIServer()
    for n in ("n0", "n1"):
        server.create(
            "nodes", v1.Node(metadata=v1.ObjectMeta(name=n), spec=v1.NodeSpec())
        )
    for i in range(3):
        p = make_pod(f"web-{i}", node="n0" if i < 2 else "n1")
        p.metadata.labels["app"] = "web"
        p.status.phase = v1.POD_RUNNING
        server.create("pods", p)
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="web-pdb"),
        spec=v1.PodDisruptionBudgetSpec(min_available=2, selector={"app": "web"}),
    )
    pdb.status.disruptions_allowed = 1  # 3 healthy, min 2
    server.create("poddisruptionbudgets", pdb)

    # direct store semantics: first eviction consumes the budget, second 429s
    server.evict_pod("default", "web-2")
    try:
        server.evict_pod("default", "web-1")
        raise AssertionError("second eviction must violate the PDB")
    except TooManyRequests:
        pass

    # over HTTP: 429 carries TooManyRequests
    srv, port, _ = serve(store=server)
    try:
        import json as _json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/namespaces/default/pods/web-1/eviction",
            data=_json.dumps({"kind": "Eviction"}).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 429")
        except urllib.error.HTTPError as e:
            assert e.code == 429

        # free the budget (as the disruption controller would) and drain n0
        def free(b):
            b.status.disruptions_allowed = 2
            return b

        server.guaranteed_update("poddisruptionbudgets", "default", "web-pdb", free)
        rc = kubectl.main(
            ["--server", f"http://127.0.0.1:{port}", "drain", "n0", "--timeout", "20"]
        )
        assert rc == 0, capsys.readouterr().err
        left = [p.metadata.name for p in server.list("pods")[0]]
        assert left == [], f"drained node pods must be evicted: {left}"
    finally:
        srv.shutdown()


def test_auth_can_i(capsys):
    from kubernetes_tpu.apiserver.auth import (
        RBACAuthorizer,
        TokenAuthenticator,
        make_rule,
    )
    from kubernetes_tpu.apiserver.rest import serve
    from kubernetes_tpu.cmd import kubectl

    authn = TokenAuthenticator(allow_anonymous=False)
    authn.add_token("tok", "alice")
    authz = RBACAuthorizer()
    authz.bind("alice", make_rule(["get", "list"], ["pods"]))
    srv, port, _ = serve(authenticator=authn, authorizer=authz)
    try:
        import urllib.request

        def can(verb, resource):
            import json as _json

            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/selfsubjectaccessreviews",
                data=_json.dumps(
                    {"spec": {"resourceAttributes": {"verb": verb, "resource": resource}}}
                ).encode(),
                method="POST",
                headers={
                    "Content-Type": "application/json",
                    "Authorization": "Bearer tok",
                },
            )
            with urllib.request.urlopen(req) as resp:
                return _json.loads(resp.read())["status"]["allowed"]

        assert can("get", "pods") is True
        assert can("delete", "pods") is False
        assert can("get", "secrets") is False
    finally:
        srv.shutdown()


def test_replication_controller_and_csr_signing():
    """RC shares the replicaset reconcile core; CSR approve+sign flow
    (pkg/controller/replication + pkg/controller/certificates — approver
    and signer are separate loops, as in the reference)."""
    from kubernetes_tpu.controller.certificates import (
        APPROVED,
        CSRApprovingController,
        CSRSigningController,
    )
    from kubernetes_tpu.controller.replicaset import (
        ReplicationControllerController,
    )

    server = APIServer()
    rc = v1.ReplicationController(
        metadata=v1.ObjectMeta(name="legacy"),
        spec=v1.ReplicaSetSpec(
            replicas=3,
            selector={"app": "legacy"},
            template=v1.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": "legacy"}),
                spec=v1.PodSpec(containers=[v1.Container(requests={"cpu": "10m"})]),
            ),
        ),
    )
    server.create("replicationcontrollers", rc)
    ctrl = ReplicationControllerController(server)
    ctrl.start()
    try:
        assert wait_until(
            lambda: sum(
                1
                for p in server.list("pods")[0]
                if any(
                    r.kind == "ReplicationController" and r.controller
                    for r in p.metadata.owner_references
                )
            )
            == 3
        ), "RC must maintain 3 replicas"
    finally:
        ctrl.stop()

    csr = v1.CertificateSigningRequest(
        metadata=v1.ObjectMeta(name="node-csr", namespace=""),
        spec=v1.CertificateSigningRequestSpec(
            request="worker-0-pubkey",
            username="system:bootstrap",
            groups=["system:bootstrappers"],
        ),
    )
    server.create("certificatesigningrequests", csr)
    approver = CSRApprovingController(server)
    signer = CSRSigningController(server)
    approver.start()
    signer.start()
    try:
        def signed():
            cur = server.get("certificatesigningrequests", "", "node-csr")
            return (
                any(c.type == APPROVED and c.status == "True" for c in cur.status.conditions)
                and bool(cur.status.certificate)
            )

        assert wait_until(signed), "bootstrap kubelet CSR must auto-approve + sign"
    finally:
        approver.stop()
        signer.stop()
