"""Autoscaler e2e chaos: scale-up/scale-down on the ChaosStore ledger.

Acceptance scenarios for the kernel-driven cluster autoscaler:

  * pending unschedulable pods → one what-if overlay pass → nodes
    provisioned (hollow kubelets pick them up) → ALL pods bind within one
    autoscaler period of the capacity registering; zero evictions, zero
    acked-bind loss, zero double-binds
  * scale-down only after the drain simulation proves every resident pod
    re-places: evictions flow through the token bucket, the controller-
    owned pods are recreated and re-bind on surviving nodes, the empty
    node is deleted and its hollow kubelet torn down
  * a node whose resident pod CANNOT re-place (simulation-infeasible) is
    never cordoned and never loses a pod — the zero-eviction guarantee
  * a degraded (read-only) store pauses provisioning without killing the
    loop; scale-up completes after recovery
"""

import time

import pytest

from test_chaos_pipeline import (
    ChaosStore,
    _watch_deletions,
    assert_bind_invariants,
    wait_until,
)

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.autoscaler import (
    ClusterAutoscaler,
    NodeGroup,
    NodeGroupCatalog,
    machine_shape,
)
from kubernetes_tpu.controller.replicaset import ReplicaSetController
from kubernetes_tpu.kubemark.hollow_node import HollowCluster
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler
from kubernetes_tpu.utils.metrics import metrics


def make_pod(name, cpu="1", node_selector=None, owners=None):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, owner_references=list(owners or [])),
        spec=v1.PodSpec(
            containers=[v1.Container(requests={"cpu": cpu})],
            node_selector=dict(node_selector or {}),
        ),
    )


def _bound_count(store):
    return store.count("pods", lambda p: bool(p.spec.node_name))


def _get_or_none(store, kind, ns, name):
    from kubernetes_tpu.client.apiserver import NotFound

    try:
        return store.get(kind, ns, name)
    except NotFound:
        return None


def _rig(store, groups, **auto_kw):
    """hollow kubelet pool + scheduler + autoscaler, wired together."""
    hollow = HollowCluster(
        store, heartbeat_interval=0.5, housekeeping_interval=0.1
    )
    for g in groups:
        g.provision, g.deprovision = hollow.provisioner_for(g.make_node)
    sched = Scheduler(store, KubeSchedulerConfiguration())
    auto = ClusterAutoscaler(
        store, sched, NodeGroupCatalog(groups), **auto_kw
    )
    return hollow, sched, auto


def test_warmup_compile_autoscaler_kernels():
    """Lint-exempt compile absorber (`warmup_compile` substring — see
    scripts/check_slow_markers.py): the first what-if pass in this process
    pays the serial lattice kernel + overlay scatter XLA compiles, which
    are positional, not per-test. The pass runs against a REAL scheduler's
    cache (8-virtual-device mesh ⇒ sharded snapshot), because the sharded
    variants are distinct executables from the bare-cache ones — an
    unsharded warmup absorbs nothing the scenarios below use."""
    from kubernetes_tpu.autoscaler import WhatIfSimulator

    store = ChaosStore()
    sched = Scheduler(store, KubeSchedulerConfiguration())
    sched.start()
    try:
        store.create(
            "nodes", machine_shape(cpu="4", memory="32Gi", pods=32)("warm-n0")
        )
        assert wait_until(
            lambda: sched.cache.get_node_info("warm-n0") is not None, 10
        )
        sim = WhatIfSimulator(sched.cache)
        res = sim.simulate(
            [make_pod("warm-p0")],
            [machine_shape(cpu="4", memory="32Gi", pods=32)("warm-v0")],
            mask_node="warm-n0",
        )
        assert res is not None
        # drive one pod through the UNSCHEDULABLE path too: the failure
        # handler's preempt-whatif kernel is yet another positional
        # compile the scale-up scenario would otherwise pay
        for i in range(5):
            store.create("pods", make_pod(f"warm-big-{i}", cpu="64"))
        assert wait_until(
            lambda: len(sched.queue.unschedulable_pod_infos()) == 5, 30
        )
    finally:
        sched.stop()


def test_scale_up_pending_pods_bind_within_one_period():
    """Acceptance: unschedulable pods drive a what-if pass, the kernel's
    chosen virtual rows become real nodes, and the queue's node-add flush
    (failure-relative backoff) gets every pod bound within one autoscaler
    period of the capacity registering — with zero evictions anywhere."""
    store = ChaosStore()
    period = 0.3
    group = NodeGroup(
        name="std",
        template=machine_shape(cpu="4", memory="32Gi", pods=32),
        max_size=16,
    )
    hollow, sched, auto = _rig(
        store, [group], period_s=period, scale_down_enabled=False
    )
    n = 12  # 1-cpu pods on 4-cpu shapes: 3 nodes
    for i in range(n):
        store.create("pods", make_pod(f"pend-{i}"))
    deletions = []
    w = _watch_deletions(store, deletions)
    hollow.start()
    sched.start()
    try:
        # no nodes at all: the whole burst lands in unschedulableQ
        assert wait_until(
            lambda: len(sched.queue.unschedulable_pod_infos()) == n, 30
        ), "pods never reached unschedulableQ"
        assert _bound_count(store) == 0
        auto.start()
        assert wait_until(lambda: store.count("nodes") > 0, 15), (
            "autoscaler never provisioned"
        )
        t_nodes = time.monotonic()
        assert wait_until(lambda: _bound_count(store) == n, 20), (
            f"only {_bound_count(store)}/{n} bound after scale-up"
        )
        elapsed = time.monotonic() - t_nodes
        # one autoscaler period + scheduling slack — NOT the 30-60 s
        # unschedulableQ leftover sweep the queue satellite bypasses
        assert elapsed <= period + 4.5, (
            f"bind-after-capacity budget blown: {elapsed:.1f}s"
        )
        nodes, _ = store.list("nodes")
        assert 3 <= len(nodes) <= 4, (
            f"expected ~3 nodes for 12x1cpu on 4-cpu shapes, got {len(nodes)}"
        )
        assert not deletions, f"scale-up must evict nothing: {deletions}"
        assert_bind_invariants(store)
        print(
            f"\n[chaos-autoscaler] scale-up: {n} pods bound {elapsed:.2f}s "
            f"after capacity registered ({len(nodes)} nodes provisioned)",
            flush=True,
        )
    finally:
        auto.stop()
        sched.stop()
        hollow.stop()
        w.stop()


@pytest.mark.slow
def test_scale_down_drains_only_after_simulation_proves_replacement():
    """Underutilized node → drain simulation proves re-placement → cordon
    → rate-limited eviction → ReplicaSet recreates the pods → they re-bind
    on surviving nodes → empty node deleted + kubelet deprovisioned. The
    fleet converges to min_size with every replica bound."""
    store = ChaosStore()
    group = NodeGroup(
        name="pool",
        template=machine_shape(cpu="4", memory="32Gi", pods=32),
        min_size=2,
        max_size=8,
    )
    hollow, sched, auto = _rig(
        store,
        [group],
        period_s=0.2,
        scale_down_util_threshold=0.3,
        scale_down_unneeded_passes=2,
    )
    for i in range(3):
        hollow.add_node(f"pool-n{i}", template=group.make_node)
    rsc = ReplicaSetController(store, resync_period=0.5)
    rs = v1.ReplicaSet(
        metadata=v1.ObjectMeta(name="web"),
        spec=v1.ReplicaSetSpec(
            replicas=4,
            selector={"app": "web"},
            template=v1.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": "web"}),
                spec=v1.PodSpec(
                    containers=[v1.Container(requests={"cpu": "1"})]
                ),
            ),
        ),
    )
    store.create("replicasets", rs)
    hollow.start()
    sched.start()
    rsc.start()
    try:
        assert wait_until(lambda: _bound_count(store) == 4, 30), (
            f"RS pods never all bound: {_bound_count(store)}/4"
        )
        evictions0 = metrics.counter("autoscaler_evictions_total")
        auto.start()
        # at least one of the 3 nodes is under 30% cpu (4 pods can at
        # most fill two nodes half-full) — it must drain and disappear
        assert wait_until(lambda: store.count("nodes") == 2, 30), (
            f"fleet never converged to min_size: {store.count('nodes')} nodes"
        )
        # every replica re-bound on the survivors
        assert wait_until(
            lambda: _bound_count(store) == 4
            and all(
                p.spec.node_name != ""
                and _get_or_none(store, "nodes", "", p.spec.node_name)
                is not None
                for p in store.list("pods")[0]
            ),
            30,
        ), "replicas did not re-place on surviving nodes"
        assert metrics.counter("autoscaler_evictions_total") > evictions0
        assert (
            metrics.counter("autoscaler_nodes_removed_total", {"group": "pool"})
            >= 1.0
        )
        # min_size floor holds even though survivors are under-threshold
        time.sleep(1.0)
        assert store.count("nodes") == 2
        # evicted (deleted) RS pods are expected; bound-exactly-once and
        # zero acked-loss still hold for every live pod
        assert_bind_invariants(store, allow_deleted=True)
        # the drained node's hollow kubelet was torn down with it
        live_nodes = {n.metadata.name for n in store.list("nodes")[0]}
        assert set(hollow.nodes) == live_nodes
    finally:
        auto.stop()
        rsc.stop()
        sched.stop()
        hollow.stop()


def test_simulation_infeasible_node_is_never_drained():
    """Zero-eviction guarantee: a node whose resident pod can re-place
    NOWHERE (nodeSelector pins it) is never cordoned and never loses the
    pod, no matter how underutilized it is."""
    store = ChaosStore()
    pinned_shape = machine_shape(
        cpu="4", memory="32Gi", pods=32, labels={"pin": "yes"}
    )
    group = NodeGroup(
        name="pool",
        template=machine_shape(cpu="4", memory="32Gi", pods=32),
        min_size=0,
        max_size=8,
    )
    hollow, sched, auto = _rig(
        store,
        [group],
        period_s=0.1,
        scale_down_util_threshold=0.5,
        scale_down_unneeded_passes=2,
    )

    def pinned_template(name):
        node = pinned_shape(name)
        node.metadata.labels[v1.LABEL_NODEGROUP] = group.name
        return node

    hollow.add_node("pool-pinned", template=pinned_template)
    hollow.add_node("pool-other", template=group.make_node)
    # owner-ref'd (movable) but nodeSelector-pinned: only the simulation
    # can prove the drain is unsafe
    store.create(
        "pods",
        make_pod(
            "stuck",
            cpu="100m",
            node_selector={"pin": "yes"},
            owners=[v1.OwnerReference(kind="ReplicaSet", name="ghost")],
        ),
    )
    deletions = []
    w = _watch_deletions(store, deletions)
    blocked0 = metrics.counter(
        "autoscaler_scale_down_blocked_total",
        {"reason": "simulation_infeasible"},
    )
    hollow.start()
    sched.start()
    try:
        assert wait_until(lambda: _bound_count(store) == 1, 30)
        auto.start()
        # give the controller many passes to (wrongly) act
        assert wait_until(
            lambda: metrics.counter(
                "autoscaler_scale_down_blocked_total",
                {"reason": "simulation_infeasible"},
            )
            > blocked0,
            15,
        ), "drain simulation never evaluated the pinned node"
        time.sleep(1.0)
        node = _get_or_none(store, "nodes", "", "pool-pinned")
        assert node is not None, "infeasible node was deleted"
        assert not node.spec.unschedulable, "infeasible node was cordoned"
        assert not deletions, (
            f"zero-eviction guarantee broken: {deletions}"
        )
        # the empty OTHER node may legally drain (nothing resident)
        assert_bind_invariants(store)
    finally:
        auto.stop()
        sched.stop()
        hollow.stop()
        w.stop()


def test_degraded_store_pauses_provisioning_until_recovery():
    """PR-1/PR-3 discipline: a read-only store makes provisioning a
    counted skip, not a crash; scale-up completes once writes reopen."""
    store = ChaosStore()
    group = NodeGroup(
        name="std",
        template=machine_shape(cpu="4", memory="32Gi", pods=32),
        max_size=8,
    )
    hollow, sched, auto = _rig(
        store, [group], period_s=0.2, scale_down_enabled=False
    )
    for i in range(4):
        store.create("pods", make_pod(f"pend-{i}"))
    hollow.start()
    sched.start()
    try:
        assert wait_until(
            lambda: len(sched.queue.unschedulable_pod_infos()) == 4, 30
        )
        store.degrade()
        skips0 = metrics.counter(
            "autoscaler_degraded_write_skips_total", {"write": "provision"}
        )
        auto.start()
        assert wait_until(
            lambda: metrics.counter(
                "autoscaler_degraded_write_skips_total",
                {"write": "provision"},
            )
            > skips0,
            15,
        ), "degraded store never produced a counted provision skip"
        assert store.count("nodes") == 0, "provisioned against a read-only store"
        store.recover()
        assert wait_until(lambda: _bound_count(store) == 4, 20), (
            "scale-up never completed after store recovery"
        )
        assert_bind_invariants(store)
    finally:
        auto.stop()
        sched.stop()
        hollow.stop()
