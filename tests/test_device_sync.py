"""Regression guards for the round-5 device-sync work (ops/encoding.py):
per-field reshape upload on capacity growth, the two-pad chunked dirty-row
scatter, warm_scatter_programs, and the per-pod fingerprint memo's
vocab-epoch invalidation (ops/templates.py)."""

import jax
import numpy as np

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.selectors import LabelSelector
from kubernetes_tpu.kubelet.kubelet import make_node_object
from kubernetes_tpu.ops.encoding import EncodingConfig, SnapshotEncoder
from kubernetes_tpu.ops.templates import TemplateCache


def _enc(n_nodes=8, **overrides):
    enc = SnapshotEncoder(EncodingConfig(**overrides))
    for i in range(n_nodes):
        enc.add_node(make_node_object(f"n{i}", cpu="8"))
    return enc


def _pod(name, cpu="100m", labels=None):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, labels=labels or {}),
        spec=v1.PodSpec(containers=[v1.Container(requests={"cpu": cpu})]),
    )


def _masters_equal_device(enc):
    dev = jax.device_get(enc.flush())
    m = enc._masters()
    for f in ("requested", "sel_counts", "port_counts", "eterm_w", "alloc"):
        name = {"alloc": "allocatable"}.get(f, f)
        assert np.array_equal(
            np.asarray(getattr(dev, name)), np.asarray(getattr(m, name))
        ), name


def test_reshape_upload_keeps_unchanged_device_fields():
    """Capacity growth re-uploads ONLY the reshaped fields; untouched
    fields keep their existing device arrays (identity-preserved), and
    the result still equals the masters everywhere."""
    enc = _enc()
    enc.add_pod("n0", _pod("a"))
    dev0 = enc.flush()
    req0 = dev0.requested
    # grow ONLY the eterm capacity: eterm_w reshapes, requested must not
    enc._ensure_cap("t_cap", enc.cfg.t_cap * 2)
    assert enc._full_upload and not enc._content_invalid
    dev1 = enc.flush()
    assert dev1.eterm_w.shape[1] == enc.cfg.t_cap
    # identity: the requested array was NOT re-uploaded (no dirty rows)
    assert dev1.requested is req0
    _masters_equal_device(enc)


def test_content_invalid_forces_true_full_upload():
    enc = _enc()
    enc.add_pod("n0", _pod("a"))
    dev0 = enc.flush()
    enc.invalidate_device()
    dev1 = enc.flush()
    assert dev1.requested is not dev0.requested  # fresh upload
    _masters_equal_device(enc)


def test_scatter_chunking_handles_large_dirty_sets():
    """>1024 dirty rows chunk through the big pad and land exactly."""
    enc = _enc(n_nodes=1100)
    for i in range(1100):
        enc.add_pod(f"n{i}", _pod(f"p{i}"))  # dirties every row
    assert len(enc._dirty_rows) >= 1100
    enc.flush()  # first flush may be the full-upload path
    # now dirty a large set again against an existing device snapshot
    for i in range(1100):
        enc.add_pod(f"n{i}", _pod(f"q{i}"))
    assert len(enc._dirty_rows) >= 1100
    _masters_equal_device(enc)


def test_warm_scatter_programs_is_content_neutral():
    enc = _enc()
    enc.add_pod("n0", _pod("a"))
    enc.flush()
    before = jax.device_get(enc.flush())
    enc.warm_scatter_programs()
    after = jax.device_get(enc.flush())
    assert np.array_equal(
        np.asarray(before.requested), np.asarray(after.requested)
    )
    _masters_equal_device(enc)


def test_fingerprint_memo_invalidates_on_vocab_growth():
    """A memoized pod fingerprint must not survive vocab growth: a new
    service predicate changes the label-effect encoding, and a stale memo
    would collapse pods the new predicate distinguishes."""
    enc = _enc()
    tc = TemplateCache(enc)
    pods = [_pod(f"p{i}", labels={"app": "web"}) for i in range(4)]
    eb1 = tc.encode(pods, pad_to=4)
    t1 = eb1.num_templates
    # interning a service predicate that MATCHES the pods changes their
    # label-effect key -> epoch bump -> fingerprints recompute
    enc.register_service_predicate(
        "default", LabelSelector.make(match_labels={"app": "web"})
    )
    eb2 = tc.encode(pods, pad_to=4)
    assert eb2.num_templates >= 1
    # the template row must now carry the service-sid match
    sid_mask = enc.service_sid_mask()
    tpl = eb2.tpl_np
    assert bool(np.asarray(tpl.match_sel)[:, sid_mask.nonzero()[0]].any())
    # and re-encoding with NO vocab change hits the memo (same outputs)
    eb3 = tc.encode(pods, pad_to=4)
    assert eb3.num_templates == eb2.num_templates
    assert t1 >= 1
