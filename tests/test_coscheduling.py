"""Gang coscheduling: all-or-nothing placement on QueueSort+Permit.

The reference has no in-tree coscheduling — the plugin is built on the same
extension points (Permit WAIT + waitingPodsMap,
framework/v1alpha1/interface.go:211-499). These tests pin the contract:
quorum release, timeout rejection with resource release, and end-to-end
gang bursts through the full batched scheduler."""

import time


from kubernetes_tpu.api.objects import (
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.client.apiserver import APIServer
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler
from kubernetes_tpu.scheduler.config import ProfileConfig
from kubernetes_tpu.scheduler.framework.plugins.coscheduling import (
    GROUP_LABEL,
    MIN_MEMBER_ANNOTATION,
)
from kubernetes_tpu.scheduler.framework.registry import coscheduling_plugin_set


def make_node(name, cpu="4"):
    return Node(
        metadata=ObjectMeta(name=name),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={"cpu": cpu, "memory": "16Gi", "pods": 110}),
    )


def gang_pod(name, gang, min_member, cpu="500m"):
    return Pod(
        metadata=ObjectMeta(
            name=name,
            labels={GROUP_LABEL: gang},
            annotations={MIN_MEMBER_ANNOTATION: str(min_member)},
        ),
        spec=PodSpec(containers=[Container(requests={"cpu": cpu})]),
    )


def _gang_scheduler(server, permit_timeout=30.0):
    cfg = KubeSchedulerConfiguration(
        profiles=[ProfileConfig(plugin_set=coscheduling_plugin_set())],
        coscheduling_permit_timeout=permit_timeout,
    )
    return Scheduler(server, cfg)


def _wait_bound(server, n, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pods, _ = server.list("pods")
        if sum(1 for p in pods if p.spec.node_name) >= n:
            return True
        time.sleep(0.05)
    return False


def test_gang_binds_all_or_nothing_success():
    server = APIServer()
    for i in range(8):
        server.create("nodes", make_node(f"n{i}"))
    sched = _gang_scheduler(server)
    sched.start()
    try:
        for i in range(20):
            server.create("pods", gang_pod(f"g0-{i}", "g0", 20))
        assert _wait_bound(server, 20), "full gang must bind"
    finally:
        sched.stop()


def test_gang_short_of_quorum_releases_resources():
    """A gang that can never reach quorum must not hold reservations: after
    the permit timeout, every member is unreserved and a later non-gang pod
    can use the freed capacity."""
    server = APIServer()
    server.create("nodes", make_node("n0", cpu="2"))
    sched = _gang_scheduler(server, permit_timeout=1.0)
    sched.start()
    try:
        # quorum 8, but only 4x500m fits on the single 2-cpu node
        for i in range(8):
            server.create("pods", gang_pod(f"g1-{i}", "g1", 8))
        time.sleep(3.0)  # permit timeout + unreserve cascade
        pods, _ = server.list("pods")
        assert all(not p.spec.node_name for p in pods), "no partial gang binds"
        # freed capacity: a plain pod requesting the whole node must fit
        server.create(
            "pods",
            Pod(
                metadata=ObjectMeta(name="solo"),
                spec=PodSpec(containers=[Container(requests={"cpu": "2"})]),
            ),
        )
        deadline = time.time() + 30
        ok = False
        while time.time() < deadline:
            solo = server.get("pods", "default", "solo")
            if solo.spec.node_name:
                ok = True
                break
            time.sleep(0.05)
        assert ok, "gang reservations were not released"
    finally:
        sched.stop()


def test_gang_failed_member_rejects_siblings_promptly():
    """When one member hard-fails (no feasible node), parked siblings must
    release their reservations well before the permit timeout."""
    server = APIServer()
    server.create("nodes", make_node("n0", cpu="4"))
    # long permit timeout: if release relied on the timeout, the freed-
    # capacity check below would not pass within the poll window
    sched = _gang_scheduler(server, permit_timeout=120.0)
    sched.start()
    try:
        # 3 members fit; the 4th requests more cpu than any node has ->
        # hard failure -> failure hook must reject the parked 3
        for i in range(3):
            server.create("pods", gang_pod(f"g2-{i}", "g2", 4, cpu="1"))
        server.create("pods", gang_pod("g2-big", "g2", 4, cpu="64"))
        server.create(
            "pods",
            Pod(
                metadata=ObjectMeta(name="solo2"),
                spec=PodSpec(containers=[Container(requests={"cpu": "4"})]),
            ),
        )
        deadline = time.time() + 30
        ok = False
        while time.time() < deadline:
            solo = server.get("pods", "default", "solo2")
            if solo.spec.node_name:
                ok = True
                break
            time.sleep(0.05)
        assert ok, "gang reservations not released on member failure"
    finally:
        sched.stop()


def test_gang_members_pop_adjacent():
    """Coscheduling QueueSort keeps gang members adjacent so one device
    batch carries whole gangs."""
    from kubernetes_tpu.scheduler.framework.plugins.coscheduling import Coscheduling
    from kubernetes_tpu.scheduler.queue.scheduling_queue import (
        PriorityQueue,
        QueuedPodInfo,
    )

    plugin = Coscheduling()
    q = PriorityQueue(less=plugin.less)
    # interleave two gangs
    for i in range(4):
        for g in ("gb", "ga"):
            q.add(gang_pod(f"{g}-{i}", g, 4))
    popped = [pi.pod.metadata.labels[GROUP_LABEL] for pi in q.pop_batch(8)]
    assert popped == sorted(popped), f"gangs interleaved in pop order: {popped}"


def test_gang_burst_end_to_end():
    """A multi-gang burst (10 gangs x 20) lands all-or-nothing per gang."""
    server = APIServer()
    for i in range(40):
        server.create("nodes", make_node(f"n{i}", cpu="8"))
    sched = _gang_scheduler(server)
    sched.start()
    try:
        for g in range(10):
            for i in range(20):
                server.create("pods", gang_pod(f"g{g}-{i}", f"g{g}", 20))
        assert _wait_bound(server, 200, timeout=120), "all gangs must bind"
    finally:
        sched.stop()
