"""Conformance capstone: one secured cluster, every controller running,
the major API machine flows exercised together (the e2e/conformance tier,
reference test/conformance + test/e2e framework).

Flow: kubeadm init (secured REST + WAL + scheduler + ALL controllers) →
token join 3 worker nodes → Deployment rollout → Service with endpoints +
slices + proxy resolution → quota enforcement → CRD create/use → drain a
node under a PDB → everything converges.
"""

import json
import time
import urllib.request

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.apiserver.client import AuthRESTClient
from kubernetes_tpu.cmd.kubeadm import init_cluster, join_node


def wait_until(fn, timeout=90.0, period=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(period)
    return False


def test_conformance_end_to_end(tmp_path):
    handle = init_cluster(str(tmp_path / "conf"), port=0)
    try:
        conf = json.load(
            open(f"{handle.data_dir}/admin.conf.json")
        )
        admin = AuthRESTClient(conf["server"], token=conf["token"])
        for i in range(3):
            join_node(
                handle.server_url,
                handle.bootstrap_token,
                f"worker-{i}",
                handle=handle,
            )
        assert wait_until(lambda: len(admin.list("nodes")[0]) == 3)

        # -- node infra controllers: CIDРs + TTL annotations ---------------
        assert wait_until(
            lambda: all(
                n.spec.pod_cidr and "node.alpha.kubernetes.io/ttl" in
                n.metadata.annotations
                for n in admin.list("nodes")[0]
            )
        ), "nodeipam + ttl controllers must dress every node"

        # -- workload: Deployment -> ReplicaSet -> running pods ------------
        admin.create(
            "deployments",
            v1.Deployment(
                metadata=v1.ObjectMeta(name="web"),
                spec=v1.DeploymentSpec(
                    replicas=4,
                    selector={"app": "web"},
                    template=v1.PodTemplateSpec(
                        metadata=v1.ObjectMeta(labels={"app": "web"}),
                        spec=v1.PodSpec(
                            containers=[
                                v1.Container(requests={"cpu": "100m"})
                            ]
                        ),
                    ),
                ),
            ),
        )

        def web_running():
            pods, _ = admin.list("pods")
            mine = [
                p
                for p in pods
                if p.metadata.labels.get("app") == "web"
                and p.status.phase == v1.POD_RUNNING
                and p.status.pod_ip
            ]
            return len(mine) == 4

        assert wait_until(web_running), "deployment must converge to 4 running"

        # -- service dataplane: endpoints + slices + VIP resolution --------
        admin.create(
            "services",
            v1.Service(
                metadata=v1.ObjectMeta(name="web"),
                spec=v1.ServiceSpec(
                    selector={"app": "web"}, ports=[("http", 80)]
                ),
            ),
        )
        svc = admin.get("services", "default", "web")
        assert svc.spec.cluster_ip, "ClusterIP allocator must assign a VIP"

        def endpoints_ready():
            try:
                eps = admin.get("endpoints", "default", "web")
            except KeyError:
                return False
            n_ep = sum(len(s.addresses) for s in eps.subsets)
            slices, _ = admin.list("endpointslices")
            n_sl = sum(
                len(s.endpoints)
                for s in slices
                if s.metadata.labels.get("kubernetes.io/service-name") == "web"
            )
            return n_ep == 4 and n_sl == 4

        assert wait_until(endpoints_ready), "endpoints + slices must publish"
        # one of the joined node agents resolves the VIP
        pool = handle._joined[0]
        assert wait_until(
            lambda: pool.proxy.resolve(svc.spec.cluster_ip, "http") is not None
        ), "node proxy must route the service VIP"

        # -- quota: hard limit enforced through admission ------------------
        admin.create(
            "resourcequotas",
            v1.ResourceQuota(
                metadata=v1.ObjectMeta(name="cap"),
                spec=v1.ResourceQuotaSpec(hard={"pods": 5}),
            ),
        )

        def quota_tracked():
            q = admin.get("resourcequotas", "default", "cap")
            return q.status.used.get("pods") == 4

        assert wait_until(quota_tracked), "quota status must track usage"
        # the FIFTH pod is within the hard pods=5 limit and must be admitted
        admin.create(
            "pods",
            v1.Pod(
                metadata=v1.ObjectMeta(name="fifth"),
                spec=v1.PodSpec(containers=[v1.Container()]),
            ),
        )
        # the SIXTH trips the limit: a real boundary check, not just
        # "something was denied"
        denied = False
        try:
            admin.create(
                "pods",
                v1.Pod(
                    metadata=v1.ObjectMeta(name="sixth"),
                    spec=v1.PodSpec(containers=[v1.Container()]),
                ),
            )
        except urllib.error.HTTPError as e:
            denied = e.code == 403
        assert denied, "the pod over quota must be denied with 403"

        # -- CRDs: define + use a custom resource through the API ----------
        admin.create(
            "customresourcedefinitions",
            v1.CustomResourceDefinition(
                metadata=v1.ObjectMeta(name="gadgets.conf.io"),
                spec=v1.CustomResourceDefinitionSpec(
                    group="conf.io",
                    names=v1.CustomResourceDefinitionNames(
                        plural="gadgets", kind="Gadget"
                    ),
                ),
            ),
        )
        req = urllib.request.Request(
            f"{handle.server_url}/apis/conf.io/v1/namespaces/default/gadgets",
            data=json.dumps(
                {"kind": "Gadget", "metadata": {"name": "g1"}, "spec": {"x": 1}}
            ).encode(),
            method="POST",
            headers={
                "Content-Type": "application/json",
                "Authorization": f"Bearer {conf['token']}",
            },
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 201

        # -- disruption: PDB + drain one node, workload re-converges -------
        admin.create(
            "poddisruptionbudgets",
            v1.PodDisruptionBudget(
                metadata=v1.ObjectMeta(name="web-pdb"),
                spec=v1.PodDisruptionBudgetSpec(
                    max_unavailable=1, selector={"app": "web"}
                ),
            ),
        )
        assert wait_until(
            lambda: admin.get(
                "poddisruptionbudgets", "default", "web-pdb"
            ).status.disruptions_allowed
            >= 1
        ), "disruption controller must grant budget"
        from kubernetes_tpu.cmd import kubectl

        rc = kubectl.main(
            [
                "--server",
                handle.server_url,
                "--token",
                conf["token"],
                "drain",
                "worker-0",
                "--timeout",
                "60",
            ]
        )
        assert rc == 0, "drain must succeed within the PDB budget"
        assert wait_until(
            lambda: web_running()
            and all(
                p.spec.node_name != "worker-0"
                for p in admin.list("pods")[0]
                if p.metadata.labels.get("app") == "web"
            ),
            timeout=120,
        ), "drained workload must re-land on surviving nodes"
    finally:
        handle.stop()
