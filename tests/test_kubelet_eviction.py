"""Kubelet node-pressure eviction + QoS classes (kubelet/eviction.py;
reference pkg/kubelet/eviction/eviction_manager.go, helper/qos)."""

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client.apiserver import APIServer
from kubernetes_tpu.kubelet.eviction import (
    MEMORY_PRESSURE_TAINT,
    QOS_BEST_EFFORT,
    QOS_BURSTABLE,
    QOS_GUARANTEED,
    EvictionManager,
    qos_class,
)
from kubernetes_tpu.kubelet.kubelet import make_node_object


def _pod(name, mem=None, lim=None, prio=0, node="n0"):
    reqs = {"memory": mem} if mem else {}
    lims = {"memory": lim, "cpu": "1"} if lim else {}
    if lim and mem:
        reqs["cpu"] = "1"
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name),
        spec=v1.PodSpec(
            node_name=node,
            priority=prio,
            containers=[v1.Container(requests=reqs, limits=lims)],
        ),
        status=v1.PodStatus(phase=v1.POD_RUNNING),
    )


def test_qos_classes():
    assert qos_class(_pod("be")) == QOS_BEST_EFFORT
    assert qos_class(_pod("burst", mem="1Gi")) == QOS_BURSTABLE
    assert qos_class(_pod("guar", mem="1Gi", lim="1Gi")) == QOS_GUARANTEED


def test_eviction_ranks_best_effort_first_and_taints_node():
    server = APIServer()
    server.create("nodes", make_node_object("n0", memory="1Gi"))
    server.create("pods", _pod("guar", mem="512Mi", lim="512Mi", prio=100))
    server.create("pods", _pod("burst", mem="400Mi"))
    be = _pod("be")
    server.create("pods", be)
    # give the BestEffort pod synthetic usage so pressure exists
    usage = {
        "default/guar": 512 << 20,
        "default/burst": 400 << 20,
        "default/be": 200 << 20,
    }
    em = EvictionManager(
        server,
        "n0",
        memory_threshold_bytes=64 << 20,
        usage_fn=lambda p: usage.get(p.metadata.key, 0),
    )
    evicted = em.synchronize()
    assert evicted == ["default/be"], evicted  # BestEffort goes first
    p = server.get("pods", "default", "be")
    assert p.status.phase == v1.POD_FAILED and p.status.reason == "Evicted"
    node = server.get("nodes", "", "n0")
    assert any(
        c.type == "MemoryPressure" and c.status == "True"
        for c in node.status.conditions
    )
    assert any(t.key == MEMORY_PRESSURE_TAINT for t in node.spec.taints)

    # pressure clears once usage drops below threshold: condition + taint go
    usage["default/be"] = 0
    usage["default/burst"] = 0
    assert em.synchronize() == []
    node = server.get("nodes", "", "n0")
    assert any(
        c.type == "MemoryPressure" and c.status == "False"
        for c in node.status.conditions
    )
    assert not any(t.key == MEMORY_PRESSURE_TAINT for t in node.spec.taints)


def test_guaranteed_within_requests_evicted_last():
    server = APIServer()
    server.create("nodes", make_node_object("n0", memory="1Gi"))
    server.create("pods", _pod("guar", mem="900Mi", lim="900Mi", prio=1000))
    server.create("pods", _pod("burst-over", mem="64Mi"))
    usage = {"default/guar": 900 << 20, "default/burst-over": 120 << 20}
    em = EvictionManager(
        server,
        "n0",
        memory_threshold_bytes=64 << 20,
        usage_fn=lambda p: usage.get(p.metadata.key, 0),
    )
    # burst-over exceeds its request: it is the victim, not the bigger
    # guaranteed pod living within its requests
    assert em.synchronize() == ["default/burst-over"]


def test_container_manager_allocatable_and_cgroups():
    """pkg/kubelet/cm: allocatable = capacity - reservations; pod cgroup
    paths follow the /kubepods/{qos}/pod{uid} layout."""
    from kubernetes_tpu.kubelet.cm import ContainerManager

    cm = ContainerManager(
        system_reserved={"cpu": "500m", "memory": "512Mi"},
        kube_reserved={"cpu": "1", "memory": "1Gi"},
        eviction_hard_memory="100Mi",
    )
    alloc = cm.node_allocatable({"cpu": "8", "memory": "16Gi", "pods": "110"})
    assert alloc["cpu"] == "6500m"
    assert alloc["memory"] == str((16 << 30) - (512 << 20) - (1 << 30) - (100 << 20))
    assert alloc["pods"] == "110"

    guar = _pod("g", mem="1Gi", lim="1Gi")
    guar.metadata.uid = "u1"
    assert cm.pod_cgroup(guar) == "/kubepods/podu1"
    burst = _pod("b", mem="1Gi")
    burst.metadata.uid = "u2"
    assert cm.pod_cgroup(burst) == "/kubepods/burstable/podu2"
    be = _pod("e")
    be.metadata.uid = "u3"
    assert cm.pod_cgroup(be) == "/kubepods/besteffort/podu3"


def test_kubelet_posts_reserved_allocatable():
    """The kubelet posts allocatable = capacity - reservations to the
    node status (the Node Allocatable KEP), so the scheduler packs
    against reserved-aware capacity."""
    from kubernetes_tpu.api.resources import cpu_to_millis
    from kubernetes_tpu.client.apiserver import APIServer
    from kubernetes_tpu.kubelet.cm import ContainerManager
    from kubernetes_tpu.kubelet.kubelet import Kubelet, make_node_object
    from kubernetes_tpu.kubelet.runtime import FakeRuntime
    from kubernetes_tpu.kubemark.hollow_node import _fake_pod_ip

    server = APIServer()
    server.create("nodes", make_node_object("n0", cpu="8"))
    kl = Kubelet(server, "n0", FakeRuntime(_fake_pod_ip))
    kl.container_manager = ContainerManager(kube_reserved={"cpu": "1"})
    kl.sync_node_allocatable()
    node = server.get("nodes", "", "n0")
    assert cpu_to_millis(node.status.allocatable["cpu"]) == 7000
    assert cpu_to_millis(node.status.capacity["cpu"]) == 8000
