"""Kubelet node-pressure eviction + QoS classes (kubelet/eviction.py;
reference pkg/kubelet/eviction/eviction_manager.go, helper/qos)."""

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client.apiserver import APIServer
from kubernetes_tpu.kubelet.eviction import (
    MEMORY_PRESSURE_TAINT,
    QOS_BEST_EFFORT,
    QOS_BURSTABLE,
    QOS_GUARANTEED,
    EvictionManager,
    qos_class,
)
from kubernetes_tpu.kubelet.kubelet import make_node_object


def _pod(name, mem=None, lim=None, prio=0, node="n0"):
    reqs = {"memory": mem} if mem else {}
    lims = {"memory": lim, "cpu": "1"} if lim else {}
    if lim and mem:
        reqs["cpu"] = "1"
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name),
        spec=v1.PodSpec(
            node_name=node,
            priority=prio,
            containers=[v1.Container(requests=reqs, limits=lims)],
        ),
        status=v1.PodStatus(phase=v1.POD_RUNNING),
    )


def test_qos_classes():
    assert qos_class(_pod("be")) == QOS_BEST_EFFORT
    assert qos_class(_pod("burst", mem="1Gi")) == QOS_BURSTABLE
    assert qos_class(_pod("guar", mem="1Gi", lim="1Gi")) == QOS_GUARANTEED


def test_eviction_ranks_best_effort_first_and_taints_node():
    server = APIServer()
    server.create("nodes", make_node_object("n0", memory="1Gi"))
    server.create("pods", _pod("guar", mem="512Mi", lim="512Mi", prio=100))
    server.create("pods", _pod("burst", mem="400Mi"))
    be = _pod("be")
    server.create("pods", be)
    # give the BestEffort pod synthetic usage so pressure exists
    usage = {
        "default/guar": 512 << 20,
        "default/burst": 400 << 20,
        "default/be": 200 << 20,
    }
    em = EvictionManager(
        server,
        "n0",
        memory_threshold_bytes=64 << 20,
        usage_fn=lambda p: usage.get(p.metadata.key, 0),
    )
    evicted = em.synchronize()
    assert evicted == ["default/be"], evicted  # BestEffort goes first
    p = server.get("pods", "default", "be")
    assert p.status.phase == v1.POD_FAILED and p.status.reason == "Evicted"
    node = server.get("nodes", "", "n0")
    assert any(
        c.type == "MemoryPressure" and c.status == "True"
        for c in node.status.conditions
    )
    assert any(t.key == MEMORY_PRESSURE_TAINT for t in node.spec.taints)

    # pressure clears once usage drops below threshold: condition + taint go
    usage["default/be"] = 0
    usage["default/burst"] = 0
    assert em.synchronize() == []
    node = server.get("nodes", "", "n0")
    assert any(
        c.type == "MemoryPressure" and c.status == "False"
        for c in node.status.conditions
    )
    assert not any(t.key == MEMORY_PRESSURE_TAINT for t in node.spec.taints)


def test_guaranteed_within_requests_evicted_last():
    server = APIServer()
    server.create("nodes", make_node_object("n0", memory="1Gi"))
    server.create("pods", _pod("guar", mem="900Mi", lim="900Mi", prio=1000))
    server.create("pods", _pod("burst-over", mem="64Mi"))
    usage = {"default/guar": 900 << 20, "default/burst-over": 120 << 20}
    em = EvictionManager(
        server,
        "n0",
        memory_threshold_bytes=64 << 20,
        usage_fn=lambda p: usage.get(p.metadata.key, 0),
    )
    # burst-over exceeds its request: it is the victim, not the bigger
    # guaranteed pod living within its requests
    assert em.synchronize() == ["default/burst-over"]
