"""Preemption with PDB budgets + the batched device what-if mask.

Covers VERDICT r1 item 5: PDB counts are real (disruption controller
publishes disruptionsAllowed; pickOneNodeForPreemption's first criterion),
and the device what-if mask is validated against the host reprieve loop
(optimistic: never excludes a node the host path could use)."""

import time

import numpy as np
import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client import APIServer
from kubernetes_tpu.controller.disruption import DisruptionController
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler
from kubernetes_tpu.scheduler.preemption import (
    filter_pods_with_pdb_violation,
    pick_one_node_for_preemption,
)


def make_node(name, cpu="4", labels=None):
    return v1.Node(
        metadata=v1.ObjectMeta(name=name, namespace="", labels=labels or {}),
        status=v1.NodeStatus(
            allocatable={"cpu": cpu, "memory": "32Gi", "pods": 110}
        ),
    )


def make_pod(name, cpu="100m", prio=0, labels=None):
    p = v1.Pod(
        metadata=v1.ObjectMeta(name=name, labels=labels or {}),
        spec=v1.PodSpec(
            containers=[v1.Container(requests={"cpu": cpu})], priority=prio
        ),
    )
    return p


def wait_until(fn, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.03)
    return False


def test_filter_pods_with_pdb_violation_budget_countdown():
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="b"),
        spec=v1.PodDisruptionBudgetSpec(min_available=1, selector={"app": "a"}),
        status=v1.PodDisruptionBudgetStatus(disruptions_allowed=1),
    )
    pods = [make_pod(f"p{i}", labels={"app": "a"}) for i in range(3)]
    other = make_pod("other", labels={"app": "b"})
    violating, ok = filter_pods_with_pdb_violation(pods + [other], [pdb])
    # budget 1: first matching pod consumes it, the rest violate
    assert [p.metadata.name for p in ok] == ["p0", "other"]
    assert [p.metadata.name for p in violating] == ["p1", "p2"]


def test_filter_pods_with_pdb_violation_overlapping_budgets():
    """A pod matching SEVERAL PDBs consumes budget from each in list
    order, and one exhausted budget among its matches is enough to mark
    it violating (the any() rule) — the reference's per-PDB countdown,
    previously untested for the overlap case."""
    pdb_a = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="a"),
        spec=v1.PodDisruptionBudgetSpec(min_available=1, selector={"app": "a"}),
        status=v1.PodDisruptionBudgetStatus(disruptions_allowed=2),
    )
    pdb_tier = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="tier"),
        spec=v1.PodDisruptionBudgetSpec(
            min_available=1, selector={"tier": "gold"}
        ),
        status=v1.PodDisruptionBudgetStatus(disruptions_allowed=1),
    )
    both = [
        make_pod(f"b{i}", labels={"app": "a", "tier": "gold"})
        for i in range(2)
    ]
    only_a = make_pod("only-a", labels={"app": "a"})
    # b0 consumes a budget unit from BOTH pdbs; b1 then violates `tier`
    # (exhausted) even though `app: a` still has budget; only-a passes on
    # a's remaining unit
    violating, ok = filter_pods_with_pdb_violation(
        both + [only_a], [pdb_a, pdb_tier]
    )
    assert [p.metadata.name for p in ok] == ["b0", "only-a"]
    assert [p.metadata.name for p in violating] == ["b1"]
    # list order decides WHO gets the budget: reversed candidates flip
    # the survivor — pinning the reference's order-dependent countdown
    violating_r, ok_r = filter_pods_with_pdb_violation(
        [both[1], both[0], only_a], [pdb_a, pdb_tier]
    )
    assert [p.metadata.name for p in ok_r] == ["b1", "only-a"]
    assert [p.metadata.name for p in violating_r] == ["b0"]


def test_active_queue_equal_priority_fifo_tie_break():
    """activeQ orders by priority DESC then admission FIFO: equal-priority
    pods must pop in arrival order (the -timestamp half of the default
    less function, previously untested)."""
    from kubernetes_tpu.scheduler.queue.scheduling_queue import PriorityQueue

    q = PriorityQueue()
    for i in range(5):
        p = make_pod(f"fifo-{i}", prio=7)
        q.add(p)
        time.sleep(0.002)  # monotonic timestamps must strictly order
    late_high = make_pod("late-high", prio=50)
    q.add(late_high)
    order = [q.pop(timeout=1).pod.metadata.name for _ in range(6)]
    assert order[0] == "late-high"  # priority beats arrival
    assert order[1:] == [f"fifo-{i}" for i in range(5)]  # FIFO within tier
    q.close()


def test_pick_one_node_prefers_fewest_pdb_violations():
    victims = {
        "a": [make_pod("v1", prio=0)],
        "b": [make_pod("v2", prio=0)],
    }
    # b has no violations, a has one -> pick b despite identical victims
    assert (
        pick_one_node_for_preemption(victims, None, {"a": 1, "b": 0}) == "b"
    )


@pytest.mark.parametrize("use_device", [True, False])
def test_preemption_respects_pdb_node_choice(use_device):
    """Two full nodes each hold a preemptable victim; the victim on node A
    is PDB-protected (disruptionsAllowed=0). The preemptor must evict from
    node B."""
    server = APIServer()
    sched = Scheduler(server, KubeSchedulerConfiguration(use_device=use_device))
    disruption = DisruptionController(server)
    server.create("nodes", make_node("node-a", cpu="2"))
    server.create("nodes", make_node("node-b", cpu="2"))
    sched.start()
    disruption.start()
    try:
        server.create(
            "pods", make_pod("protected", cpu="1500m", labels={"app": "quorum"})
        )
        server.create(
            "pods", make_pod("expendable", cpu="1500m", labels={"app": "batch"})
        )
        assert wait_until(
            lambda: all(
                p.spec.node_name for p in server.list("pods")[0]
            )
        )
        # mark both Running so the disruption controller counts them healthy
        for p in server.list("pods")[0]:
            def run(cur):
                cur.status.phase = v1.POD_RUNNING
                return cur

            server.guaranteed_update(
                "pods", p.metadata.namespace, p.metadata.name, run
            )
        server.create(
            "poddisruptionbudgets",
            v1.PodDisruptionBudget(
                metadata=v1.ObjectMeta(name="quorum-pdb"),
                spec=v1.PodDisruptionBudgetSpec(
                    min_available=1, selector={"app": "quorum"}
                ),
            ),
        )
        assert wait_until(
            lambda: server.get(
                "poddisruptionbudgets", "default", "quorum-pdb"
            ).status.observed_generation
            >= 0
            and server.get(
                "poddisruptionbudgets", "default", "quorum-pdb"
            ).status.current_healthy
            == 1
        )
        # high-priority pod needs a full node; only eviction helps
        server.create("pods", make_pod("urgent", cpu="1500m", prio=1000))
        assert wait_until(
            lambda: (server.get("pods", "default", "urgent").spec.node_name != "")
        ), [
            (p.metadata.name, p.spec.node_name)
            for p in server.list("pods")[0]
        ]
        names = {p.metadata.name for p in server.list("pods")[0]}
        assert "protected" in names, "PDB-protected pod was evicted"
        assert "expendable" not in names, "wrong victim chosen"
    finally:
        disruption.stop()
        sched.stop()


def test_device_whatif_mask_is_optimistic_superset():
    """preempt_whatif must never exclude a node where the host reprieve loop
    would find victims (false positives allowed, false negatives not)."""
    import jax

    from kubernetes_tpu.ops.batch import encode_pod_batch
    from kubernetes_tpu.ops.encoding import SnapshotEncoder
    from kubernetes_tpu.ops.lattice import preempt_whatif

    rng = np.random.RandomState(7)
    enc = SnapshotEncoder()
    nodes = []
    for i in range(16):
        n = make_node(f"n{i}", cpu="2")
        nodes.append(n)
        enc.add_node(n)
    # random low-prio load
    placed = []
    for i in range(40):
        p = make_pod(f"low{i}", cpu=f"{rng.randint(2, 9)*100}m", prio=int(rng.randint(0, 3)))
        node = f"n{rng.randint(0, 16)}"
        p.spec.node_name = node
        enc.add_pod(node, p)
        placed.append((node, p))

    pending = [
        make_pod(f"hi{i}", cpu="1800m", prio=10) for i in range(4)
    ]
    eb = encode_pod_batch(enc, pending, pad_to=4)
    snap = enc.flush()
    mask = np.asarray(
        preempt_whatif(snap, eb.batch, eb.batch.priority)
    )

    # host oracle: for each (pod, node), remove ALL lower-prio pods and
    # check resource fit — exactly the kernel's claim
    from kubernetes_tpu.api.objects import compute_pod_resource_request

    for pi, pod in enumerate(pending):
        preq = compute_pod_resource_request(pod)
        for ni, node in enumerate(nodes):
            name = node.metadata.name
            alloc = node.allocatable()
            kept = [
                p
                for (nn, p) in placed
                if nn == name and p.priority >= pod.priority
            ]
            used = {"cpu": 0, "pods": len(kept)}
            for p in kept:
                used["cpu"] += compute_pod_resource_request(p)["cpu"]
            fits = (
                preq["cpu"] <= alloc["cpu"] - used["cpu"]
                and 1 <= alloc["pods"] - used["pods"]
            )
            had_victims = any(
                nn == name and p.priority < pod.priority for (nn, p) in placed
            )
            host_would_succeed = fits and had_victims
            if host_would_succeed:
                assert mask[pi, ni], (
                    f"what-if mask excluded viable node {name} for {pod.metadata.name}"
                )


def test_preemption_policy_never_blocks_preemption():
    """A PriorityClass with preemptionPolicy=Never yields high priority
    WITHOUT the right to evict (admission.go + podEligibleToPreemptOthers):
    the pod queues ahead but never takes victims."""
    from kubernetes_tpu.apiserver.auth import AdmissionChain, PriorityAdmission

    server = APIServer()
    server.create(
        "priorityclasses",
        v1.PriorityClass(
            metadata=v1.ObjectMeta(name="polite-high", namespace=""),
            value=100000,
            preemption_policy="Never",
        ),
    )
    server.admit_hooks.append(
        AdmissionChain(mutating=[PriorityAdmission(server)])
    )
    sched = Scheduler(server, KubeSchedulerConfiguration())
    server.create("nodes", make_node("only", cpu="2"))
    sched.start()
    try:
        low = make_pod("low", cpu="1500m")
        low.spec.priority = 0
        server.create("pods", low)
        deadline = time.time() + 30
        while time.time() < deadline:
            if server.get("pods", "default", "low").spec.node_name:
                break
            time.sleep(0.03)
        assert server.get("pods", "default", "low").spec.node_name == "only"

        polite = make_pod("polite", cpu="1500m")
        polite.spec.priority_class_name = "polite-high"
        server.create("pods", polite)
        stored = server.get("pods", "default", "polite")
        assert stored.spec.priority == 100000
        assert stored.spec.preemption_policy == "Never"
        time.sleep(2.0)
        # the victim survives and the polite pod stays pending
        names = {p.metadata.name for p in server.list("pods")[0]}
        assert "low" in names, "Never-policy pod must not evict"
        assert server.get("pods", "default", "polite").spec.node_name == ""
    finally:
        sched.stop()
