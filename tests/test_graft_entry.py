"""Driver-artifact regression tests (VERDICT r1 item 1).

Round 1 shipped with dryrun_multichip hanging on TPU backend init — these
tests pin the contract: entry() must lower under jit single-device, and
dryrun_multichip(8) must complete on the virtual CPU mesh.
"""

import pathlib
import subprocess
import sys

import jax

REPO = str(pathlib.Path(__file__).resolve().parents[1])


def test_entry_lowers():
    sys.path.insert(0, REPO)
    import __graft_entry__ as g

    fn, args = g.entry()
    jax.jit(fn).lower(*args)  # compile-check without executing


def test_dryrun_multichip_8():
    # run in a subprocess with a hard timeout: the round-1 failure mode was
    # a hang, which an in-process call would propagate to the whole suite
    r = subprocess.run(
        [
            sys.executable,
            "-c",
            f"import sys; sys.path.insert(0, {REPO!r}); "
            "import __graft_entry__ as g; g.dryrun_multichip(8)",
        ],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "scheduled" in r.stdout
