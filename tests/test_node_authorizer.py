"""Node authorizer (apiserver/nodeauth.py; reference
plugin/pkg/auth/authorizer/node/node_authorizer.go): a kubelet identity
is scoped to its own node's objects — kubelet A cannot bind/patch pods on
node B (r4 verdict #8)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.cmd.kubeadm import init_cluster, join_node


def _req(port, path, token, method="GET", body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method,
        headers={
            "Authorization": f"Bearer {token}",
            "Content-Type": "application/json",
        },
    )
    with urllib.request.urlopen(req, timeout=10.0) as r:
        return r.status, json.loads(r.read() or b"{}")


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("nodeauth")
    handle = init_cluster(
        str(tmp / "c"),
        controllers=["bootstrapsigner", "csrapproving", "csrsigning"],
    )
    try:
        join_node(
            handle.server_url, handle.bootstrap_token, "node-a", handle=handle
        )
        join_node(
            handle.server_url, handle.bootstrap_token, "node-b", handle=handle
        )
        # node credentials signed by the CSR controllers
        creds = {}
        deadline = time.time() + 15.0
        while time.time() < deadline and len(creds) < 2:
            for n in ("node-a", "node-b"):
                try:
                    csr = handle.store.get(
                        "certificatesigningrequests", "", f"node-csr-{n}"
                    )
                    if csr.status.certificate:
                        creds[n] = csr.status.certificate
                except Exception:
                    pass
            time.sleep(0.1)
        assert len(creds) == 2, "node credentials never issued"
        # one pod bound to each node (created by the admin store directly)
        for n in ("node-a", "node-b"):
            handle.store.create(
                "pods",
                v1.Pod(
                    metadata=v1.ObjectMeta(name=f"pod-{n}"),
                    spec=v1.PodSpec(
                        node_name=n,
                        containers=[v1.Container(requests={"cpu": "100m"})],
                        volumes=[
                            v1.Volume(name="s", secret=f"secret-{n}")
                        ],
                    ),
                ),
            )
            handle.store.create(
                "secrets",
                v1.Secret(
                    metadata=v1.ObjectMeta(name=f"secret-{n}"),
                    data={"k": b"v"},
                ),
            )
        yield handle, creds
    finally:
        handle.stop()


def test_kubelet_cannot_patch_pods_on_other_node(cluster):
    handle, creds = cluster
    # kubelet A updating ITS pod's status: allowed
    status, body = _req(
        handle.port,
        "/api/v1/namespaces/default/pods/pod-node-a",
        creds["node-a"],
    )
    assert status == 200
    body.setdefault("status", {})["message"] = "from-node-a"
    body["metadata"]["resourceVersion"] = 0  # unconditional PUT
    status, _ = _req(
        handle.port,
        "/api/v1/namespaces/default/pods/pod-node-a",
        creds["node-a"],
        method="PUT",
        body=body,
    )
    assert status == 200
    # kubelet A updating a pod bound to node B: 403
    status, other = _req(
        handle.port,
        "/api/v1/namespaces/default/pods/pod-node-b",
        creds["node-a"],
    )
    assert status == 200  # reads allowed (informer surface)
    other.setdefault("status", {})["message"] = "hijack"
    other["metadata"]["resourceVersion"] = 0
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(
            handle.port,
            "/api/v1/namespaces/default/pods/pod-node-b",
            creds["node-a"],
            method="PUT",
            body=other,
        )
    assert ei.value.code == 403


def test_kubelet_cannot_bind_pods(cluster):
    handle, creds = cluster
    handle.store.create(
        "pods",
        v1.Pod(
            metadata=v1.ObjectMeta(name="unbound"),
            spec=v1.PodSpec(containers=[v1.Container()]),
        ),
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(
            handle.port,
            "/api/v1/namespaces/default/pods/unbound/binding",
            creds["node-a"],
            method="POST",
            body={"target": {"name": "node-a"}, "metadata": {"name": "unbound"}},
        )
    assert ei.value.code == 403


def test_kubelet_cannot_write_other_node_object(cluster):
    handle, creds = cluster
    status, nb = _req(handle.port, "/api/v1/nodes/node-b", creds["node-a"])
    assert status == 200
    nb["metadata"]["resourceVersion"] = 0
    nb.setdefault("spec", {})["unschedulable"] = True
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(
            handle.port,
            "/api/v1/nodes/node-b",
            creds["node-a"],
            method="PUT",
            body=nb,
        )
    assert ei.value.code == 403


def test_kubelet_secret_access_follows_pod_graph(cluster):
    handle, creds = cluster
    # secret referenced by A's pod: readable by A
    status, _ = _req(
        handle.port,
        "/api/v1/namespaces/default/secrets/secret-node-a",
        creds["node-a"],
    )
    assert status == 200
    # secret referenced only by B's pod: 403 for A
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(
            handle.port,
            "/api/v1/namespaces/default/secrets/secret-node-b",
            creds["node-a"],
        )
    assert ei.value.code == 403
