"""Native group-commit WAL sink (kubernetes_tpu/native/walsink.cpp).

The reference's durability layer (etcd) group-commits raft appends — many
proposals, one fsync. These tests pin: correctness of the native path
(records recoverable, compaction rotation survives), the group-commit win
(a bulk bind's N records cost far fewer than N fsyncs), and the pure
Python fallback staying equivalent."""

import os
import time

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client import APIServer
from kubernetes_tpu.native import load_walsink
from kubernetes_tpu.runtime.wal import WriteAheadLog


def make_pod(name):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name),
        spec=v1.PodSpec(containers=[v1.Container(requests={"cpu": "100m"})]),
    )


def test_native_sink_builds_and_roundtrips(tmp_path):
    if load_walsink() is None:
        pytest.skip("no native toolchain")
    path = str(tmp_path / "n")
    wal = WriteAheadLog(path)
    assert wal.native, "native sink must load where g++ exists"
    server = APIServer(wal=wal)
    for i in range(50):
        server.create("pods", make_pod(f"p{i}"))
    wal.close()
    recovered = APIServer.recover(path)
    pods, _ = recovered.list("pods")
    assert len(pods) == 50


def test_bulk_bind_group_commits(tmp_path):
    if load_walsink() is None:
        pytest.skip("no native toolchain")
    path = str(tmp_path / "g")
    wal = WriteAheadLog(path)
    server = APIServer(wal=wal)
    n = 256
    for i in range(n):
        server.create("pods", make_pod(f"b{i}"))
    before = wal.fsync_count()
    bindings = [
        v1.Binding(pod_name=f"b{i}", pod_namespace="default", target_node="n0")
        for i in range(n)
    ]
    errs = server.bind_pods(bindings)
    assert all(e is None for e in errs)
    extra = wal.fsync_count() - before
    # one enqueue burst -> the committer batches; allow a little slack for
    # scheduling but require the collapse to be dramatic
    assert extra <= 8, f"{extra} fsyncs for a {n}-record bulk bind"
    wal.close()
    recovered = APIServer.recover(path)
    pods, _ = recovered.list("pods")
    assert sum(1 for p in pods if p.spec.node_name == "n0") == n


def test_compaction_rotation_with_native_sink(tmp_path):
    if load_walsink() is None:
        pytest.skip("no native toolchain")
    path = str(tmp_path / "c")
    wal = WriteAheadLog(path, compact_every=20)
    server = APIServer(wal=wal)
    for i in range(75):
        server.create("pods", make_pod(f"c{i}"))
    deadline = time.time() + 30
    while time.time() < deadline:
        if os.path.exists(path + ".snapshot.json"):
            break
        time.sleep(0.05)
    assert os.path.exists(path + ".snapshot.json")
    # appends after rotation still land
    server.create("pods", make_pod("after-rotate"))
    wal.close()
    recovered = APIServer.recover(path)
    pods, _ = recovered.list("pods")
    assert len(pods) == 76


def test_python_fallback_equivalence(tmp_path, monkeypatch):
    """Force the fallback and require identical WAL semantics."""
    import kubernetes_tpu.runtime.wal as wal_mod

    monkeypatch.setattr(
        "kubernetes_tpu.native.load_walsink", lambda: None
    )
    # wal.py imports load_walsink inside _open_sink via the package — patch
    # there too for safety
    path = str(tmp_path / "f")
    wal = wal_mod.WriteAheadLog(path)
    if wal.native:
        pytest.skip("monkeypatch did not take (import binding)")
    server = APIServer(wal=wal)
    for i in range(10):
        server.create("pods", make_pod(f"f{i}"))
    wal.close()
    recovered = APIServer.recover(path)
    pods, _ = recovered.list("pods")
    assert len(pods) == 10
