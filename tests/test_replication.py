"""Replicated API store: sync log shipping, lease failover, term fencing
(runtime/replication.py; reference: etcd raft behind storage.Interface,
staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:1)."""

import json
import socket
import threading
import time

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client.apiserver import APIServer, NotPrimary
from kubernetes_tpu.runtime.replication import Follower, ReplicationListener


def _pod(name, node=""):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name),
        spec=v1.PodSpec(
            node_name=node, containers=[v1.Container(requests={"cpu": "100m"})]
        ),
    )


def _mk_pair(lease_s=0.6):
    primary = APIServer()
    listener = ReplicationListener(heartbeat_s=0.1)
    listener.attach(primary)
    follower = Follower(listener.address, lease_s=lease_s).start()
    assert follower.wait_synced(5.0)
    return primary, listener, follower


def test_follower_receives_snapshot_and_live_stream():
    primary = APIServer()
    primary.create("pods", _pod("pre-existing"))
    listener = ReplicationListener(heartbeat_s=0.1)
    listener.attach(primary)
    follower = Follower(listener.address, lease_s=30.0).start()
    assert follower.wait_synced(5.0)
    assert "pre-existing" in {
        k.split("/")[-1] for k in follower.objects.get("pods", {})
    }
    primary.create("pods", _pod("live"))
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if any(k.endswith("/live") for k in follower.objects.get("pods", {})):
            break
        time.sleep(0.01)
    else:
        pytest.fail("live record never replicated")
    listener.close()
    follower.stop()


def test_chaos_kill_primary_mid_burst_no_acked_write_lost():
    """The VERDICT r3 'done' bar: kill the primary mid-burst, the follower
    promotes, and every write the client saw acknowledged is present on
    the promoted server."""
    primary, listener, follower = _mk_pair(lease_s=0.5)
    acked = []
    dead = threading.Event()

    def writer():
        i = 0
        while not dead.is_set() and i < 500:
            name = f"burst-{i}"
            try:
                primary.create("pods", _pod(name))
            except Exception:
                break  # primary died mid-call: write was NOT acknowledged
            acked.append(name)
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    # kill mid-burst, but only once the burst is real: a fixed sleep
    # under-shoots on a loaded machine (writes pace at follower-ack speed)
    deadline = time.monotonic() + 10.0
    while len(acked) < 20 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(acked) >= 20, "burst never got going"
    listener.close()  # kill -9 the primary's replication + service
    dead.set()
    t.join()

    # lease lapses -> promotion (automatic via the monitor thread)
    deadline = time.monotonic() + 5.0
    while follower.promoted is None and time.monotonic() < deadline:
        time.sleep(0.02)
    promoted = follower.promoted
    assert promoted is not None, "follower never promoted"
    have = set(promoted._objects.get("pods", {}))
    missing = [n for n in acked if f"default/{n}" not in have]
    assert not missing, f"acknowledged writes lost: {missing[:5]}…"


def test_higher_term_fences_old_primary():
    primary, listener, follower = _mk_pair(lease_s=30.0)
    # a successor (term 2) introduces itself: the old primary must fence
    sock = socket.create_connection(listener.address, timeout=5.0)
    f = sock.makefile("rwb")
    f.write((json.dumps({"hello": {"rv": 0, "term": 2}}) + "\n").encode())
    f.flush()
    resp = json.loads(f.readline())
    assert resp == {"fence": 2}
    deadline = time.monotonic() + 2.0
    while not primary.read_only and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(NotPrimary):
        primary.create("pods", _pod("rejected"))
    sock.close()
    listener.close()
    follower.stop()


def test_promoted_server_serves_scheduler_relist_and_converges():
    """After failover the scheduler re-lists against the promoted server
    and schedules new work (SURVEY §5 failure recovery)."""
    primary, listener, follower = _mk_pair(lease_s=30.0)
    for i in range(3):
        primary.create(
            "nodes",
            v1.Node(
                metadata=v1.ObjectMeta(name=f"n{i}", namespace=""),
                status=v1.NodeStatus(
                    capacity={"cpu": "4", "memory": "8Gi", "pods": "110"}
                ),
            ),
        )
    primary.create("pods", _pod("before-failover"))
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and follower.rv < primary._rv:
        time.sleep(0.01)
    listener.close()
    promoted = follower.promote()
    assert promoted._rv == follower.rv

    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.scheduler.config import KubeSchedulerConfiguration

    sched = Scheduler(promoted, KubeSchedulerConfiguration(use_mesh=False))
    sched.start()
    try:
        promoted.create("pods", _pod("after-failover"))
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            scheduled = promoted.count(
                "pods", lambda p: bool(p.spec.node_name)
            )
            if scheduled >= 2:
                break
            time.sleep(0.05)
        else:
            pytest.fail("scheduler did not converge on the promoted server")
    finally:
        sched.stop()
        follower.stop()


def test_replication_survives_follower_death():
    """A dead follower must not stall the primary's write path (it is
    dropped after ack_timeout, etcd-style ejection from the critical path)."""
    primary = APIServer()
    listener = ReplicationListener(heartbeat_s=0.1, ack_timeout_s=0.3)
    listener.attach(primary)
    follower = Follower(listener.address, lease_s=30.0).start()
    assert follower.wait_synced(5.0)
    follower.stop()  # stops acking (socket stays half-open briefly)
    t0 = time.monotonic()
    for i in range(3):
        primary.create("pods", _pod(f"alone-{i}"))
    assert time.monotonic() - t0 < 5.0
    assert primary.count("pods") == 3
    listener.close()


def test_kubeadm_ha_standby_promotes_full_control_plane(tmp_path):
    """kubeadm init --with-replication + standby: kill the primary, the
    standby promotes into a LIVE control plane (REST + scheduler +
    controllers on the replicated state) and schedules new work."""
    from kubernetes_tpu.cmd.kubeadm import init_cluster, standby_cluster

    primary = init_cluster(
        str(tmp_path / "primary"), controllers=[], replication=True
    )
    standby = None
    try:
        assert primary.replication_address is not None
        standby = standby_cluster(
            primary.replication_address,
            str(tmp_path / "standby"),
            lease_s=0.6,
            controllers=[],
            admin_token=primary.admin_token,
        )
        assert standby.follower.wait_synced(10.0)
        # state written before the failover...
        primary.store.create(
            "nodes",
            v1.Node(
                metadata=v1.ObjectMeta(name="n0", namespace=""),
                status=v1.NodeStatus(
                    capacity={"cpu": "8", "memory": "16Gi", "pods": "110"},
                    allocatable={"cpu": "8", "memory": "16Gi", "pods": "110"},
                    conditions=[
                        v1.NodeCondition(type=v1.NODE_READY, status="True")
                    ],
                ),
            ),
        )
        deadline = time.monotonic() + 10.0
        while (
            standby.follower.rv < primary.store._rv
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        # the primary dies
        primary.stop()
        assert standby.wait_promoted(15.0), "standby never promoted"
        cluster = standby.cluster
        # ...survives, and NEW work schedules on the promoted plane
        cluster.store.create("pods", _pod("post-failover"))
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            p = cluster.store.get("pods", "default", "post-failover")
            if p.spec.node_name:
                break
            time.sleep(0.05)
        assert cluster.store.get(
            "pods", "default", "post-failover"
        ).spec.node_name == "n0"
    finally:
        if standby is not None:
            standby.stop()


def test_promotion_fences_stalled_primary(tmp_path):
    """Split-brain guard: promoting while the old primary is merely
    STALLED (alive, lease lapsed) fences it read-only via the higher-term
    hello, and the promoted plane keeps the security assembly."""
    from kubernetes_tpu.client.apiserver import NotPrimary as _NP
    from kubernetes_tpu.cmd.kubeadm import init_cluster, standby_cluster

    primary = init_cluster(
        str(tmp_path / "p"), controllers=[], replication=True
    )
    standby = None
    try:
        standby = standby_cluster(
            primary.replication_address,
            str(tmp_path / "s"),
            lease_s=30.0,  # no auto-promotion: we promote explicitly
            controllers=[],
            admin_token=primary.admin_token,
        )
        assert standby.follower.wait_synced(10.0)
        cluster = standby.promote()
        assert cluster is not None and cluster.port > 0
        # the promoted REST facade still authenticates (401 without token)
        import urllib.request
        import urllib.error

        req = urllib.request.Request(
            f"http://127.0.0.1:{cluster.port}/api/v1/pods"
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5.0)
        assert ei.value.code == 401
        # the stalled old primary is fenced: writes refused
        deadline = time.monotonic() + 5.0
        while not primary.store.read_only and time.monotonic() < deadline:
            time.sleep(0.05)
        assert primary.store.read_only, "old primary not fenced"
        with pytest.raises(_NP):
            primary.store.create("pods", _pod("split-brain"))
    finally:
        if standby is not None:
            standby.stop()
        primary.stop()


def test_follower_wal_compacts(tmp_path):
    """The replica's WAL must compact on its own cadence (the primary's
    compaction doesn't reach across the wire); recovery from the
    compacted WAL still holds the full state."""
    from kubernetes_tpu.runtime.wal import WriteAheadLog

    primary = APIServer()
    listener = ReplicationListener(heartbeat_s=0.1)
    listener.attach(primary)
    wal = WriteAheadLog(
        str(tmp_path / "replica"), compact_every=50, fsync=False
    )
    follower = Follower(listener.address, lease_s=30.0, wal=wal).start()
    assert follower.wait_synced(5.0)
    for i in range(120):
        primary.create("pods", _pod(f"w-{i}"))
    deadline = time.monotonic() + 10.0
    while follower.rv < primary._rv and time.monotonic() < deadline:
        time.sleep(0.02)
    assert follower.rv == primary._rv
    # compaction runs async off the tail thread: poll for the shrunken log
    deadline = time.monotonic() + 10.0
    tail_records = 10**9
    while time.monotonic() < deadline:
        with open(wal.log_path, encoding="utf-8") as f:
            tail_records = sum(1 for line in f if line.strip())
        if tail_records < 120:
            break
        time.sleep(0.05)
    assert tail_records < 120, f"follower WAL never compacted: {tail_records}"
    # and recovery from the compacted state is complete. Poll-until: the
    # async compactor may still be mid-rewrite (snapshot published, tail
    # not yet settled) when the shrunken log is first observed — a
    # one-shot recover() here read exactly that window and flaked with
    # a short pod count under suite load
    deadline = time.monotonic() + 10.0
    rv, objects = WriteAheadLog.recover(str(tmp_path / "replica"))
    while (
        rv != follower.rv or len(objects.get("pods", {})) != 120
    ) and time.monotonic() < deadline:
        time.sleep(0.05)
        rv, objects = WriteAheadLog.recover(str(tmp_path / "replica"))
    assert rv == follower.rv, f"recovered rv {rv} != follower rv {follower.rv}"
    n = len(objects.get("pods", {}))
    assert n == 120, f"recovered {n}/120 pods from the compacted WAL"
    listener.close()
    follower.stop()
