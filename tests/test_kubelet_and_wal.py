"""Kubelet-lite node agent + durable WAL store.

Covers VERDICT r1 item 7: a minimal node agent (pod sync against a fake
runtime, status + lease heartbeats) sharing one code path between hollow
and real nodes, plus a durable snapshot/WAL behind the API store with a
crash-recovery test (reference fault model: crash-only against etcd,
etcd3/store.go)."""

import json
import os
import time

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client import APIServer
from kubernetes_tpu.controller.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.kubelet import ANN_FAIL, ANN_RUN_SECONDS, NodeAgentPool
from kubernetes_tpu.runtime.wal import WriteAheadLog
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler


def wait_until(fn, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.03)
    return False


def make_pod(name, cpu="100m", annotations=None):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, annotations=annotations or {}),
        spec=v1.PodSpec(containers=[v1.Container(requests={"cpu": cpu})]),
    )


# ---------------------------------------------------------------------------
# kubelet
# ---------------------------------------------------------------------------


def test_kubelet_runs_bound_pods_and_reports_status():
    server = APIServer()
    pool = NodeAgentPool(server, housekeeping_interval=0.1)
    pool.add_node("node-0")
    sched = Scheduler(server, KubeSchedulerConfiguration())
    pool.start()
    sched.start()
    try:
        server.create("pods", make_pod("web"))
        assert wait_until(
            lambda: server.get("pods", "default", "web").status.phase == "Running"
        )
        pod = server.get("pods", "default", "web")
        assert pod.spec.node_name == "node-0"
        assert pod.status.pod_ip.startswith("10.")
        assert pod.status.start_time is not None
    finally:
        sched.stop()
        pool.stop()


def test_kubelet_pleg_drives_scripted_completion():
    server = APIServer()
    pool = NodeAgentPool(server, housekeeping_interval=0.05)
    pool.add_node("node-0")
    sched = Scheduler(server, KubeSchedulerConfiguration())
    pool.start()
    sched.start()
    try:
        server.create(
            "pods", make_pod("batch", annotations={ANN_RUN_SECONDS: "0.2"})
        )
        server.create(
            "pods",
            make_pod(
                "doomed", annotations={ANN_RUN_SECONDS: "0.2", ANN_FAIL: "true"}
            ),
        )
        assert wait_until(
            lambda: server.get("pods", "default", "batch").status.phase
            == "Succeeded"
        )
        assert wait_until(
            lambda: server.get("pods", "default", "doomed").status.phase
            == "Failed"
        )
    finally:
        sched.stop()
        pool.stop()


def test_kubelet_heartbeats_feed_nodelifecycle_eviction():
    server = APIServer()
    pool = NodeAgentPool(server, heartbeat_interval=0.1, housekeeping_interval=0.1)
    pool.add_node("alive")
    pool.add_node("dying")
    sched = Scheduler(server, KubeSchedulerConfiguration())
    nlc = NodeLifecycleController(
        server,
        node_monitor_period=0.1,
        node_monitor_grace_period=0.6,
        pod_eviction_timeout=0.2,
    )
    pool.start()
    sched.start()
    nlc.start()
    try:
        # pin a pod to the doomed node via nodeName
        pod = make_pod("victim")
        pod.spec.node_name = "dying"
        server.create("pods", pod)
        assert wait_until(
            lambda: server.get("pods", "default", "victim").status.phase
            == "Running"
        )
        pool.remove_node("dying")  # node stops heartbeating
        # nodelifecycle marks NotReady and evicts the pod
        assert wait_until(
            lambda: not any(
                p.metadata.name == "victim" for p in server.list("pods")[0]
            ),
            timeout=30,
        )
    finally:
        nlc.stop()
        sched.stop()
        pool.stop()


# ---------------------------------------------------------------------------
# WAL / crash recovery
# ---------------------------------------------------------------------------


def test_wal_crash_recovery_roundtrip(tmp_path):
    path = str(tmp_path / "cluster")
    server = APIServer(wal=WriteAheadLog(path))
    server.create("nodes", v1.Node(metadata=v1.ObjectMeta(name="n0", namespace="")))
    server.create("pods", make_pod("p0"))
    server.create("pods", make_pod("p1"))
    server.delete("pods", "default", "p1")

    def bind(cur):
        cur.spec.node_name = "n0"
        return cur

    server.guaranteed_update("pods", "default", "p0", bind)
    rv_before = server.resource_version

    # "crash": drop the in-memory server entirely, recover from disk
    recovered = APIServer.recover(path)
    assert recovered.resource_version == rv_before
    pods, _ = recovered.list("pods")
    assert [p.metadata.name for p in pods] == ["p0"]
    assert pods[0].spec.node_name == "n0"
    nodes, _ = recovered.list("nodes")
    assert [n.metadata.name for n in nodes] == ["n0"]
    # writes continue with monotone resourceVersion
    recovered.create("pods", make_pod("p2"))
    assert recovered.resource_version > rv_before


def test_wal_snapshot_compaction_and_torn_tail(tmp_path):
    path = str(tmp_path / "cluster")
    wal = WriteAheadLog(path, compact_every=10)
    server = APIServer(wal=wal)
    for i in range(25):
        server.create("pods", make_pod(f"p{i}"))
    # compaction runs async off the mutation path; wait for the snapshot
    # generous timeout: fsync-per-append + async compaction under a
    # CPU-contended suite can stretch well past 10s
    assert wait_until(lambda: os.path.exists(path + ".snapshot.json"), timeout=60)
    # simulate a torn final record (crash mid-append)
    with open(path + ".wal", "a", encoding="utf-8") as f:
        f.write('{"rv": 99999, "verb": "create", "kind": "pods", "obj": {tru')
    recovered = APIServer.recover(path)
    pods, _ = recovered.list("pods")
    assert len(pods) == 25  # torn record dropped, everything else intact


def test_wal_recover_races_live_compaction(tmp_path):
    """Regression: a reader whose snapshot read lands before a compaction
    publish and whose log read lands after that compaction's log rewrite
    silently lost the records in between (observed as 14/25 pods). The
    staleness re-check must compare against the LOADED snapshot's rv —
    replayed tail records can push the recovered rv past the new
    snapshot's rv and mask the stale read."""
    for trial in range(15):
        path = str(tmp_path / f"c{trial}")
        wal = WriteAheadLog(path, compact_every=10, fsync=False)
        server = APIServer(wal=wal)
        for i in range(25):
            server.create("pods", make_pod(f"p{i}"))
            if i == 12:
                time.sleep(0.01)  # let the first compaction land mid-stream
        server._maybe_compact()  # second compaction races the recover below
        recovered = APIServer.recover(path)
        pods, _ = recovered.list("pods")
        assert len(pods) == 25, f"trial {trial}: lost {25 - len(pods)} records"
        t0 = time.time()
        while server._compacting.is_set() and time.time() - t0 < 10:
            time.sleep(0.005)
        wal.close()


def test_wal_scheduler_end_to_end_restart(tmp_path):
    """Full crash-restart: scheduler + kubelet pool against a durable store;
    after 'crash', a fresh control plane on the recovered store sees the
    bound pods and schedules new ones."""
    path = str(tmp_path / "cluster")
    server = APIServer(wal=WriteAheadLog(path))
    pool = NodeAgentPool(server, housekeeping_interval=0.1)
    pool.add_node("node-0")
    sched = Scheduler(server, KubeSchedulerConfiguration())
    pool.start()
    sched.start()
    try:
        server.create("pods", make_pod("before-crash"))
        assert wait_until(
            lambda: server.get("pods", "default", "before-crash").status.phase
            == "Running"
        )
    finally:
        sched.stop()
        pool.stop()

    # crash + recover
    server2 = APIServer.recover(path)
    pod = server2.get("pods", "default", "before-crash")
    assert pod.spec.node_name == "node-0"
    pool2 = NodeAgentPool(server2, housekeeping_interval=0.1)
    pool2.add_node("node-0", register=False)  # node object survived the crash
    sched2 = Scheduler(server2, KubeSchedulerConfiguration())
    pool2.start()
    sched2.start()
    try:
        server2.create("pods", make_pod("after-crash"))
        assert wait_until(
            lambda: server2.get("pods", "default", "after-crash").status.phase
            == "Running"
        )
        # the recovered scheduler accounted the pre-crash pod: node-0 has 2
        assert (
            server2.get("pods", "default", "after-crash").spec.node_name
            == "node-0"
        )
    finally:
        sched2.stop()
        pool2.stop()


# ---------------------------------------------------------------------------
# probes (pkg/kubelet/prober)
# ---------------------------------------------------------------------------


def _probe_pod(name, annotations, readiness=None, liveness=None):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, annotations=annotations),
        spec=v1.PodSpec(
            containers=[
                v1.Container(
                    name="c0",
                    requests={"cpu": "100m"},
                    readiness_probe=readiness,
                    liveness_probe=liveness,
                )
            ]
        ),
    )


def test_readiness_probe_gates_ready_condition_and_endpoints():
    from kubernetes_tpu.controller.endpoints import EndpointsController
    from kubernetes_tpu.kubelet.runtime import ANN_READY_AFTER

    server = APIServer()
    pool = NodeAgentPool(server, housekeeping_interval=0.05)
    pool.add_node("node-0")
    sched = Scheduler(server, KubeSchedulerConfiguration())
    epc = EndpointsController(server)
    pool.start()
    sched.start()
    epc.start()
    try:
        server.create(
            "services",
            v1.Service(
                metadata=v1.ObjectMeta(name="web"),
                spec=v1.ServiceSpec(selector={"app": "web"}),
            ),
        )
        p = _probe_pod(
            "warm",
            {ANN_READY_AFTER: "0.6"},
            readiness=v1.Probe(period_seconds=0.05, failure_threshold=1),
        )
        p.metadata.labels = {"app": "web"}
        server.create("pods", p)
        # runs, but NOT Ready during warmup: condition False, endpoints
        # list it under notReadyAddresses
        assert wait_until(
            lambda: server.get("pods", "default", "warm").status.phase
            == "Running"
        )
        pod = server.get("pods", "default", "warm")
        conds = {c.type: c.status for c in pod.status.conditions}
        assert conds.get("Ready") == "False"

        def ep_ready_count():
            try:
                ep = server.get("endpoints", "default", "web")
            except Exception:
                return -1
            return sum(len(s.addresses) for s in ep.subsets)

        assert ep_ready_count() < 1
        # after warmup the probe flips Ready and endpoints pick it up
        assert wait_until(
            lambda: {
                c.type: c.status
                for c in server.get("pods", "default", "warm").status.conditions
            }.get("Ready")
            == "True",
        ), "readiness probe must flip Ready after ready-after elapses"
        assert wait_until(lambda: ep_ready_count() == 1)
    finally:
        epc.stop()
        sched.stop()
        pool.stop()


def test_liveness_probe_restarts_container():
    from kubernetes_tpu.kubelet.runtime import ANN_UNHEALTHY_AFTER

    server = APIServer()
    pool = NodeAgentPool(server, housekeeping_interval=0.05)
    pool.add_node("node-0")
    sched = Scheduler(server, KubeSchedulerConfiguration())
    pool.start()
    sched.start()
    try:
        p = _probe_pod(
            "crashy",
            {ANN_UNHEALTHY_AFTER: "0.3"},
            liveness=v1.Probe(period_seconds=0.05, failure_threshold=2),
        )
        server.create("pods", p)
        assert wait_until(
            lambda: server.get("pods", "default", "crashy").status.phase
            == "Running"
        )
        # the runtime goes unhealthy after 0.3s; two consecutive failures
        # trigger an in-place restart, counted in containerStatuses
        assert wait_until(
            lambda: any(
                cs.restart_count >= 1
                for cs in server.get(
                    "pods", "default", "crashy"
                ).status.container_statuses
            ),
        ), "liveness failure must restart the container and count it"
        # pod stays Running (restart, not kill)
        assert server.get("pods", "default", "crashy").status.phase == "Running"
    finally:
        sched.stop()
        pool.stop()
