"""Round-5 admission breadth: the remaining reference in-tree plugins
(plugin/pkg/admission/): namespace autoprovision/exists, SecurityContextDeny,
LimitPodHardAntiAffinityTopology, EventRateLimit, gc (blockOwnerDeletion),
DefaultIngressClass, certificate approval/signing signer gates."""

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.selectors import LabelSelector
from kubernetes_tpu.apiserver.admission import (
    CertificateApprovalAdmission,
    CertificateSigningAdmission,
    DefaultIngressClassAdmission,
    EventRateLimitAdmission,
    LimitPodHardAntiAffinityTopologyAdmission,
    NamespaceAutoProvisionAdmission,
    NamespaceExistsAdmission,
    OwnerReferencesPermissionEnforcementAdmission,
    SecurityContextDenyAdmission,
    request_user,
)
from kubernetes_tpu.apiserver.auth import (
    AdmissionDenied,
    RBACAuthorizer,
    UserInfo,
    make_rule,
)
from kubernetes_tpu.client.apiserver import APIServer, NotFound


class _Ctx:
    def __init__(self, user):
        self.user = user

    def __enter__(self):
        self.tok = request_user.set(self.user)

    def __exit__(self, *a):
        request_user.reset(self.tok)


def _pod(name="p", **spec_kw):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name),
        spec=v1.PodSpec(
            containers=[v1.Container(requests={"cpu": "100m"})], **spec_kw
        ),
    )


def test_namespace_autoprovision_creates_missing_namespace():
    server = APIServer()
    plugin = NamespaceAutoProvisionAdmission(server)
    pod = _pod()
    pod.metadata.namespace = "fresh-ns"
    with pytest.raises(NotFound):
        server.get("namespaces", "", "fresh-ns")
    plugin.mutate("create", "pods", pod)
    assert server.get("namespaces", "", "fresh-ns").metadata.name == "fresh-ns"
    # idempotent
    plugin.mutate("create", "pods", pod)


def test_namespace_exists_denies_missing_allows_present():
    server = APIServer()
    plugin = NamespaceExistsAdmission(server)
    pod = _pod()
    pod.metadata.namespace = "nope"
    with pytest.raises(AdmissionDenied, match="does not exist"):
        plugin.validate("create", "pods", pod)
    server.create(
        "namespaces", v1.Namespace(metadata=v1.ObjectMeta(name="nope", namespace=""))
    )
    plugin.validate("create", "pods", pod)  # no raise
    # cluster-scoped kinds exempt
    plugin.validate(
        "create", "nodes", v1.Node(metadata=v1.ObjectMeta(name="n", namespace=""))
    )


def test_security_context_deny():
    plugin = SecurityContextDenyAdmission()
    ok = _pod()
    plugin.validate("create", "pods", ok)
    bad = _pod()
    bad.spec.containers[0].security_context = v1.SecurityContext(privileged=True)
    with pytest.raises(AdmissionDenied, match="SecurityContextDeny"):
        plugin.validate("create", "pods", bad)
    bad2 = _pod()
    bad2.spec.containers[0].security_context = v1.SecurityContext(run_as_user=0)
    with pytest.raises(AdmissionDenied):
        plugin.validate("create", "pods", bad2)


def test_limit_hard_anti_affinity_topology():
    plugin = LimitPodHardAntiAffinityTopologyAdmission()
    sel = LabelSelector.make(match_labels={"app": "a"})
    host = _pod(
        affinity=v1.Affinity(
            pod_anti_affinity=v1.PodAntiAffinity(
                required=(
                    v1.PodAffinityTerm(
                        label_selector=sel, topology_key="kubernetes.io/hostname"
                    ),
                )
            )
        )
    )
    plugin.validate("create", "pods", host)  # hostname: allowed
    zone = _pod(
        affinity=v1.Affinity(
            pod_anti_affinity=v1.PodAntiAffinity(
                required=(
                    v1.PodAffinityTerm(label_selector=sel, topology_key="zone"),
                )
            )
        )
    )
    with pytest.raises(AdmissionDenied, match="topologyKey"):
        plugin.validate("create", "pods", zone)


def test_event_rate_limit_sheds_over_burst():
    plugin = EventRateLimitAdmission(qps=0.0, burst=3)
    ev = object.__new__(object)  # the plugin never touches the object
    for _ in range(3):
        plugin.validate("create", "events", ev)
    with pytest.raises(AdmissionDenied, match="budget exhausted"):
        plugin.validate("create", "events", ev)
    # non-event kinds unaffected
    plugin.validate("create", "pods", ev)


def test_block_owner_deletion_requires_delete_on_owner():
    server = APIServer()
    authz = RBACAuthorizer()
    authz.bind("dev", make_rule(["create"], ["pods"]))
    plugin = OwnerReferencesPermissionEnforcementAdmission(authz, server)
    pod = _pod()
    pod.metadata.owner_references = [
        v1.OwnerReference(
            kind="ReplicaSet", name="rs1", controller=True,
            block_owner_deletion=True,
        )
    ]
    # in-process caller (no identity): unrestricted
    plugin.validate("create", "pods", pod)
    with _Ctx(UserInfo("dev", ())):
        with pytest.raises(AdmissionDenied, match="blockOwnerDeletion"):
            plugin.validate("create", "pods", pod)
    authz.bind("ops", make_rule(["delete"], ["replicasets"]))
    with _Ctx(UserInfo("ops", ())):
        plugin.validate("create", "pods", pod)
    # without the gate bit there is nothing to enforce
    pod.metadata.owner_references[0].block_owner_deletion = False
    with _Ctx(UserInfo("dev", ())):
        plugin.validate("create", "pods", pod)


def test_block_owner_deletion_delta_gated_on_update():
    """An unrelated update of an ALREADY-protected object needs no owner
    permission (gc_admission.go compares against oldObject); only newly
    protected refs are gated."""
    server = APIServer()
    authz = RBACAuthorizer()
    plugin = OwnerReferencesPermissionEnforcementAdmission(authz, server)
    pod = _pod("owned")
    pod.metadata.owner_references = [
        v1.OwnerReference(
            kind="ReplicaSet", name="rs1", controller=True,
            block_owner_deletion=True,
        )
    ]
    stored = server.create("pods", pod)
    # label patch by a user who cannot delete replicasets: allowed
    stored.metadata.labels["x"] = "y"
    with _Ctx(UserInfo("labeler", ())):
        plugin.validate("update", "pods", stored)
        # but ADDING protection on another owner is gated
        stored.metadata.owner_references.append(
            v1.OwnerReference(
                kind="Deployment", name="d1", block_owner_deletion=True
            )
        )
        with pytest.raises(AdmissionDenied, match="Deployment"):
            plugin.validate("update", "pods", stored)


def test_default_ingress_class_stamped_and_ambiguity_denied():
    server = APIServer()
    plugin = DefaultIngressClassAdmission(server)
    ing = v1.Ingress(metadata=v1.ObjectMeta(name="web"))
    plugin.mutate("create", "ingresses", ing)
    assert ing.spec.ingress_class_name is None  # no classes at all
    server.create(
        "ingressclasses",
        v1.IngressClass(
            metadata=v1.ObjectMeta(
                name="nginx",
                namespace="",
                annotations={"ingressclass.kubernetes.io/is-default-class": "true"},
            )
        ),
    )
    plugin.mutate("create", "ingresses", ing)
    assert ing.spec.ingress_class_name == "nginx"
    # explicit class untouched
    ing2 = v1.Ingress(
        metadata=v1.ObjectMeta(name="api"),
        spec=v1.IngressSpec(ingress_class_name="haproxy"),
    )
    plugin.mutate("create", "ingresses", ing2)
    assert ing2.spec.ingress_class_name == "haproxy"
    # two defaults: ambiguous
    server.create(
        "ingressclasses",
        v1.IngressClass(
            metadata=v1.ObjectMeta(
                name="traefik",
                namespace="",
                annotations={"ingressclass.kubernetes.io/is-default-class": "true"},
            )
        ),
    )
    with pytest.raises(AdmissionDenied, match="multiple default"):
        plugin.mutate(
            "create", "ingresses", v1.Ingress(metadata=v1.ObjectMeta(name="x"))
        )


def _csr(signer="kubernetes.io/kube-apiserver-client-kubelet"):
    return v1.CertificateSigningRequest(
        metadata=v1.ObjectMeta(name="csr1", namespace=""),
        spec=v1.CertificateSigningRequestSpec(signer_name=signer),
    )


def test_certificate_approval_requires_signer_permission():
    server = APIServer()
    authz = RBACAuthorizer()
    plugin = CertificateApprovalAdmission(authz, server)
    csr = _csr()
    csr.status.conditions.append(v1.PodCondition(type="Approved", status="True"))
    # in-process approver controller: unrestricted
    plugin.validate("update", "certificatesigningrequests", csr)
    with _Ctx(UserInfo("rando", ())):
        with pytest.raises(AdmissionDenied, match="may not approve"):
            plugin.validate("update", "certificatesigningrequests", csr)
        # creating a CSR PRE-approved is gated the same way (a create
        # would otherwise bypass the gate and mint a credential)
        with pytest.raises(AdmissionDenied, match="may not approve"):
            plugin.validate("create", "certificatesigningrequests", csr)
    authz.bind(
        "approver",
        make_rule(
            ["approve"], ["signers"],
            names=["kubernetes.io/kube-apiserver-client-kubelet"],
        ),
    )
    with _Ctx(UserInfo("approver", ())):
        plugin.validate("update", "certificatesigningrequests", csr)
    # updates that do NOT carry an approval are not gated
    plain = _csr()
    with _Ctx(UserInfo("rando", ())):
        plugin.validate("update", "certificatesigningrequests", plain)


def test_certificate_approval_delta_gated():
    """A signer writing status.certificate on an ALREADY-approved CSR does
    not need 'approve' (the approval state did not change) — reference
    certificates/approval gates only condition changes."""
    server = APIServer()
    authz = RBACAuthorizer()
    authz.bind("signer", make_rule(["sign"], ["signers"]))
    approval = CertificateApprovalAdmission(authz, server)
    signing = CertificateSigningAdmission(authz, server)
    csr = _csr()
    csr.status.conditions.append(v1.PodCondition(type="Approved", status="True"))
    stored = server.create("certificatesigningrequests", csr)
    stored.status.certificate = "issued"
    with _Ctx(UserInfo("signer", ())):
        approval.validate("update", "certificatesigningrequests", stored)
        signing.validate("update", "certificatesigningrequests", stored)


def test_certificate_signing_requires_signer_permission():
    server = APIServer()
    authz = RBACAuthorizer()
    plugin = CertificateSigningAdmission(authz, server)
    csr = _csr()
    csr.status.certificate = "signed-bytes"
    plugin.validate("update", "certificatesigningrequests", csr)  # loopback
    with _Ctx(UserInfo("rando", ())):
        with pytest.raises(AdmissionDenied, match="may not sign"):
            plugin.validate("update", "certificatesigningrequests", csr)
        # create with a pre-set certificate: same gate
        with pytest.raises(AdmissionDenied, match="may not sign"):
            plugin.validate("create", "certificatesigningrequests", csr)
    authz.bind("signer", make_rule(["sign"], ["signers"]))
    with _Ctx(UserInfo("signer", ())):
        plugin.validate("update", "certificatesigningrequests", csr)
