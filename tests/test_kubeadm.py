"""kubeadm-lite bootstrap: init phases + token join + workload runs.

Reference: cmd/kubeadm/app/cmd/phases/init (phased init, bootstrap-token
join). The test boots a full secured control plane, joins a node over the
bootstrap token, and runs a pod end to end through it."""

import json
import os
import time

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.apiserver.client import AuthRESTClient
from kubernetes_tpu.cmd.kubeadm import ADMIN_CONF, init_cluster, join_node


def wait_until(fn, timeout=60.0, period=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(period)
    return False


def test_init_join_and_schedule(tmp_path):
    handle = init_cluster(str(tmp_path / "cluster"), port=0)
    pool = None
    try:
        # kubeconfig phase wrote usable admin credentials
        conf = json.load(open(os.path.join(handle.data_dir, ADMIN_CONF)))
        admin = AuthRESTClient(conf["server"], token=conf["token"])
        nodes, _ = admin.list("nodes")
        assert nodes == []

        # unauthenticated requests bounce (the cluster is secured)
        import urllib.error
        import urllib.request

        try:
            urllib.request.urlopen(f"{handle.server_url}/api/v1/nodes")
            raise AssertionError("anonymous request must be rejected")
        except urllib.error.HTTPError as e:
            assert e.code == 401

        # join a node with the bootstrap token; owned by the handle
        pool = join_node(
            handle.server_url, handle.bootstrap_token, "worker-0", handle=handle
        )
        assert pool in handle._joined
        assert wait_until(
            lambda: any(
                n.metadata.name == "worker-0" for n in admin.list("nodes")[0]
            )
        )

        # a workload scheduled by the in-process control plane runs on it
        admin.create(
            "pods",
            v1.Pod(
                metadata=v1.ObjectMeta(name="boot-pod"),
                spec=v1.PodSpec(
                    containers=[v1.Container(requests={"cpu": "100m"})]
                ),
            ),
        )

        def running():
            p = admin.get("pods", "default", "boot-pod")
            return p.spec.node_name == "worker-0" and p.status.phase == "Running"

        assert wait_until(running, timeout=90), "pod must run on the joined node"
    finally:
        if pool is not None:
            pool.stop()
        handle.stop()


def test_upgrade_plan_and_apply(tmp_path):
    """kubeadm upgrade: plan reads the recorded cluster version, apply
    migrates it (refusing downgrades) — cmd/kubeadm/app/cmd/upgrade/."""
    import json as _json

    import pytest as _pytest

    from kubernetes_tpu import __version__
    from kubernetes_tpu.cmd.kubeadm import (
        init_cluster,
        upgrade_apply,
        upgrade_plan,
    )

    handle = init_cluster(str(tmp_path / "kubeadm"), controllers=[])
    try:
        plan = upgrade_plan(handle.store)
        assert plan["current"] == __version__
        assert plan["upgrade_available"] is False
        # apply to a newer version migrates the stored config
        res = upgrade_apply(handle.store, "v9.9.9")
        assert res == {"from": __version__, "to": "v9.9.9"}
        cm = handle.store.get("configmaps", "kube-system", "kubeadm-config")
        cfg = _json.loads(cm.data["ClusterConfiguration"])
        assert cfg["kubernetesVersion"] == "v9.9.9"
        # downgrades are refused
        with _pytest.raises(ValueError, match="downgrade"):
            upgrade_apply(handle.store, "v0.0.1")
        # idempotent re-apply
        assert upgrade_apply(handle.store, "v9.9.9")["to"] == "v9.9.9"
    finally:
        handle.stop()
