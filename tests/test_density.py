"""Density threshold: the CI throughput floor the reference enforces
(test/integration/scheduler_perf/scheduler_test.go:40-42: fail below
30 pods/s, warn below 100 on the 3k-pod/100-node density config). Runs on
the CPU backend, so the floor guards against host-path regressions (queue,
encode, store) — device speed is bench.py's job."""

import logging

from kubernetes_tpu.perf.harness import run_benchmark
from kubernetes_tpu.perf.workloads import WorkloadConfig

logger = logging.getLogger(__name__)

THRESHOLD = 30.0  # hard floor (scheduler_test.go threshold3K)
WARNING = 100.0


def test_density_3k_pods_100_nodes_min_throughput():
    cfg = WorkloadConfig("SchedulingBasic", 100, 0, 3000)
    res = run_benchmark(cfg, quiet=True, timeout_s=240)
    assert res.unscheduled == 0, f"{res.unscheduled} pods unscheduled"
    if res.throughput_pods_per_s < WARNING:
        logger.warning(
            "density throughput %.1f pods/s below warning level %.0f",
            res.throughput_pods_per_s,
            WARNING,
        )
    assert res.throughput_pods_per_s >= THRESHOLD, (
        f"density throughput {res.throughput_pods_per_s:.1f} pods/s "
        f"below the {THRESHOLD:.0f} pods/s floor"
    )


def test_secrets_and_intree_pv_workloads_schedule():
    """The remaining performance-config variants: secret-volume pods ride
    the device path; in-tree-PV pods take the host fallback lane — both
    must fully schedule."""
    from kubernetes_tpu.perf.workloads import WORKLOADS

    r = run_benchmark(
        WorkloadConfig("SchedulingSecrets", 50, 0, 200), quiet=True,
        timeout_s=240,
    )
    assert r.unscheduled == 0
    r = run_benchmark(
        WorkloadConfig("SchedulingInTreePVs", 50, 0, 100), quiet=True,
        timeout_s=240,
    )
    assert r.unscheduled == 0
    assert "SchedulingSecrets/5000" in WORKLOADS
