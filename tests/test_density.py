"""Density threshold: the CI throughput floor the reference enforces
(test/integration/scheduler_perf/scheduler_test.go:40-42: fail below
30 pods/s, warn below 100 on the 3k-pod/100-node density config). Runs on
the CPU backend, so the floor guards against host-path regressions (queue,
encode, store) — device speed is bench.py's job."""

import logging

from kubernetes_tpu.perf.harness import run_benchmark
from kubernetes_tpu.perf.workloads import WorkloadConfig

logger = logging.getLogger(__name__)

THRESHOLD = 30.0  # hard floor (scheduler_test.go threshold3K)
WARNING = 100.0


import pytest


@pytest.mark.parametrize(
    "nodes,pods,timeout_s",
    [
        # the reference's 3k-pod/100-node gate (scheduler_test.go:71-90)
        (100, 3000, 240),
        # the 1000-node cluster of the 30k-pod gate (scheduler_test.go:
        # 93-103) at a CPU-scale pod count; the full 30k-pod config is
        # SchedulingDensity/1000 in the TPU bench queue
        # (scripts/tpu_experiments.py density)
        (1000, 3000, 300),
    ],
    ids=["100n-3k", "1000n-3k"],
)
def test_density_min_throughput(nodes, pods, timeout_s):
    cfg = WorkloadConfig("SchedulingBasic", nodes, 0, pods)
    res = run_benchmark(cfg, quiet=True, timeout_s=timeout_s)
    assert res.unscheduled == 0, f"{res.unscheduled} pods unscheduled"
    if res.throughput_pods_per_s < WARNING:
        logger.warning(
            "density %dn throughput %.1f pods/s below warning level %.0f",
            nodes,
            res.throughput_pods_per_s,
            WARNING,
        )
    assert res.throughput_pods_per_s >= THRESHOLD, (
        f"density {nodes}n throughput {res.throughput_pods_per_s:.1f} "
        f"pods/s below the {THRESHOLD:.0f} pods/s floor"
    )


def test_secrets_and_intree_pv_workloads_schedule():
    """The remaining performance-config variants: secret-volume pods ride
    the device path; in-tree-PV pods take the host fallback lane — both
    must fully schedule."""
    from kubernetes_tpu.perf.workloads import WORKLOADS

    r = run_benchmark(
        WorkloadConfig("SchedulingSecrets", 50, 0, 200), quiet=True,
        timeout_s=240,
    )
    assert r.unscheduled == 0
    r = run_benchmark(
        WorkloadConfig("SchedulingInTreePVs", 50, 0, 100), quiet=True,
        timeout_s=240,
    )
    assert r.unscheduled == 0
    assert "SchedulingSecrets/5000" in WORKLOADS
