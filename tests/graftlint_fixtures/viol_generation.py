"""graftlint fixture: generation-lease discipline violations (parsed
only, never executed) — the contract that replaced the retired
`device_lock`.

Expected findings (tests/test_graftlint.py asserts exactly these):
  1. unlocked-caller: `advance` is marked holds-generation-lease, and
     `caller_outside` invokes it outside any donation_lease region
  2. retired-device-lock: `old_style_reader` still serializes a gather
     on the retired big lock
  3. unlocked-donation: `chunk_no_marker` dispatches the donating
     scatter with neither a lease region nor a deferral marker

Clean shapes exercised alongside (must NOT be findings):
  * `leased_caller` invokes the holds-generation-lease function inside
    a `with enc.donation_lease(...)` region (call-form context manager)
  * `repair` is alias-safe and uses the non-donating variant
"""

import functools

import jax


def _impl(snap, idx):
    return snap


_scatter = functools.partial(jax.jit, donate_argnums=(0,))(_impl)
_scatter_safe = jax.jit(_impl)  # graftlint: alias-safe


def advance(enc, snap):  # graftlint: holds-generation-lease
    return _scatter(snap, 0)


def caller_outside(enc, snap):
    return advance(enc, snap)  # finding 1: no lease at the call site


def leased_caller(enc):
    with enc.donation_lease(donating=True) as dl:
        dl.result = advance(enc, dl.snap)  # clean: lease held lexically
        return dl.result


def old_style_reader(enc, idx):
    with enc.device_lock:  # finding 2: the big lock is retired
        return idx


def chunk_no_marker(snap):
    return _scatter(snap, 1)  # finding 3: bare donation site


def repair(snap):  # graftlint: alias-safe
    return _scatter_safe(snap, 1)  # clean: alias-free variant
