"""graftlint fixture: donation-safety violations (NOT collected by
pytest — parsed only, never imported/executed).

Expected findings (tests/test_graftlint.py asserts exactly these):
  1. unlocked-donation: `_don(x)` outside any device_lock region
  2. unmarked-handoff: `_don` passed to `seam`, which marks nothing
  3. alias-safe-contradiction: `_lying_safe` is marked alias-safe but
     its definition donates
"""

import functools

import jax


def _impl(snap, idx):
    return snap


_don = functools.partial(jax.jit, donate_argnums=(0,))(_impl)
_lying_safe = jax.jit(_impl, donate_argnums=(0,))  # graftlint: alias-safe


def unlocked_call(x):
    return _don(x, 0)  # finding 1: no device_lock, no marker


def seam(kern, snap):
    return kern(snap, 0)  # no donating-call marker here


def handoff(snap):
    return seam(_don, snap)  # finding 2: unmarked handoff


def locked_ok(self, x):
    with self.device_lock:
        return _don(x, 0)  # clean: lexically inside device_lock
