"""graftlint fixture: donation-safety violations (NOT collected by
pytest — parsed only, never imported/executed).

Expected findings (tests/test_graftlint.py asserts exactly these):
  1. unlocked-donation: `_don(x)` outside any donation_lease region
  2. unmarked-handoff: `_don` passed to `seam`, which marks nothing
  3. alias-safe-contradiction: `_lying_safe` is marked alias-safe but
     its definition donates
  4. retired-device-lock: `legacy_locked` holds a `with ...device_lock`
     region — the big lock is retired, generation leases replaced it
  5. unlocked-donation: the `_don` call inside `legacy_locked` — the
     retired lock no longer excuses a donation site
"""

import functools

import jax


def _impl(snap, idx):
    return snap


_don = functools.partial(jax.jit, donate_argnums=(0,))(_impl)
_lying_safe = jax.jit(_impl, donate_argnums=(0,))  # graftlint: alias-safe


def unlocked_call(x):
    return _don(x, 0)  # finding 1: no donation lease, no marker


def seam(kern, snap):
    return kern(snap, 0)  # no donating-call marker here


def handoff(snap):
    return seam(_don, snap)  # finding 2: unmarked handoff


def legacy_locked(self, x):
    with self.device_lock:  # findings 4+5: retired lock, unexcused site
        return _don(x, 0)


def leased_ok(self, x):
    with self.donation_lease() as dl:
        dl.result = _don(dl.snap, 0)  # clean: inside a donation lease
        return dl.result
