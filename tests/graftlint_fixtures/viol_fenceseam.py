"""graftlint fixture: bind-fence-seam violations (parsed only).

Expected findings:
  1. unfenced-bind: `self.server.bind_pods` in `rogue_batch`
  2. unfenced-bind: `server.bind_pod` in `rogue_single`
  3. no-reason: fence-exempt pragma without a reason in `lazy_exempt`
"""


class RogueScheduler:
    def rogue_batch(self, bindings):
        return self.server.bind_pods(bindings)  # finding 1

    def _bind_pods_fenced(self, bindings):
        # clean: this IS the seam
        return self.server.bind_pods(bindings, fence=self._bind_fence)


def rogue_single(server, binding):
    server.bind_pod(binding)  # finding 2


def lazy_exempt(server, binding):
    server.bind_pod(binding)  # graftlint: fence-exempt()


def marked_exempt(server, binding):
    server.bind_pod(binding)  # graftlint: fence-exempt(fixture: injected surface is the seam)


def local_heap_named_server(binding):
    server = {}  # a local merely NAMED server is not an API handle
    server.bind_pod(binding)  # clean: bare name, not a parameter
