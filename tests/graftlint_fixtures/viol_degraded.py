"""graftlint fixture: degraded-write violations (parsed only).

Expected findings:
  1. unguarded-write: `server.create` in `naked_create`
  2. unguarded-write: `self.server.guaranteed_update` in
     `BareController.flip` (catches only NotFound)
  3. no-reason: degraded-ok pragma without a reason in `lazy_marker`
"""


def naked_create(server, obj):
    return server.create("pods", obj)  # finding 1


class BareController:
    def flip(self, ns, name, mutate):
        try:
            self.server.guaranteed_update("pods", ns, name, mutate)  # finding 2
        except NotFound:
            pass

    def guarded(self, obj):
        try:
            self.server.create("pods", obj)  # clean: handler qualifies
        except DegradedWrites:
            pass


def lazy_marker(server, obj):
    server.create("pods", obj)  # graftlint: degraded-ok()


def marked_ok(server, obj):  # graftlint: degraded-ok(fixture: caller handles)
    server.create("pods", obj)  # clean


class GuardedByBase(WorkqueueController):
    def sync(self, key):
        self.server.delete("pods", "", key)  # clean: tolerant base
