"""graftlint fixture: a clean file — every pass must report zero
findings here (parsed only, never executed)."""

import functools

import jax

from kubernetes_tpu.utils.metrics import metrics


def _impl(snap, idx):
    return snap


_don = functools.partial(jax.jit, donate_argnums=(0,))(_impl)
_safe = jax.jit(_impl)  # graftlint: alias-safe


class Encoder:
    def flush_rows(self, snap):
        with self.donation_lease() as dl:
            dl.result = _don(dl.snap, 0)
            return dl.result

    def repair_rows(self, snap):  # graftlint: alias-safe
        # the audit-path shape: a statically-donating callable invoked
        # from a function DECLARED alias-free (donate=False at runtime).
        # The marker is consulted — the stale-pragma audit fails a
        # function-level alias-safe that no donation site needs.
        return _don(snap, 0)


class KindCache:
    def _run(self):
        self.q.put_nowait(1)
        self.q.put(2, timeout=0.5)
        self.q.put(3, False)  # positional block=False: non-blocking
        self.thread.join(timeout=1.0)
        objs, rv = self.store.list("pods")  # graftlint: allow-blocking(fixture: seed list gates readiness)


def heap_local_is_not_a_store(items):
    # a LOCAL merely named `store` (a heap, a dict) is not an API
    # handle: the degraded pass only matches bare names that are
    # function parameters
    for store in items:
        store.update(1)
        store.delete(2)


def emit():
    metrics.inc("fixture_clean_total")
    metrics.set_gauge("fixture_gauge", 1.0, {"kind": "pods"})


class SafeWriter:
    def write(self, obj):
        try:
            self.server.create("pods", obj)
        except DegradedWrites:
            pass
