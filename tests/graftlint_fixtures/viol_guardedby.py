"""guardedby-pass fixture: known violations with exact finding keys.

Not imported at runtime — parsed by scripts/graftlint/guardedby.py in
tests with classes=("FixtureCache",). One majority-guarded dict with a
minority bare access, one declared-guard attribute violated, one
exempted single-writer attribute, a call-graph-inherited helper, a
reasonless unguarded pragma, and a shared module global with a racy
bump.
"""

import threading

from kubernetes_tpu.testing.lockgraph import named_lock

_epoch = 0
_glock = named_lock("fixture.global")


def bump_epoch():
    global _epoch
    with _glock:
        _epoch += 1


def bump_epoch_again():
    global _epoch
    with _glock:
        _epoch += 2


def racy_bump():
    global _epoch
    _epoch += 1  # global write outside _glock: finding


class FixtureCache:
    def __init__(self):
        self._lock = named_lock("fixture.cache")
        self._items = {}
        self._hits = 0
        self._era = 0  # graftlint: guarded-by(FixtureCache._lock)
        self._solo = 0  # graftlint: unguarded(single-writer stat, read-torn values acceptable)

    def put(self, k, v):
        with self._lock:
            self._items[k] = v
            self._hits += 1

    def get(self, k):
        with self._lock:
            return self._items.get(k)

    def size(self):
        with self._lock:
            return len(self._items)

    def churn(self):
        with self._lock:
            self._locked_helper()

    def _locked_helper(self):
        # every call site holds the lock: the access inherits it through
        # the call graph, no pragma needed
        self._hits += 1

    def bad_peek(self):
        return self._items.get("x")  # minority bare access: finding

    def bump_era(self):
        self._era += 1  # declared guard not held: finding

    def solo_tick(self):
        self._solo += 1  # attr-level unguarded override: silent

    def lazy_read(self):
        return self._hits  # graftlint: unguarded()
