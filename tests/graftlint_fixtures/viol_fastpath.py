"""graftlint fixture: split-phase fast-path readback escapes (NOT
collected by pytest — parsed only, never imported/executed).

Expected findings (tests/test_graftlint.py asserts exactly these):
  1. fastpath-escape: `res.chosen.copy_to_host_async()` in
     `escaped_readback` — the donating launch ran inside its donation
     lease, but the fast-path readback fires AFTER the lease released,
     outside any generation pin: the async transfer races generation
     retirement against the next donor.

`leased_readback` (copy inside the launching donation lease) and
`pinned_readback` (copy inside an explicit pin_generation region) are
the two sanctioned shapes and must stay clean.
"""

import functools

import jax


def _impl(snap, idx):
    return snap


_kern = functools.partial(jax.jit, donate_argnums=(0,))(_impl)


def escaped_readback(self, batch):
    with self.encoder.donation_lease() as dl:
        res = _kern(dl.snap, batch)
        dl.result = res
    res.chosen.copy_to_host_async()  # finding 1: lease already released
    return res


def leased_readback(self, batch):
    with self.encoder.donation_lease() as dl:
        res = _kern(dl.snap, batch)
        res.chosen.copy_to_host_async()  # clean: inside the lease
        dl.result = res
    return res


def pinned_readback(self, res):
    with self.encoder.pin_generation():
        res.score.copy_to_host_async()  # clean: generation pinned
    return res
