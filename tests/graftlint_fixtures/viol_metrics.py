"""graftlint fixture: metrics-contract violations (parsed only).

Expected findings (against the fixture doc fixtures_metrics.md, which
documents only `fixture_clean_total` and `fixture_gauge`):
  1. counter-suffix: `fixture_bad_count` is a counter without `_total`
  2. label-drift: `fixture_drift_total` emitted with two label key sets
  3. kind-conflict: `fixture_kind_total` used as counter AND gauge
  4. dynamic-name: series name built with an f-string
  5-7. undocumented: fixture_bad_count, fixture_drift_total,
       fixture_kind_total missing from the fixture doc
"""

from kubernetes_tpu.utils.metrics import metrics

FIXTURE_CONST = "fixture_clean_total"


def emit(kind):
    metrics.inc("fixture_bad_count")  # findings 1 + undocumented
    metrics.inc("fixture_drift_total", {"kind": kind})
    metrics.inc("fixture_drift_total", {"reason": kind})  # label drift
    metrics.inc("fixture_kind_total")
    metrics.set_gauge("fixture_kind_total", 1.0)  # kind conflict
    metrics.inc(f"fixture_{kind}_total")  # dynamic name
    metrics.inc(FIXTURE_CONST)  # clean: resolves through the constant
    metrics.set_gauge("fixture_gauge", 2.0, {"kind": kind})  # clean
