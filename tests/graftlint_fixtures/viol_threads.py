"""thread-hygiene fixture: known violations with exact finding keys."""

import threading


def spawn_implicit():
    t = threading.Thread(target=print)  # no daemon=: finding
    t.start()


def spawn_unjoined():
    t = threading.Thread(target=print, daemon=False)  # no bounded join: finding
    t.start()


def spawn_ok_daemon():
    t = threading.Thread(target=print, daemon=True)
    t.start()


class Pump:
    def start(self):
        self._t = threading.Thread(target=print, daemon=False)
        self._t.start()

    def stop(self):
        self._t.join(timeout=2.0)  # bounded join on the same name: clean


def spawn_none_join():
    t2 = threading.Thread(target=print, daemon=False)
    t2.start()
    t2.join(timeout=None)  # explicit None is still unbounded: finding


def spawn_marked():
    t = threading.Thread(target=print)  # graftlint: thread-ok(fixture: short-lived, process exit waits on it elsewhere)
    t.start()


def spawn_lazy_marked():
    t = threading.Thread(target=print)  # graftlint: thread-ok()
    t.start()
