"""Fixture: tracing span-lifecycle violations (pass 7).

Expected findings:
  * `leaked_assignment` — span manager assigned, never entered: the span
    is either never opened or never closed.
  * `bare_call` — span manager called and dropped on the floor.
  * `_ok_with` / `_ok_add_span` / `_ok_marked` must stay clean.
"""

import time

from kubernetes_tpu.utils.tracing import tracer


def leaked_assignment(tid):
    s = tracer.span(tid, "encode")  # finding: not a with-statement
    return s


def bare_call(tid):
    tracer.span(tid, "device")  # finding: manager dropped, span never opens


def suppressed_no_reason(tid):
    s = tracer.span(tid, "guard")  # graftlint: span-ok
    return s


def _ok_with(tid):
    with tracer.span(tid, "bind"):
        pass


def _ok_add_span(tid):
    t0 = time.monotonic()
    tracer.add_span(tid, "assume", t0, time.monotonic())


def _ok_marked(tid, stack):
    s = stack.enter_context(tracer.span(tid, "readback"))  # graftlint: span-ok(ExitStack composition closes it with the stack)
    return s
