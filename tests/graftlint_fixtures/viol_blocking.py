"""graftlint fixture: dispatch-thread blocking-call violations (parsed
only, never executed).

The class/method names below deliberately collide with a registered
dispatch root (config.DISPATCH_ROOTS contains "KindCache._run") so the
reachability walk starts here.

Expected findings:
  1. unbounded queue.put in `_run`
  2. unbounded .join() in `_helper` (reachable from `_run`)
  3. store RPC .list() on `self.store` in `_run`
  4. time.sleep under a hot lock (`_gen_lock`) in `hot_section`
  5. allow-blocking pragma without a reason in `_lazy`
"""

import time


class KindCache:
    def _run(self):
        self.q.put(1)  # finding 1
        self._helper()
        self._lazy()
        objs, rv = self.store.list("pods")  # finding 3
        self.q.put_nowait(2)  # clean
        self.q.put(3, timeout=1.0)  # clean: bounded

    def _helper(self):
        self.thread.join()  # finding 2
        self.thread.join(timeout=2.0)  # clean

    def _lazy(self):
        self.q.put(4)  # graftlint: allow-blocking()


def hot_section(enc):
    with enc._gen_lock:
        time.sleep(0.5)  # finding 4
