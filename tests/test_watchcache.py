"""Watch cache (apiserver/cacher.py): RV-window edge cases, pagination,
bookmarks, fan-out discipline, and the REST/flow-control integration.

The window contract under test (ISSUE 6 acceptance list):
  * reconnect at exactly the oldest buffered RV → replay, no store touch
  * reconnect one before it → 410 Expired (outside the window)
  * reconnect at a future RV → only events past that RV are delivered
  * empty-cache cold start → watch works, no spurious 410
  * continue token across a compaction → pagination stays consistent at
    the ORIGINAL rv even as the event window and live state move on
"""

import json
import socket
import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.apiserver.cacher import Cacher, readpath_health_lines
from kubernetes_tpu.apiserver.flowcontrol import (
    GAUGE_SEATS_IN_USE,
    FlowController,
    RequestRejected,
)
from kubernetes_tpu.apiserver.rest import serve
from kubernetes_tpu.client.apiserver import APIServer, Expired
from kubernetes_tpu.client.informers import SharedInformer
from kubernetes_tpu.runtime.watch import ADDED, BOOKMARK, DELETED
from kubernetes_tpu.utils.metrics import metrics


def wait_until(fn, timeout=10.0, period=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(period)
    return False


def make_pod(name, cpu="100m"):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name),
        spec=v1.PodSpec(containers=[v1.Container(requests={"cpu": cpu})]),
    )


def drain(watcher, timeout=0.5):
    """Collect queued non-bookmark events until the queue goes quiet.
    Bounded by a wall deadline: periodic bookmarks must not keep the
    drain alive forever."""
    out = []
    deadline = time.time() + max(timeout * 4, 2.0)
    while time.time() < deadline:
        ev = watcher.get(timeout=timeout)
        if ev is None:
            return out
        if ev.type != BOOKMARK:
            out.append(ev)
    return out


@pytest.fixture
def cached_store():
    store = APIServer()
    cacher = Cacher(store, window=4, bookmark_period_s=0.15)
    yield store, cacher
    cacher.stop()


# -- the RV window ------------------------------------------------------------


def test_reconnect_at_exactly_oldest_buffered_rv(cached_store):
    store, cacher = cached_store
    kc = cacher.cache_for("pods")  # cache FIRST: the ring buffers events
    for i in range(8):  # window=4: the first events get evicted
        store.create("pods", make_pod(f"p{i}"))
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    assert wait_until(lambda: len(kc._ring) == 4)
    oldest = kc._ring[0].resource_version
    w = cacher.watch("pods", from_version=oldest)
    evs = drain(w)
    assert [e.resource_version for e in evs] == list(
        range(oldest + 1, store.resource_version + 1)
    )
    w.stop()


def test_reconnect_one_before_oldest_is_410(cached_store):
    store, cacher = cached_store
    kc = cacher.cache_for("pods")
    for i in range(8):
        store.create("pods", make_pod(f"p{i}"))
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    assert wait_until(lambda: len(kc._ring) == 4)
    oldest = kc._ring[0].resource_version
    x0 = metrics.counter("watch_cache_expired_total", {"kind": "pods"})
    with pytest.raises(Expired):
        cacher.watch("pods", from_version=oldest - 1)
    assert (
        metrics.counter("watch_cache_expired_total", {"kind": "pods"}) - x0
        == 1
    )


def test_reconnect_at_future_rv_skips_already_seen_events(cached_store):
    store, cacher = cached_store
    store.create("pods", make_pod("p0"))
    kc = cacher.cache_for("pods")
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    future = store.resource_version + 2
    w = cacher.watch("pods", from_version=future)
    # these two land AT or BELOW the client's claimed position: skipped
    store.create("pods", make_pod("claimed-1"))
    store.create("pods", make_pod("claimed-2"))
    # this one is past it: delivered
    store.create("pods", make_pod("new"))
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    evs = drain(w)
    assert [e.object.metadata.name for e in evs] == ["new"]
    w.stop()


def test_empty_cache_cold_start(cached_store):
    store, cacher = cached_store
    # no objects, no history: watch from 0 must neither 410 nor replay
    w = cacher.watch("pods", from_version=0)
    assert cacher.cache_for("pods").current_rv == 0
    store.create("pods", make_pod("first"))
    ev = w.get(timeout=2.0)
    assert ev is not None and ev.type == ADDED
    assert ev.object.metadata.name == "first"
    w.stop()


def test_replay_within_window_touches_no_store_watch(cached_store):
    """A windowed reconnect is served purely from the buffer: the store
    still sees exactly ONE watcher for the kind no matter how many
    clients replay."""
    store, cacher = cached_store
    kc = cacher.cache_for("pods")
    for i in range(3):
        store.create("pods", make_pod(f"p{i}"))
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    r0 = metrics.counter("watch_cache_replays_total", {"kind": "pods"})
    watchers = [cacher.watch("pods", from_version=1) for _ in range(20)]
    for w in watchers:
        assert len(drain(w, timeout=0.1)) == store.resource_version - 1
    assert store.watcher_count("pods") == 1
    assert (
        metrics.counter("watch_cache_replays_total", {"kind": "pods"}) - r0
        == 20
    )
    for w in watchers:
        w.stop()


# -- bookmarks ----------------------------------------------------------------


def test_bookmarks_advance_idle_clients(cached_store):
    store, cacher = cached_store
    store.create("pods", make_pod("p0"))
    kc = cacher.cache_for("pods")
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    w = cacher.watch("pods", from_version=store.resource_version)
    got = []

    def consume():
        while True:
            ev = w.get(timeout=1.0)
            if ev is None:
                return
            got.append(ev)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    assert wait_until(
        lambda: any(ev.type == BOOKMARK for ev in got), timeout=3.0
    ), "idle watcher never received a bookmark"
    bm = next(ev for ev in got if ev.type == BOOKMARK)
    assert bm.resource_version == store.resource_version
    w.stop()
    t.join(timeout=2.0)


def test_informer_consumes_bookmarks_without_handler_churn(cached_store):
    """Bookmarks advance last_resource_version but never invoke handlers
    (informer_bookmarks_total counts them); after a long idle + window
    churn on OTHER kinds, the informer still resumes without a relist."""
    store, cacher = cached_store
    store.create("pods", make_pod("p0"))
    inf = SharedInformer(cacher, "pods")
    calls = []
    inf.add_handler(
        on_add=lambda o: calls.append(("add", o.metadata.name)),
        on_update=lambda o, n: calls.append(("upd", n.metadata.name)),
        on_delete=lambda o: calls.append(("del", o.metadata.name)),
    )
    b0 = metrics.counter("informer_bookmarks_total", {"kind": "pods"})
    inf.start()
    try:
        assert wait_until(inf.has_synced, 5)
        assert wait_until(
            lambda: metrics.counter(
                "informer_bookmarks_total", {"kind": "pods"}
            )
            > b0,
            timeout=3.0,
        ), "informer never consumed a bookmark"
        assert calls == [("add", "p0")], (
            "bookmarks must not invoke event handlers"
        )
        assert inf.last_resource_version == store.resource_version
    finally:
        inf.stop()


def test_informer_relist_reason_window_expired(cached_store):
    """A resume attempt whose rv fell out of the window is the ONE case
    that still re-lists — counted under reason=window_expired."""
    store, cacher = cached_store
    store.create("pods", make_pod("p0"))
    inf = SharedInformer(cacher, "pods")
    seen = []
    inf.add_handler(on_add=lambda o: seen.append(o.metadata.name))
    inf.start()
    try:
        assert wait_until(inf.has_synced, 5)
        we0 = metrics.counter(
            "informer_relists_total",
            {"kind": "pods", "reason": "window_expired"},
        )
        # stop the informer's stream, then blow past the window while it
        # is disconnected (window=4, 8 events): its resume rv is now
        # outside the buffer -> true 410 -> relist
        inf._watcher.stop()
        for i in range(8):
            store.create("pods", make_pod(f"storm-{i}"))
        assert wait_until(
            lambda: metrics.counter(
                "informer_relists_total",
                {"kind": "pods", "reason": "window_expired"},
            )
            > we0,
            timeout=10.0,
        ), "410-outside-window did not surface as a window_expired relist"
        assert wait_until(lambda: "storm-7" in seen, 10.0)
        # Replace semantics reconciled the full set
        assert len(inf.list()) == store.count("pods")
    finally:
        inf.stop()


# -- pagination ---------------------------------------------------------------


def test_list_pagination_consistent_at_single_rv(cached_store):
    store, cacher = cached_store
    for i in range(7):
        store.create("pods", make_pod(f"p{i}"))
    kc = cacher.cache_for("pods")
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    items, rv, tok = cacher.list_page("pods", limit=3)
    assert len(items) == 3 and tok
    items2, rv2, tok2 = cacher.list_page("pods", limit=3, continue_token=tok)
    items3, rv3, tok3 = cacher.list_page("pods", limit=3, continue_token=tok2)
    assert rv == rv2 == rv3
    assert tok3 is None
    names = [o.metadata.name for o in items + items2 + items3]
    assert names == sorted(names) and len(names) == 7


def test_continue_token_across_compaction(cached_store):
    """Between page 1 and page 2, churn past the whole event window (a
    compaction of the replay buffer) AND delete rows from live state:
    the continuation still serves the original snapshot at the original
    rv — pagination never tears."""
    store, cacher = cached_store
    for i in range(6):
        store.create("pods", make_pod(f"p{i}"))
    kc = cacher.cache_for("pods")
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    items, rv, tok = cacher.list_page("pods", limit=2)
    # compaction: window=4, so 8 more events evict everything page 1 saw
    for i in range(8):
        store.create("pods", make_pod(f"churn-{i}"))
    store.delete("pods", "default", "p3")
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    items2, rv2, tok2 = cacher.list_page("pods", limit=2, continue_token=tok)
    assert rv2 == rv, "continuation drifted off its snapshot rv"
    assert [o.metadata.name for o in items2] == ["p2", "p3"], (
        "continuation must serve the original snapshot (p3 was deleted "
        "live but belongs to the page-1 view)"
    )


def test_unknown_continue_token_is_410(cached_store):
    store, cacher = cached_store
    store.create("pods", make_pod("p0"))
    with pytest.raises(Expired):
        cacher.list_page("pods", limit=2, continue_token="bogus-token")


def test_list_from_cache_waits_until_fresh(cached_store):
    store, cacher = cached_store
    store.create("pods", make_pod("p0"))
    kc = cacher.cache_for("pods")
    store.create("pods", make_pod("p1"))
    items, rv, _ = cacher.list_page(
        "pods", limit=10, fresh_rv=store.resource_version
    )
    assert rv >= store.resource_version
    assert len(items) == 2


# -- fan-out discipline -------------------------------------------------------


def test_slow_watcher_terminated_not_blocking(cached_store):
    """A client that stops draining fills its bounded queue and is
    TERMINATED; the dispatch loop and every other client keep going."""
    store, _ = cached_store
    cacher = Cacher(store, window=64, bookmark_period_s=60)
    try:
        store.create("pods", make_pod("seed"))
        kc = cacher.cache_for("pods")
        assert wait_until(lambda: kc.current_rv == store.resource_version)
        slow = kc.watch(from_version=0, queue_size=8)
        healthy = cacher.watch("pods", from_version=0)
        s0 = metrics.counter(
            "watch_cache_slow_watchers_evicted_total", {"kind": "pods"}
        )
        done = []

        def drain_healthy():
            while True:
                ev = healthy.get(timeout=2.0)
                if ev is None:
                    return
                done.append(ev)

        t = threading.Thread(target=drain_healthy, daemon=True)
        t.start()
        for i in range(20):  # queue size 8: the slow client must overflow
            store.create("pods", make_pod(f"burst-{i}"))
        assert wait_until(lambda: len(done) >= 20, 10.0), (
            "healthy client starved behind a slow one"
        )
        assert wait_until(lambda: slow.stopped, 5.0), (
            "slow watcher was never terminated"
        )
        assert slow.terminated_slow
        assert (
            metrics.counter(
                "watch_cache_slow_watchers_evicted_total", {"kind": "pods"}
            )
            > s0
        )
        healthy.stop()
        t.join(timeout=2.0)
    finally:
        cacher.stop()


def test_cacher_resyncs_after_store_watch_death(cached_store):
    """The cacher's OWN store stream dying (store restart analogue):
    re-list, window reset, connected watchers TERMINATED (the reference's
    terminateAllWatchers — a mid-gap synthetic diff could desync a
    flapping client forever). An informer rides it out end to end: its
    terminated stream reconnects (resume, or 410 → re-list when the
    post-gap floor moved past it) and keeps delivering."""
    store, cacher = cached_store
    store.create("pods", make_pod("p0"))
    inf = SharedInformer(cacher, "pods")
    seen = []
    inf.add_handler(on_add=lambda o: seen.append(o.metadata.name))
    inf.start()
    assert wait_until(inf.has_synced, 5)
    kc = cacher.cache_for("pods")
    w = cacher.watch("pods", from_version=store.resource_version)
    r0 = metrics.counter("watch_cache_resyncs_total", {"kind": "pods"})
    try:
        kc._store_watcher.stop()  # kill the one store watch under the cache
        assert wait_until(
            lambda: metrics.counter(
                "watch_cache_resyncs_total", {"kind": "pods"}
            )
            > r0,
            5.0,
        )
        # direct watcher: terminated by the resync, not left half-synced
        assert wait_until(lambda: w.stopped, 5.0)
        # informer: recovers through the 410 → re-list path and keeps up
        store.create("pods", make_pod("after-resync"))
        assert wait_until(lambda: "after-resync" in seen, 10.0)
        assert store.watcher_count("pods") == 1
    finally:
        inf.stop()
        w.stop()


def test_dispatch_thread_survives_resync_errors(cached_store):
    """An exception inside the dispatch loop (here: the resync's store
    list failing) must not silently kill the per-kind thread — it logs,
    counts `watch_cache_dispatch_errors_total`, backs off, and retries
    until the resync lands; clients converge afterwards."""
    store, cacher = cached_store
    kc = cacher.cache_for("pods")
    store.create("pods", make_pod("p0"))
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    orig_list = store.list
    fails = {"n": 2}  # first two re-list attempts blow up

    def flaky_list(kind, namespace=None):
        if kind == "pods" and fails["n"] > 0:
            fails["n"] -= 1
            raise RuntimeError("chaos: store list failed mid-resync")
        return orig_list(kind, namespace=namespace)

    store.list = flaky_list
    d0 = metrics.counter(
        "watch_cache_dispatch_errors_total", {"kind": "pods"}
    )
    kc._store_watcher.stop()  # force the resync path into the failure
    assert wait_until(
        lambda: metrics.counter(
            "watch_cache_dispatch_errors_total", {"kind": "pods"}
        )
        > d0,
        5.0,
    ), "dispatch error was never counted"
    # the loop kept retrying: a post-recovery write reaches a new client
    store.create("pods", make_pod("after-error"))
    assert wait_until(lambda: kc.get("default/after-error") is not None, 10.0)
    assert fails["n"] == 0
    w = cacher.watch("pods", from_version=0)
    names = {ev.object.metadata.name for ev in drain(w, 0.2)}
    assert {"p0", "after-error"} <= names
    w.stop()


# -- REST integration ---------------------------------------------------------


@pytest.fixture
def rest_server():
    srv, port, store = serve(port=0, bookmark_period_s=0.2)
    yield srv, port, store
    srv.shutdown()


def test_rest_watch_emits_bookmark_lines_and_410(rest_server):
    srv, port, store = rest_server
    store.create("pods", make_pod("p0"))
    # old-rv watch over HTTP: churn past the window first
    small = Cacher(store, window=2, bookmark_period_s=60)
    srv.cacher.stop()
    srv.cacher = small
    for i in range(6):
        store.create("pods", make_pod(f"w{i}"))
    kc = small.cache_for("pods")
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/v1/pods?watch=1&resourceVersion=1",
            timeout=5,
        )
        assert False, "expected 410"
    except urllib.error.HTTPError as e:
        assert e.code == 410
    # a live watch on an idle resource still heartbeats bookmarks
    resp = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/v1/pods?watch=1&resourceVersion="
        f"{store.resource_version}",
        timeout=5,
    )
    srv.bookmark_period_s = 0.2
    line = resp.readline()
    msg = json.loads(line)
    assert msg["type"] == "BOOKMARK"
    assert int(msg["object"]["metadata"]["resourceVersion"]) == (
        store.resource_version
    )
    resp.close()


def test_rest_half_open_watch_reaped_by_heartbeat(rest_server):
    """A silently dropped client: the idle bookmark write fails and the
    watcher thread exits instead of leaking (the stream gauge drops)."""
    srv, port, store = rest_server
    store.create("pods", make_pod("p0"))
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(
        b"GET /api/v1/pods?watch=1&resourceVersion=0 HTTP/1.1\r\n"
        b"Host: x\r\n\r\n"
    )
    s.recv(4096)  # response headers
    assert wait_until(lambda: srv.watch_stream_count("pods") == 1, 5.0)
    # drop the connection without closing the HTTP stream politely
    s.setsockopt(
        socket.SOL_SOCKET, socket.SO_LINGER,
        __import__("struct").pack("ii", 1, 0),
    )
    s.close()  # RST
    assert wait_until(lambda: srv.watch_stream_count("pods") == 0, 10.0), (
        "half-open watch stream was never reaped by the bookmark heartbeat"
    )


def test_rest_list_rv0_served_from_cache(rest_server):
    srv, port, store = rest_server
    store.create("pods", make_pod("p0"))
    kc = srv.cacher.cache_for("pods")
    assert wait_until(lambda: kc.current_rv == store.resource_version)
    p0 = metrics.counter("watch_cache_list_pages_total", {"kind": "pods"})
    out = json.load(
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/v1/pods?resourceVersion=0",
            timeout=5,
        )
    )
    assert [i["metadata"]["name"] for i in out["items"]] == ["p0"]
    assert (
        metrics.counter("watch_cache_list_pages_total", {"kind": "pods"}) - p0
        == 1
    )


# -- flow control: watch-init seats ------------------------------------------


def test_watch_init_seats_accounted_and_released():
    fc = FlowController(total_concurrency=20)
    lv = fc.begin(None, "pods", "watch")
    assert lv.name == "watch-init"
    assert (
        metrics.gauge(GAUGE_SEATS_IN_USE, {"priority_level": "watch-init"})
        == 1
    )
    fc.end(lv)
    assert (
        metrics.gauge(GAUGE_SEATS_IN_USE, {"priority_level": "watch-init"})
        == 0
    )


def test_watch_init_storm_cannot_starve_system_level():
    """Saturate watch-init completely: system-level requests (kubelet
    heartbeats, scheduler binds) still admit instantly — isolation
    between levels is exact."""
    from kubernetes_tpu.apiserver.auth import UserInfo

    fc = FlowController(total_concurrency=20, queue_wait_s=0.01)
    node = UserInfo("system:node:n1", ("system:nodes",))
    held = []
    try:
        while True:
            held.append(fc.begin(None, "pods", "watch"))
    except RequestRejected as e:
        assert e.level == "watch-init"
    assert held, "watch-init pool admitted nothing"
    # the storm is saturated; system traffic is untouched: heartbeats
    # (lease renewals -> leader-election level) and binds (pod writes ->
    # system level) both admit instantly
    for _ in range(3):
        lv = fc.begin(node, "leases", "update")
        assert lv.name == "leader-election"
        fc.end(lv)
        lv = fc.begin(node, "pods", "create")
        assert lv.name == "system"
        fc.end(lv)
    for lv in held:
        fc.end(lv)


def test_readpath_health_lines_render():
    metrics.set_gauge("watch_cache_size", 3, {"kind": "pods"})
    lines = readpath_health_lines()
    assert any("watch_cache_size{kind=pods}: 3" in l for l in lines)
