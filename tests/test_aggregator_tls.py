"""kube-aggregator cert handling: TLS verification to https backends via
the APIService caBundle + requestheader identity propagation.

Reference: staging/src/k8s.io/kube-aggregator proxy handler — backend TLS
config from APIService.Spec.CABundle / InsecureSkipTLSVerify, and the
front-proxy's X-Remote-User / X-Remote-Group requestheader contract."""

import base64
import datetime
import json
import ssl
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

# every test here mints a self-signed backend cert; images without the
# cryptography wheel must SKIP the module at collection instead of
# erroring 5 times in the fixture (tier-1 runs --continue-on-collection-
# errors, but errors still pollute the suite result)
pytest.importorskip("cryptography")

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.apiserver.rest import serve


class _Echo(BaseHTTPRequestHandler):
    """Backend that echoes the identity headers it saw."""

    def do_GET(self):
        body = json.dumps(
            {
                "path": self.path,
                "remote_user": self.headers.get("X-Remote-User"),
                "remote_groups": self.headers.get_all("X-Remote-Group") or [],
            }
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def _selfsigned_cert(tmp_path):
    """(cert_pem_path, key_pem_path, cert_pem_bytes) for 127.0.0.1."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "ext-apiserver")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1"))]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )
    cp, kp = tmp_path / "tls.crt", tmp_path / "tls.key"
    cp.write_bytes(cert_pem)
    kp.write_bytes(key_pem)
    return str(cp), str(kp), cert_pem


@pytest.fixture
def tls_backend(tmp_path):
    cert_path, key_path, cert_pem = _selfsigned_cert(tmp_path)
    httpd = HTTPServer(("127.0.0.1", 0), _Echo)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield httpd.server_address[1], cert_pem
    httpd.shutdown()


def _apiservice(port, **kw):
    return v1.APIService(
        metadata=v1.ObjectMeta(name="v1.metrics.example.com"),
        spec=v1.APIServiceSpec(
            group="metrics.example.com",
            service_url=f"https://127.0.0.1:{port}",
            **kw,
        ),
    )


def _get(front_port, path):
    url = f"http://127.0.0.1:{front_port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_ca_bundle_verifies_backend(tls_backend):
    bport, cert_pem = tls_backend
    srv, port, store = serve()
    try:
        store.create(
            "apiservices",
            _apiservice(
                bport, ca_bundle=base64.b64encode(cert_pem).decode()
            ),
        )
        code, resp = _get(port, "/apis/metrics.example.com/v1/things")
        assert code == 200
        assert resp["path"] == "/apis/metrics.example.com/v1/things"
    finally:
        srv.shutdown()


def test_untrusted_backend_rejected(tls_backend):
    """No caBundle, no skip flag: the self-signed backend must fail
    verification against system roots -> 502, never silent plaintext."""
    bport, _ = tls_backend
    srv, port, store = serve()
    try:
        store.create("apiservices", _apiservice(bport))
        code, _ = _get(port, "/apis/metrics.example.com/v1/things")
        assert code == 502
    finally:
        srv.shutdown()


def test_insecure_skip_tls_verify(tls_backend):
    bport, _ = tls_backend
    srv, port, store = serve()
    try:
        store.create(
            "apiservices", _apiservice(bport, insecure_skip_tls_verify=True)
        )
        code, _ = _get(port, "/apis/metrics.example.com/v1/things")
        assert code == 200
    finally:
        srv.shutdown()


def test_requestheader_identity_propagated_and_spoof_stripped(tls_backend):
    """The authenticated identity reaches the backend as X-Remote-*;
    client-supplied X-Remote-* headers must NOT pass through."""
    from kubernetes_tpu.apiserver.auth import TokenAuthenticator

    bport, cert_pem = tls_backend
    auth = TokenAuthenticator()
    auth.add_token("tok-1", "alice", groups=("dev", "oncall"))
    srv, port, store = serve(authenticator=auth)
    try:
        store.create(
            "apiservices",
            _apiservice(
                bport, ca_bundle=base64.b64encode(cert_pem).decode()
            ),
        )
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/apis/metrics.example.com/v1/x",
            headers={
                "Authorization": "Bearer tok-1",
                # spoof attempt: must be stripped by the front proxy
                "X-Remote-User": "system:admin",
                "X-Remote-Group": "system:masters",
            },
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["remote_user"] == "alice"
        got = {
            g.strip()
            for h in body["remote_groups"]
            for g in h.split(",")
        }
        assert got == {"dev", "oncall"}
    finally:
        srv.shutdown()


def test_invalid_ca_bundle_is_502(tls_backend):
    bport, _ = tls_backend
    srv, port, store = serve()
    try:
        store.create("apiservices", _apiservice(bport, ca_bundle="!not-b64!"))
        code, _ = _get(port, "/apis/metrics.example.com/v1/things")
        assert code == 502
    finally:
        srv.shutdown()
