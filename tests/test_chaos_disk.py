"""Disk chaos: the storage medium is the last un-chaos'd fault domain.

Every robustness layer above the store (leadership fencing, HA adoption,
persist-first promotion) treats the WAL as the one component that never
lies — these tests make the WAL earn it under the four real disk failure
modes, with deterministic injection (testing/diskfaults.py, never
random) and the consistency-check ledger as the done-bar:

  * crash mid-append (kill -9 loops + a byte-level truncation sweep):
    recovery is exactly the acked prefix, zero acked-write loss, zero
    wrong binds;
  * bit-flip mid-log: recovery refuses to serve silently-wrong state —
    longest valid prefix + DiskCorrupt promotion bar, healed by a
    replication resync from the leader;
  * fsync/write failure: the sink poisons permanently (fsyncgate), the
    store degrades to read-only with the retryable DiskFailed reason,
    and a LEADER with a failed disk releases its lease so a healthy
    replica promotes within retry-periods — not lease expiry;
  * ENOSPC / low space: read-only BEFORE writes fail, nothing poisoned,
    auto-reopen once space recovers, with the fsync-stall watchdog
    catching the slow-dying-disk prequel.

Plus the disaster-recovery end of the story: a cluster restored from an
online backup structurally rejects every pre-restore fencing token.
"""

import json
import os
import shutil
import subprocess
import sys
import threading
import time
import types

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client.apiserver import APIServer, LeaderFenced, NotFound
from kubernetes_tpu.client.leaderelection import (
    COUNTER_DISK_STEPDOWNS,
    BindFence,
    Lease,
    LeaderElectionConfig,
    LeaderElector,
)
from kubernetes_tpu.runtime import backup
from kubernetes_tpu.runtime.consensus import DiskFailed, DiskPressure
from kubernetes_tpu.runtime.replication import Follower, ReplicationListener
from kubernetes_tpu.runtime.wal import (
    COUNTER_FSYNC_STALLS,
    COUNTER_RETRIES_EXHAUSTED,
    COUNTER_TMP_SWEEPS,
    DiskSpaceProbe,
    RecoveryReport,
    SinkFailed,
    WriteAheadLog,
)
from kubernetes_tpu.testing.diskfaults import (
    DiskFaultInjector,
    bit_flip_record,
    truncate_log_at,
)
from kubernetes_tpu.utils.metrics import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.join(REPO, "scripts") not in sys.path:
    sys.path.insert(0, os.path.join(REPO, "scripts"))

import consistency_check  # noqa: E402  (scripts/ is not a package)


def wait_until(fn, timeout=30.0, period=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(period)
    return False


def make_pod(name, namespace="default"):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, namespace=namespace),
        spec=v1.PodSpec(containers=[v1.Container(name="c", image="img")]),
    )


def make_wal(tmp_path, name="store", **kw):
    kw.setdefault("native", False)  # python sink: the injection seam
    kw.setdefault("fsync", False)
    return WriteAheadLog(str(tmp_path / name), **kw)


def fake_probe(path, free_bytes):
    """DiskSpaceProbe with injected statvfs + an always-advancing clock
    (defeats the 1s rate limit); mutate probe.free[0] to move space."""
    free = [free_bytes]
    tick = [0.0]

    def clock():
        tick[0] += 10.0
        return tick[0]

    def statvfs(_d):
        return types.SimpleNamespace(f_bavail=free[0], f_frsize=1)

    probe = DiskSpaceProbe(path, statvfs=statvfs, clock=clock)
    probe.free = free
    return probe


# ---------------------------------------------------------------------------
# kill -9 mid-append loops (the ChaosStore/consistency-check ledger)
# ---------------------------------------------------------------------------

_KILL_LOOP_CHILD = r"""
import json, os, signal, sys, time

prefix, ack_path, cycles, repo = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), sys.argv[4]
)
sys.path.insert(0, repo)
from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client.apiserver import APIServer
from kubernetes_tpu.runtime.wal import WriteAheadLog


def worker():
    # recover exactly like a restarting node, then append + bind forever
    # until SIGKILLed; every ack line is written only AFTER the client-
    # visible success (the consistency checker's contract)
    report = WriteAheadLog.recover_report(prefix)
    if report.corrupt:
        os._exit(7)  # a process kill must never look like media damage
    srv = APIServer(wal=WriteAheadLog(prefix, fsync=False, native=False))
    srv._rv = report.rv
    srv._objects = report.objects
    ack = open(ack_path, "a", buffering=1)
    have = {p.metadata.name: p for p in srv.list("pods", "default")[0]}
    i = 0
    while True:
        name = "p%d" % i
        pod = have.get(name)
        if pod is None:
            pod = srv.create("pods", v1.Pod(
                metadata=v1.ObjectMeta(name=name, namespace="default"),
                spec=v1.PodSpec(
                    containers=[v1.Container(name="c", image="img")]
                ),
            ))
            ack.write(json.dumps({
                "op": "create", "kind": "pods",
                "key": "default/%s" % name,
                "rv": pod.metadata.resource_version,
            }) + "\n")
        if not pod.spec.node_name:
            srv.bind_pod(v1.Binding(
                pod_name=name, pod_namespace="default",
                target_node="n%d" % (i % 4),
            ))
            bound = srv.get("pods", "default", name)
            ack.write(json.dumps({
                "op": "update", "kind": "pods",
                "key": "default/%s" % name,
                "rv": bound.metadata.resource_version,
            }) + "\n")
        i += 1


for cycle in range(cycles):
    pid = os.fork()
    if pid == 0:
        try:
            worker()
        finally:
            os._exit(9)
    time.sleep(0.12)
    os.kill(pid, signal.SIGKILL)
    os.waitpid(pid, 0)

report = WriteAheadLog.recover_report(prefix)
pods = report.objects.get("pods", {})
wrong = [
    key for key, pod in pods.items()
    if pod.spec.node_name
    and pod.spec.node_name != "n%d" % (int(pod.metadata.name[1:]) % 4)
]
print(json.dumps({
    "rv": report.rv,
    "pods": len(pods),
    "bound": sum(1 for p in pods.values() if p.spec.node_name),
    "corrupt": report.corrupt,
    "wrong_binds": wrong,
}))
"""


def _run_kill_loop(tmp_path, cycles):
    prefix = str(tmp_path / "killstore")
    ack_path = str(tmp_path / "acks.jsonl")
    child = tmp_path / "kill_child.py"
    child.write_text(_KILL_LOOP_CHILD)
    proc = subprocess.run(
        [sys.executable, str(child), prefix, ack_path, str(cycles), REPO],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=60 + 2 * cycles,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"kill loop child failed (rc={proc.returncode}):\n"
        f"{proc.stdout}\n{proc.stderr}"
    )
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    # zero double/wrong binds on the recovered state
    assert summary["corrupt"] is False
    assert summary["wrong_binds"] == []
    assert summary["pods"] > cycles  # each cycle made real progress
    # zero acked-write loss, proven by the external checker against the
    # surviving WAL exactly as a restarted node would recover it
    assert consistency_check.run(ack_path, [prefix]) == 0
    return summary


def test_kill9_mid_append_recovery_loop(tmp_path):
    """A handful of kill -9-mid-append crash/recover cycles: every acked
    create and bind survives; recovery never classifies a process kill
    as media corruption (tier-1-speed variant of the 50x loop below)."""
    _run_kill_loop(tmp_path, cycles=4)


@pytest.mark.slow
def test_kill9_mid_append_recovery_loop_50x(tmp_path):
    """The acceptance bar: 50 consecutive kill -9 mid-append cycles with
    zero acked-write loss and zero double-binds on the ledger."""
    summary = _run_kill_loop(tmp_path, cycles=50)
    assert summary["bound"] >= 50


# ---------------------------------------------------------------------------
# byte-level crash points (satellite: property sweep + legacy format)
# ---------------------------------------------------------------------------

def test_every_crash_point_recovers_exactly_the_acked_prefix(tmp_path):
    """Truncate a live WAL at EVERY byte offset of the final-record
    region: recovery must equal exactly the acked prefix (records whose
    bytes fully landed), never lose an acked write, and never classify
    the torn tail as mid-log corruption."""
    prefix = str(tmp_path / "sweep")
    wal = make_wal(tmp_path, "sweep")
    acks = []  # (end_offset_of_record, ack dict)
    for i in range(8):
        rv = i + 1
        pod = make_pod(f"p{i}")
        pod.metadata.resource_version = rv
        wal.append(rv, "create", "pods", pod)
        acks.append((
            os.path.getsize(wal.log_path),
            {"op": "create", "kind": "pods", "key": f"default/p{i}", "rv": rv},
        ))
    wal.close()
    size = os.path.getsize(prefix + ".wal")
    last_start = acks[-2][0]  # byte where the final record begins
    scratch = str(tmp_path / "cut")
    for cut in range(last_start, size + 1):
        shutil.copyfile(prefix + ".wal", scratch + ".wal")
        truncate_log_at(scratch + ".wal", cut)
        report = WriteAheadLog.recover_report(scratch)
        assert not report.corrupt, f"cut@{cut}: torn tail misread as corrupt"
        acked = [a for end, a in acks if end <= cut]
        want_rv = acked[-1]["rv"] if acked else 0
        # recovery must hold AT LEAST every acked record; one extra is
        # legal (a complete record whose trailing newline the crash ate
        # — durable but never acknowledged), more than one is not
        assert want_rv <= report.rv <= want_rv + 1, (
            f"cut@{cut}: recovered rv={report.rv}, acked prefix rv={want_rv}"
        )
        state = {
            "rv": report.rv,
            "commit": report.commit,
            "objects": {
                kind: {
                    key: o.metadata.resource_version for key, o in d.items()
                }
                for kind, d in report.objects.items()
            },
        }
        losses = consistency_check.check(acked, state)
        assert not losses, f"cut@{cut}: {losses}"


def test_legacy_pre_crc_wal_still_recovers(tmp_path):
    """A v1 (pre-CRC, raw-JSON-lines) log recovers unchanged, and a new
    writer appends v2 frames after it — the reader sniffs per line."""
    from kubernetes_tpu.api import serialization

    prefix = str(tmp_path / "legacy")
    with open(prefix + ".wal", "w", encoding="utf-8") as f:
        for i in range(5):
            f.write(json.dumps({
                "rv": i + 1, "verb": "create", "kind": "pods",
                "obj": serialization.encode(make_pod(f"old{i}")),
            }) + "\n")
    report = WriteAheadLog.recover_report(prefix)
    assert report.rv == 5 and not report.corrupt
    assert len(report.objects["pods"]) == 5

    wal = make_wal(tmp_path, "legacy")
    wal.append(6, "create", "pods", make_pod("new0"))
    wal.close()
    report = WriteAheadLog.recover_report(prefix)
    assert report.rv == 6 and not report.corrupt
    names = {p.metadata.name for p in report.objects["pods"].values()}
    assert names == {"old0", "old1", "old2", "old3", "old4", "new0"}


# ---------------------------------------------------------------------------
# bit-flip mid-log: refuse-to-lie + heal-by-resync
# ---------------------------------------------------------------------------

def test_bit_flip_midlog_recovers_longest_valid_prefix(tmp_path):
    prefix = str(tmp_path / "flip")
    wal = make_wal(tmp_path, "flip")
    for i in range(10):
        wal.append(i + 1, "create", "pods", make_pod(f"p{i}"))
    wal.close()
    bit_flip_record(prefix + ".wal", 3)
    report = WriteAheadLog.recover_report(prefix)
    # valid acked records exist AFTER the damage: this is mid-log
    # corruption, not a torn tail — serve the honest prefix and say so
    assert report.corrupt and report.bad_records >= 1
    assert report.rv == 3
    assert set(report.objects["pods"]) == {
        "default/p0", "default/p1", "default/p2"
    }
    # the recovered server carries the promotion bar
    srv = APIServer.recover(prefix)
    assert srv.disk_corrupt


def test_corrupt_replica_heals_via_resync_and_promotes(tmp_path):
    """DiskCorrupt bars promotion until the replication snapshot-resync
    from a healthy leader replaces the state — then the bar lifts."""
    primary = APIServer()
    for i in range(6):
        primary.create("pods", make_pod(f"p{i}"))
    listener = ReplicationListener(heartbeat_s=0.1)
    listener.attach(primary)
    follower = Follower(
        listener.address,
        lease_s=30.0,
        wal=make_wal(tmp_path, "healme"),
        disk_corrupt=True,
    ).start()
    try:
        assert follower.disk_corrupt
        assert follower.promote() is None  # barred while corrupt
        assert wait_until(lambda: not follower.disk_corrupt, 10), (
            "snapshot resync never lifted the DiskCorrupt bar"
        )
        promoted = follower.promote()
        assert promoted is not None
        assert len(promoted.list("pods", "default")[0]) == 6
    finally:
        follower.stop()
        listener.close()


def test_follower_own_disk_failure_bars_promotion_keeps_serving(tmp_path):
    """A follower whose OWN wal append fails fail-stops durability only:
    it keeps tailing in memory (reads/watch stay live) but is barred
    from promotion permanently."""
    primary = APIServer()
    listener = ReplicationListener(heartbeat_s=0.1)
    listener.attach(primary)
    wal = make_wal(tmp_path, "failfoll")
    inj = DiskFaultInjector(fail_writes=(0,)).install(wal)
    follower = Follower(listener.address, lease_s=30.0, wal=wal).start()
    try:
        assert wait_until(follower._synced.is_set, 10)
        primary.create("pods", make_pod("after-sync"))
        assert wait_until(lambda: follower.disk_failed, 10), (
            "WAL append failure never flipped disk_failed"
        )
        # in-memory replication still tracked the write...
        assert wait_until(
            lambda: follower.list_kind("pods")[1]
            >= primary.resource_version,
            10,
        )
        # ...but this replica can never again vouch for durability
        assert follower.promote() is None
    finally:
        inj.uninstall()
        follower.stop()
        listener.close()


# ---------------------------------------------------------------------------
# fail-stop fsync discipline (fsyncgate) + leader step-down
# ---------------------------------------------------------------------------

def test_fsync_failure_poisons_sink_and_store_fail_stops(tmp_path):
    wal = make_wal(tmp_path, "fsyncfail", fsync=True)
    srv = APIServer(wal=wal)
    srv.create("pods", make_pod("before"))
    inj = DiskFaultInjector(fail_all_fsyncs=True).install(wal)
    with pytest.raises(DiskFailed):
        srv.create("pods", make_pod("doomed"))
    assert wal.failed is not None  # poisoned permanently
    assert srv.write_gate.disk_failed
    assert metrics.gauge("store_disk_state") == 2.0
    # fsyncgate: the next write must 503 WITHOUT touching the sink —
    # retrying fsync on dirty pages can never prove durability
    calls = inj.write_calls
    with pytest.raises(DiskFailed):
        srv.create("pods", make_pod("rejected"))
    assert inj.write_calls == calls
    # reads and the already-applied (readable, unacked-durable) state
    # keep serving: fail-stop is a durability statement, not an outage
    names = {p.metadata.name for p in srv.list("pods", "default")[0]}
    assert "before" in names and "doomed" in names
    inj.uninstall()
    # poisoning survives the injector: the sink never comes back
    with pytest.raises((DiskFailed, SinkFailed)):
        srv.create("pods", make_pod("still-rejected"))


def test_leader_with_failed_disk_steps_down_within_retry_periods(tmp_path):
    """The leader releases its lease on disk death, so a disk-healthy
    standby promotes inside retry-periods — NOT after lease expiry."""
    store = APIServer()
    cfg = lambda ident: LeaderElectionConfig(  # noqa: E731
        identity=ident,
        lease_duration=2.0,
        renew_deadline=1.2,
        retry_period=0.2,
        lock_name="disk-chaos",
    )
    disk_ok = [True]
    led_a, led_b = threading.Event(), threading.Event()
    a = LeaderElector(
        store, cfg("leader"), on_started_leading=led_a.set,
        disk_health=lambda: disk_ok[0],
    )
    b = LeaderElector(store, cfg("standby"), on_started_leading=led_b.set)
    ta = threading.Thread(target=a.run, daemon=True)
    tb = threading.Thread(target=b.run, daemon=True)
    ta.start()
    assert wait_until(led_a.is_set, 10)
    tb.start()
    stepdowns0 = metrics.counter(COUNTER_DISK_STEPDOWNS)
    try:
        t0 = time.monotonic()
        disk_ok[0] = False  # the leader's disk dies
        assert wait_until(led_b.is_set, 10), "standby never promoted"
        elapsed = time.monotonic() - t0
        assert metrics.counter(COUNTER_DISK_STEPDOWNS) > stepdowns0
        assert elapsed < cfg("x").lease_duration, (
            f"failover took {elapsed:.2f}s — that's lease-expiry takeover, "
            "not an active disk-death release"
        )
    finally:
        a.stop()
        b.stop()
        ta.join(timeout=5)
        tb.join(timeout=5)


# ---------------------------------------------------------------------------
# ENOSPC / disk-pressure ride-through + heal
# ---------------------------------------------------------------------------

def test_enospc_ride_through_and_heal(tmp_path):
    """ENOSPC mid-append degrades to DiskPressure read-only WITHOUT
    poisoning the sink; once space frees, a retried write reopens the
    store; recovery shows zero acked loss either side of the squeeze."""
    prefix = str(tmp_path / "enospc")
    wal = make_wal(tmp_path, "enospc")
    srv = APIServer(wal=wal)
    # pre-arm a deterministic probe so the auto-clear path is driven by
    # the test, not the real (never-full) filesystem under tmp_path
    probe = fake_probe(prefix, free_bytes=1 << 30)
    srv.disk_probe = probe
    inj = DiskFaultInjector(enospc_after_bytes=700).install(wal)

    created, squeezed = [], False
    for i in range(100):
        try:
            srv.create("pods", make_pod(f"p{i}"))
            created.append(f"p{i}")
        except DiskPressure:
            squeezed = True
            break
    assert squeezed and created, "never hit the ENOSPC squeeze"
    assert srv.write_gate.disk_pressure
    assert wal.failed is None, "ENOSPC pre-fsync must not poison the sink"
    assert probe.under_pressure, (
        "ENOSPC entry must arm the probe's hysteresis or nothing clears"
    )
    assert metrics.gauge("store_disk_state") == 1.0
    assert len(srv.list("pods", "default")[0]) >= len(created)  # reads

    # space still low: writes keep 503ing as DiskPressure
    probe.free[0] = 0
    with pytest.raises(DiskPressure):
        srv.create("pods", make_pod("still-full"))

    # space recovers: the next (client-retried) write reopens the store
    inj.free_space()
    probe.free[0] = 1 << 30
    srv.create("pods", make_pod("healed"))
    assert not srv.write_gate.disk_pressure
    assert metrics.gauge("store_disk_state") == 0.0

    inj.uninstall()
    wal.close()
    report = WriteAheadLog.recover_report(prefix)
    assert not report.corrupt
    names = {p.metadata.name for p in report.objects["pods"].values()}
    for n in created:
        assert n in names, f"acked {n} lost across the ENOSPC squeeze"
    assert "healed" in names


def test_low_watermark_enters_read_only_before_writes_fail(tmp_path):
    """The probe trips the gate on the admission path BEFORE any append
    can hit ENOSPC — the sink is never even touched while gated."""
    prefix = str(tmp_path / "watermark")
    wal = make_wal(tmp_path, "watermark")
    srv = APIServer(wal=wal)
    inj = DiskFaultInjector().install(wal)
    probe = fake_probe(prefix, free_bytes=(32 << 20) - 1)
    assert probe.free[0] < probe.low_bytes
    srv.disk_probe = probe
    with pytest.raises(DiskPressure):
        srv.create("pods", make_pod("early"))
    assert inj.write_calls == 0, "gated write must never reach the sink"
    assert srv.write_gate.disk_pressure
    # hysteresis: recovering past low but under high stays read-only
    probe.free[0] = probe.high_bytes - 1
    with pytest.raises(DiskPressure):
        srv.create("pods", make_pod("between-watermarks"))
    probe.free[0] = probe.high_bytes
    srv.create("pods", make_pod("recovered"))
    assert not srv.write_gate.disk_pressure
    inj.uninstall()


def test_fsync_stall_watchdog_flags_slow_disk(tmp_path):
    """A dying disk stretches fsyncs long before erroring: the watchdog
    gauge flips on a stalled fsync and clears on the next healthy one."""
    wal = make_wal(tmp_path, "stall", fsync=True)
    wal.FSYNC_STALL_S = 0.01
    inj = DiskFaultInjector(slow_fsyncs=(0,), fsync_delay_s=0.05).install(wal)
    stalls0 = metrics.counter(COUNTER_FSYNC_STALLS)
    wal.append(1, "create", "pods", make_pod("slow"))
    assert metrics.counter(COUNTER_FSYNC_STALLS) == stalls0 + 1
    assert metrics.gauge("wal_fsync_stalled") == 1.0
    wal.append(2, "create", "pods", make_pod("fast"))
    assert metrics.gauge("wal_fsync_stalled") == 0.0
    inj.uninstall()
    wal.close()


# ---------------------------------------------------------------------------
# compaction resilience + recovery-signal satellites
# ---------------------------------------------------------------------------

def test_compaction_failure_backs_off_then_recovers(tmp_path, monkeypatch):
    prefix = str(tmp_path / "compact")
    wal = make_wal(tmp_path, "compact", compact_every=3)
    srv = APIServer(wal=wal)
    real_snapshot = wal.write_snapshot
    fails0 = metrics.counter("wal_compaction_failures_total")

    def exploding_snapshot(rv, objects):
        raise OSError("simulated snapshot I/O error")

    monkeypatch.setattr(wal, "write_snapshot", exploding_snapshot)
    for i in range(4):
        srv.create("pods", make_pod(f"p{i}"))
    # the failed compaction must clear the in-flight flag (no wedge)...
    assert wait_until(lambda: not srv._compacting.is_set(), 10)
    assert wait_until(
        lambda: metrics.counter("wal_compaction_failures_total") > fails0, 10
    )
    assert srv._compact_backoff_until > time.monotonic(), (
        "failure must arm backoff, not retry hot"
    )
    # ...and the append path kept working throughout
    srv.create("pods", make_pod("during-backoff"))
    # past the backoff with a healthy disk, the next write compacts
    monkeypatch.setattr(wal, "write_snapshot", real_snapshot)
    srv._compact_backoff_until = 0.0
    srv.create("pods", make_pod("trigger"))
    assert wait_until(
        lambda: os.path.exists(prefix + ".snapshot.json"), 10
    ), "compaction never recovered after the backoff"
    wal.close()


def test_orphaned_compaction_tmp_files_swept_at_open(tmp_path):
    prefix = str(tmp_path / "orphans")
    for suffix in (".snapshot.json.tmp", ".wal.tmp"):
        with open(prefix + suffix, "w") as f:
            f.write("{half-written garbage from a crash mid-compaction")
    sweeps0 = metrics.counter(COUNTER_TMP_SWEEPS)
    wal = WriteAheadLog(prefix, native=False, fsync=False)
    assert not os.path.exists(prefix + ".snapshot.json.tmp")
    assert not os.path.exists(prefix + ".wal.tmp")
    assert metrics.counter(COUNTER_TMP_SWEEPS) == sweeps0 + 2
    wal.close()


def test_recover_staleness_retries_exhausted_is_surfaced(
    tmp_path, monkeypatch
):
    """recover_full exhausting its 10 staleness retries must say so
    (report flag + counter), never silently return possibly-torn state."""
    prefix = str(tmp_path / "stale")
    wal = make_wal(tmp_path, "stale")
    wal.write_snapshot(5, {"pods": [make_pod("p0")]})
    wal.close()

    def always_stale(path):
        return RecoveryReport(rv=4, snap_rv=4)  # never matches disk's rv=5

    monkeypatch.setattr(
        WriteAheadLog, "_recover_once", staticmethod(always_stale)
    )
    exhausted0 = metrics.counter(COUNTER_RETRIES_EXHAUSTED)
    report = WriteAheadLog.recover_report(prefix)
    assert report.retries_exhausted
    assert metrics.counter(COUNTER_RETRIES_EXHAUSTED) == exhausted0 + 1


# ---------------------------------------------------------------------------
# fenced backup / restore: disaster recovery without split-brain
# ---------------------------------------------------------------------------

def test_restore_structurally_rejects_every_pre_restore_fence(tmp_path):
    # a live cluster with pods and a scheduler holding the lease
    src = APIServer()
    for i in range(4):
        src.create("pods", make_pod(f"p{i}"))
    src.create("leases", Lease(
        metadata=v1.ObjectMeta(name="sched", namespace="kube-system"),
        holder_identity="sched-1",
        lease_transitions=3,
    ))
    zombie_fence = BindFence(
        namespace="kube-system", name="sched", identity="sched-1",
        transitions=3,
    )
    # sanity: the fence is valid against the LIVE cluster
    errs = src.bind_pods(
        [v1.Binding(pod_name="p0", pod_namespace="default",
                    target_node="n0")],
        fence=zombie_fence,
    )
    assert errs == [None]

    # disaster: online backup, restore into a fresh WAL, recover
    image = backup.backup_from_server(src, str(tmp_path / "img.json"))
    summary = backup.restore_into(
        backup.load_backup(str(tmp_path / "img.json")),
        str(tmp_path / "restored"),
    )
    assert summary["term"] == image["term"] + 1  # durable epoch bump
    assert summary["fenced_leases"] == 1
    restored = APIServer.recover(str(tmp_path / "restored"))
    assert restored.resource_version == image["rv"]
    assert not restored.disk_corrupt

    # EVERY pre-restore token is structurally rejected: the restored
    # lease has no holder and a bumped transition count, so the zombie's
    # identity AND transitions both mismatch — no wall clocks involved
    with pytest.raises(LeaderFenced):
        restored.bind_pods(
            [v1.Binding(pod_name="p1", pod_namespace="default",
                        target_node="n1")],
            fence=zombie_fence,
        )
    # the restored cluster itself is fully writable (unfenced paths)
    restored.create("pods", make_pod("post-restore"))
    assert restored.bind_pods([
        v1.Binding(pod_name="p1", pod_namespace="default", target_node="n1")
    ]) == [None]


def test_restore_refuses_to_clobber_without_force(tmp_path):
    src = APIServer()
    src.create("pods", make_pod("keep"))
    image = backup.backup_from_server(src, str(tmp_path / "img.json"))
    wal = make_wal(tmp_path, "occupied")
    wal.append(1, "create", "pods", make_pod("resident"))
    wal.close()
    with pytest.raises(FileExistsError):
        backup.restore_into(image, str(tmp_path / "occupied"))
    with pytest.raises(NotFound):
        # the resident log was NOT touched by the refused restore
        APIServer.recover(str(tmp_path / "occupied")).get(
            "pods", "default", "keep"
        )
    backup.restore_into(image, str(tmp_path / "occupied"), force=True)
    restored = APIServer.recover(str(tmp_path / "occupied"))
    assert restored.get("pods", "default", "keep").metadata.name == "keep"


def test_offline_backup_of_corrupt_wal_flags_the_image(tmp_path):
    prefix = str(tmp_path / "sick")
    wal = make_wal(tmp_path, "sick")
    for i in range(6):
        wal.append(i + 1, "create", "pods", make_pod(f"p{i}"))
    wal.close()
    bit_flip_record(prefix + ".wal", 2)
    image = backup.backup_from_wal(prefix, str(tmp_path / "sick.json"))
    assert image.get("source_corrupt") is True
    assert image["rv"] == 2  # honest: the longest valid prefix only
