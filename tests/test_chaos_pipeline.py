"""Full-pipeline chaos: degraded-store ride-through end to end.

PR 1 gave the API store an honest degraded read-only mode (retryable 503 /
QuorumLost). These scenarios exercise every consumer riding that window
out: the scheduler's pending-bind buffer + circuit breaker
(scheduler/ridethrough.py), the node-lifecycle controller's eviction-storm
safeguards (rate limiter + partial-disruption halt), kubelet heartbeat
retries, and the informer's relist-on-flap loop.

The invariant checker asserts, per scenario:
  * zero acked-bind loss  — every bind the store ACKED is still bound
  * zero double-binds     — no pod's bind ever applied twice
  * zero evictions during a control-plane-only outage
  * every pod from a genuinely dead node reschedules
"""

import threading
import time
from collections import defaultdict

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.client import APIServer
from kubernetes_tpu.client.apiserver import Expired
from kubernetes_tpu.client.informers import (
    RELIST_BACKOFF_INITIAL,
    SharedInformer,
)
from kubernetes_tpu.controller.nodelifecycle import (
    GAUGE_PARTIAL_DISRUPTION,
    NodeLifecycleController,
)
from kubernetes_tpu.kubelet.kubelet import NODE_LEASE_NS, NodeAgentPool
from kubernetes_tpu.runtime.consensus import DegradedWrites, QuorumLost
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler
from kubernetes_tpu.utils.metrics import metrics


def wait_until(fn, timeout=60.0, period=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(period)
    return False


def make_pod(name, cpu="100m", labels=None):
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, labels=labels or {}),
        spec=v1.PodSpec(containers=[v1.Container(requests={"cpu": cpu})]),
    )


def _bound_count(server):
    return server.count("pods", lambda p: bool(p.spec.node_name))


class _GateConsensus:
    """Minimal consensus stand-in for WriteGate.attach_consensus: flips
    the store between healthy and degraded read-only (the same contract
    runtime/consensus.py arms — writes 503 retryably, reads serve)."""

    def __init__(self):
        self.degraded = False

    def check_writable(self):
        if self.degraded:
            raise DegradedWrites(
                "chaos: store degraded read-only — retry later"
            )


class ChaosStore(APIServer):
    """In-process store with chaos knobs + the bind-invariant ledger.

    ``acked_binds`` maps pod uid -> node for every bind the store
    ACKNOWLEDGED to its caller (error None returned). ``applied_binds``
    counts every application per uid, acked or not. ``fail_next_bind``
    injects one failure into the next bind_pods call:

      "degraded"     refuse BEFORE applying anything (retryable; the
                     store stays read-only until recover())
      "quorum_lost"  apply locally, then lose the quorum ack — the
                     caller sees QuorumLost (outcome unknown) while the
                     binds are readable in the store
    """

    def __init__(self):
        super().__init__()
        self.gate = _GateConsensus()
        self.write_gate.attach_consensus(self.gate)
        self.acked_binds = {}
        self.applied_binds = defaultdict(int)
        self.fail_next_bind = None
        self._chaos_lock = threading.Lock()

    def degrade(self):
        self.gate.degraded = True

    def recover(self):
        self.gate.degraded = False

    def bind_pods(self, bindings, fence=None):
        with self._chaos_lock:
            mode, self.fail_next_bind = self.fail_next_bind, None
        if mode == "degraded":
            self.gate.degraded = True
            raise DegradedWrites("chaos: bind refused, store degraded")
        errors = super().bind_pods(bindings, fence=fence)
        for b, err in zip(bindings, errors):
            if err is None:
                self.applied_binds[b.pod_uid] += 1
        if mode == "quorum_lost":
            self.gate.degraded = True
            raise QuorumLost("chaos: bind applied locally, quorum ack lost")
        for b, err in zip(bindings, errors):
            if err is None:
                self.acked_binds[b.pod_uid] = b.target_node
        return errors


def assert_bind_invariants(store: ChaosStore, allow_deleted=False):
    """Zero acked-bind loss + zero double-binds against the live store."""
    pods, _ = store.list("pods")
    by_uid = {p.metadata.uid: p for p in pods}
    lost = []
    for uid, node in store.acked_binds.items():
        cur = by_uid.get(uid)
        if cur is None:
            if not allow_deleted:
                lost.append((uid, node, "pod gone"))
            continue
        if cur.spec.node_name != node:
            lost.append((uid, node, f"bound to {cur.spec.node_name!r}"))
    assert not lost, f"acked binds lost: {lost}"
    doubles = {u: n for u, n in store.applied_binds.items() if n > 1}
    assert not doubles, f"double-applied binds: {doubles}"


def _watch_deletions(store, sink):
    w = store.watch("pods")

    def drain():
        for ev in w:
            if ev.type == "DELETED":
                sink.append(ev.object.metadata.key)

    threading.Thread(target=drain, daemon=True).start()
    return w


# -- scenario 1: degrade the store mid-wave, then recover ---------------------


@pytest.mark.slow
def test_degrade_store_mid_wave_then_recover_drains_buffer():
    """Acceptance scenario. A wave's bulk bind hits a degraded store
    (refused before anything applied). The wave is NOT failed: every
    placement parks in the pending-bind buffer, the breaker pauses
    dispatch, the partial-disruption threshold halts evictions while
    kubelet heartbeats 503 — and within 5 s of writes reopening the
    buffer drains and placing resumes. Zero acked-bind loss, zero
    double-binds, zero evictions."""
    store = ChaosStore()
    pool = NodeAgentPool(
        store, heartbeat_interval=0.2, housekeeping_interval=0.1
    )
    for i in range(8):
        pool.add_node(f"node-{i}")
    nlc = NodeLifecycleController(
        store,
        node_monitor_period=0.1,
        node_monitor_grace_period=0.8,
        pod_eviction_timeout=0.3,
    )
    n = 60
    for i in range(n):
        store.create("pods", make_pod(f"wave-{i}"))
    deletions = []
    w = _watch_deletions(store, deletions)
    store.fail_next_bind = "degraded"
    sched = Scheduler(store, KubeSchedulerConfiguration())
    pool.start()
    sched.start()
    nlc.start()
    try:
        # the first bind wave trips the breaker; nothing applied
        assert wait_until(
            lambda: metrics.gauge("scheduler_bind_breaker_state") == 1.0, 15
        ), "breaker never opened on the degraded bind"
        assert _bound_count(store) == 0
        assert metrics.gauge("scheduler_pending_binds") >= 1
        # leases go stale while the store is read-only: the lifecycle
        # controller must read that as a control-plane outage and halt
        assert wait_until(
            lambda: metrics.gauge(GAUGE_PARTIAL_DISRUPTION) == 1.0, 15
        ), "partial-disruption mode never armed during the outage"
        assert _bound_count(store) == 0, "read-only store accepted a bind"
        store.recover()
        t0 = time.monotonic()
        assert wait_until(lambda: _bound_count(store) == n, 15), (
            f"only {_bound_count(store)}/{n} bound after recovery"
        )
        assert wait_until(
            lambda: metrics.gauge("scheduler_pending_binds") == 0.0
            and metrics.gauge("scheduler_bind_breaker_state") == 0.0,
            5,
        ), "pending-bind buffer never drained / breaker never closed"
        elapsed = time.monotonic() - t0
        assert elapsed <= 5.0, (
            f"resume-placing budget blown: {elapsed:.1f}s > 5s after reopen"
        )
        print(
            f"\n[chaos] degrade-mid-wave: {n} pods drained+bound "
            f"{elapsed:.2f}s after writes reopened",
            flush=True,
        )
        assert not deletions, (
            f"control-plane-only outage must evict nothing: {deletions}"
        )
        assert_bind_invariants(store)
        # the fleet recovers: taints lifted once heartbeats resume
        assert wait_until(
            lambda: all(
                not nd.spec.taints for nd in store.list("nodes")[0]
            ),
            15,
        ), "stale taints after recovery"
    finally:
        nlc.stop()
        sched.stop()
        pool.stop()
        w.stop()


# -- scenario 2: quorum lost mid-bind (applied, unacked) ----------------------


@pytest.mark.slow
def test_quorum_lost_mid_bind_reconciles_without_double_bind():
    """The unknown-outcome path: the wave's binds APPLY locally but the
    quorum ack is lost. The scheduler buffers them, reads each pod back
    on recovery, detects the landed binds, and never replays them —
    every pod bound exactly once."""
    store = ChaosStore()
    pool = NodeAgentPool(store, housekeeping_interval=0.1)
    for i in range(6):
        pool.add_node(f"node-{i}")
    n = 40
    for i in range(n):
        store.create("pods", make_pod(f"ql-{i}"))
    store.fail_next_bind = "quorum_lost"
    landed0 = metrics.counter(
        "scheduler_bind_reconcile_total", {"outcome": "landed"}
    )
    sched = Scheduler(store, KubeSchedulerConfiguration())
    pool.start()
    sched.start()
    try:
        assert wait_until(
            lambda: metrics.gauge("scheduler_bind_breaker_state") == 1.0, 15
        )
        applied = sum(store.applied_binds.values())
        assert applied >= 1, "chaos hook never saw an applied bind"
        time.sleep(0.4)
        store.recover()
        assert wait_until(lambda: _bound_count(store) == n, 15), (
            f"only {_bound_count(store)}/{n} bound after recovery"
        )
        assert wait_until(
            lambda: metrics.gauge("scheduler_pending_binds") == 0.0, 5
        )
        # the reconciler confirmed the applied binds from read-back
        landed = metrics.counter(
            "scheduler_bind_reconcile_total", {"outcome": "landed"}
        )
        assert landed - landed0 >= 1, "no buffered bind was confirmed landed"
        assert all(c == 1 for c in store.applied_binds.values()), (
            f"double-applied binds: "
            f"{ {u: c for u, c in store.applied_binds.items() if c > 1} }"
        )
        assert_bind_invariants(store)
    finally:
        sched.stop()
        pool.stop()


# -- scenario 3: eviction storm halted, then rate-limited drain ---------------


@pytest.mark.slow
def test_eviction_storm_halts_then_drains_rate_limited():
    """>55% of lease-managed nodes going dark in one pass is a
    control-plane-outage signature: evictions halt. When most of the
    fleet comes back, the genuinely dead minority drains through the
    rate limiter and their pods are evicted."""
    store = ChaosStore()
    pool = NodeAgentPool(
        store, heartbeat_interval=0.1, housekeeping_interval=0.1
    )
    names = [f"sn-{i}" for i in range(10)]
    for nm in names:
        pool.add_node(nm)
    nlc = NodeLifecycleController(
        store,
        node_monitor_period=0.05,
        node_monitor_grace_period=0.5,
        # eviction timeout comfortably past the grace period so the
        # partial-disruption threshold always arms BEFORE any node of the
        # simultaneously-dying majority reaches eviction eligibility
        pod_eviction_timeout=0.4,
        eviction_limiter_qps=50.0,
    )
    sched = Scheduler(store, KubeSchedulerConfiguration())
    pool.start()
    sched.start()
    nlc.start()
    try:
        for i in range(20):
            store.create("pods", make_pod(f"victim-{i}"))
        assert wait_until(lambda: _bound_count(store) == 20, 30)
        ev0 = metrics.counter("node_lifecycle_evictions_total")
        # 7 of 10 kubelets die at once (their node objects stay)
        dead = names[:7]
        for nm in dead:
            pool.remove_node(nm)
        assert wait_until(
            lambda: metrics.gauge(GAUGE_PARTIAL_DISRUPTION) == 1.0, 10
        ), "mass unhealthiness never armed partial-disruption mode"
        time.sleep(1.0)  # well past grace + eviction timeout
        assert metrics.counter("node_lifecycle_evictions_total") == ev0, (
            "evictions ran during the halted (partial-disruption) window"
        )
        # 5 of the 7 come back: fraction drops to 2/10 — the halt lifts
        # and ONLY the genuinely dead pair drains (rate-limited)
        for nm in dead[:5]:
            pool.add_node(nm, register=False)
        still_dead = set(dead[5:])
        assert wait_until(
            lambda: store.count(
                "pods", lambda p: p.spec.node_name in still_dead
            )
            == 0,
            20,
        ), "pods on genuinely dead nodes were never evicted"
        assert metrics.counter("node_lifecycle_evictions_total") > ev0
        # the replaced kubelets' nodes recover (no lingering taints)
        assert wait_until(
            lambda: all(
                not nd.spec.taints
                for nd in store.list("nodes")[0]
                if nd.metadata.name not in still_dead
            ),
            15,
        )
    finally:
        nlc.stop()
        sched.stop()
        pool.stop()


# -- scenario 4: kill a kubelet mid-bind; everything reschedules --------------


@pytest.mark.slow
def test_kill_kubelet_mid_bind_reschedules_everything():
    """One node dies with binds in flight. The lifecycle controller
    (rate-limited, below the disruption threshold) evicts its pods and
    the workload controller replaces them on survivors — every pod from
    the dead node reschedules, and no acked bind is lost on the
    survivors."""
    from kubernetes_tpu.controller.replicaset import ReplicaSetController

    store = ChaosStore()
    pool = NodeAgentPool(
        store, heartbeat_interval=0.1, housekeeping_interval=0.1
    )
    names = [f"kn-{i}" for i in range(4)]
    for nm in names:
        pool.add_node(nm)
    nlc = NodeLifecycleController(
        store,
        node_monitor_period=0.1,
        node_monitor_grace_period=0.8,
        pod_eviction_timeout=0.3,
        eviction_limiter_qps=50.0,
    )
    sched = Scheduler(store, KubeSchedulerConfiguration())
    rs = ReplicaSetController(store)
    pool.start()
    sched.start()
    rs.start()
    nlc.start()
    try:
        store.create(
            "replicasets",
            v1.ReplicaSet(
                metadata=v1.ObjectMeta(name="web"),
                spec=v1.ReplicaSetSpec(
                    replicas=10,
                    selector={"app": "web"},
                    template=v1.PodTemplateSpec(
                        metadata=v1.ObjectMeta(labels={"app": "web"}),
                        spec=v1.PodSpec(
                            containers=[v1.Container(requests={"cpu": "100m"})]
                        ),
                    ),
                ),
            ),
        )
        # kill mid-burst: some replicas bound, some still binding
        assert wait_until(lambda: _bound_count(store) >= 3, 30)
        pool.remove_node("kn-0")

        def converged():
            pods, _ = store.list("pods")
            live = [
                p
                for p in pods
                if p.metadata.labels.get("app") == "web"
                and p.metadata.deletion_timestamp is None
                and p.spec.node_name
                and p.spec.node_name != "kn-0"
            ]
            return len(live) >= 10

        assert wait_until(converged, 90), (
            "replicas never re-landed on surviving nodes"
        )
        # the dead node's pods were evicted, not stranded
        assert wait_until(
            lambda: store.count(
                "pods",
                lambda p: p.spec.node_name == "kn-0"
                and p.metadata.deletion_timestamp is None,
            )
            == 0,
            30,
        )
        assert_bind_invariants(store, allow_deleted=True)
    finally:
        nlc.stop()
        rs.stop()
        sched.stop()
        pool.stop()


# -- scenario 5: kubelet heartbeat rides through transient 503s ---------------


def test_kubelet_heartbeat_retries_transient_503():
    class FlakyLeaseStore(APIServer):
        def __init__(self):
            super().__init__()
            self.fail_renewals = 0

        def guaranteed_update(self, kind, namespace, name, mutate):
            if kind == "leases" and self.fail_renewals > 0:
                self.fail_renewals -= 1
                raise DegradedWrites("chaos: transient 503")
            return super().guaranteed_update(kind, namespace, name, mutate)

    store = FlakyLeaseStore()
    pool = NodeAgentPool(store)
    kl = pool.add_node("hb-node")
    t = store.get("leases", NODE_LEASE_NS, "hb-node").renew_time + 5.0
    store.fail_renewals = 2
    r0 = metrics.counter("kubelet_heartbeat_retries_total")
    kl.heartbeat(now=t)
    assert store.get("leases", NODE_LEASE_NS, "hb-node").renew_time == t, (
        "renewal dropped despite transient 503s"
    )
    assert metrics.counter("kubelet_heartbeat_retries_total") - r0 == 2


def test_kubelet_heartbeat_fast_drops_during_persistent_outage():
    """A persistent outage (write gate reports degraded) must not stall
    the shared heartbeat loop in retry sleeps: the renewal drops fast
    and the NEXT beat retries."""
    store = ChaosStore()
    pool = NodeAgentPool(store)
    kl = pool.add_node("hb2-node")
    before = store.get("leases", NODE_LEASE_NS, "hb2-node").renew_time
    store.degrade()
    d0 = metrics.counter("kubelet_heartbeat_renewals_dropped_total")
    t0 = time.monotonic()
    kl.heartbeat(now=before + 5.0)
    assert time.monotonic() - t0 < 0.2, "heartbeat stalled in retries"
    assert metrics.counter("kubelet_heartbeat_renewals_dropped_total") > d0
    assert store.get("leases", NODE_LEASE_NS, "hb2-node").renew_time == before
    store.recover()
    kl.heartbeat(now=before + 6.0)
    assert (
        store.get("leases", NODE_LEASE_NS, "hb2-node").renew_time
        == before + 6.0
    )


# -- scenario 6: informer watch flap / Expired relist -------------------------


def test_informer_expired_relist_backoff_grows_and_resets():
    """Consecutive Expired (410) watch attempts grow the relist backoff;
    the first event through a recovered watch resets it to the floor."""

    class ExpiringStore(APIServer):
        def __init__(self):
            super().__init__()
            self.expired_budget = 0

        def watch(self, kind, from_version=0):
            if self.expired_budget > 0:
                self.expired_budget -= 1
                raise Expired("chaos: resourceVersion too old")
            return super().watch(kind, from_version)

    store = ExpiringStore()
    store.create("pods", make_pod("seed"))
    inf = SharedInformer(store, "pods")
    seen = []
    inf.add_handler(on_add=lambda p: seen.append(p.metadata.name))
    e0 = metrics.counter(
        "informer_relists_total", {"kind": "pods", "reason": "expired"}
    )
    store.expired_budget = 3
    inf.start()
    try:
        assert wait_until(lambda: inf._watcher is not None, 10), (
            "informer never established a watch through the Expired storm"
        )
        assert (
            metrics.counter(
                "informer_relists_total", {"kind": "pods", "reason": "expired"}
            )
            - e0
            == 3
        )
        assert inf._relist_backoff > RELIST_BACKOFF_INITIAL, (
            "backoff did not grow across consecutive Expired relists"
        )
        # reset-on-success: a delivered event proves the stream is healthy
        store.create("pods", make_pod("after-recovery"))
        assert wait_until(lambda: "after-recovery" in seen, 5)
        assert inf._relist_backoff == RELIST_BACKOFF_INITIAL
    finally:
        inf.stop()


def test_informer_watch_flap_resumes_without_relist():
    """A watch stream dying WITHOUT stop() (connection flap) RESUMES at
    last_resource_version instead of re-listing (PR-6 watch-cache
    semantics): zero relists, zero handler churn, and nothing created
    during the gap is missed — the event window replays it."""
    store = APIServer()
    store.create("pods", make_pod("a"))
    inf = SharedInformer(store, "pods")
    seen = []
    inf.add_handler(on_add=lambda p: seen.append(p.metadata.name))
    inf.start()
    try:
        assert wait_until(lambda: inf.has_synced(), 5)
        store.create("pods", make_pod("b"))
        assert wait_until(lambda: "b" in seen, 5)
        relists0 = sum(
            metrics.counter(
                "informer_relists_total", {"kind": "pods", "reason": r}
            )
            for r in ("watch-closed", "window_expired", "expired")
        )
        resumes0 = metrics.counter(
            "informer_watch_resumes_total", {"kind": "pods"}
        )
        adds_before_flap = len(seen)
        flapped = inf._watcher
        flapped.stop()  # the stream dies under the informer
        # created DURING the gap: the resume replays it from watch history
        store.create("pods", make_pod("c"))
        assert wait_until(lambda: "c" in seen, 10), (
            "event after the flap never delivered"
        )
        assert inf._watcher is not flapped
        assert (
            metrics.counter(
                "informer_watch_resumes_total", {"kind": "pods"}
            )
            > resumes0
        ), "flap did not go through the resume path"
        relists1 = sum(
            metrics.counter(
                "informer_relists_total", {"kind": "pods", "reason": r}
            )
            for r in ("watch-closed", "window_expired", "expired")
        )
        assert relists1 == relists0, (
            "a watch flap must resume from the event window, not re-list"
        )
        # no Replace churn: exactly the one new add was delivered
        assert len(seen) == adds_before_flap + 1
        assert inf.get("default/c") is not None
    finally:
        inf.stop()


# -- soak: repeated degrade/recover cycles (slow tier) ------------------------


@pytest.mark.slow
def test_soak_degrade_recover_cycles_no_loss_no_double_bind():
    """Alternating Degraded / QuorumLost outages across several burst
    waves: after every recovery the invariants hold and the cluster
    fully converges."""
    store = ChaosStore()
    pool = NodeAgentPool(store, housekeeping_interval=0.1)
    for i in range(8):
        pool.add_node(f"soak-{i}")
    sched = Scheduler(store, KubeSchedulerConfiguration())
    pool.start()
    sched.start()
    total = 0
    try:
        for cycle in range(4):
            store.fail_next_bind = (
                "quorum_lost" if cycle % 2 else "degraded"
            )
            for i in range(50):
                store.create("pods", make_pod(f"soak-c{cycle}-{i}"))
            total += 50
            assert wait_until(
                lambda: metrics.gauge("scheduler_bind_breaker_state") == 1.0,
                15,
            ), f"cycle {cycle}: breaker never opened"
            time.sleep(0.3)
            store.recover()
            assert wait_until(
                lambda: _bound_count(store) == total, 20
            ), f"cycle {cycle}: {_bound_count(store)}/{total} bound"
            assert wait_until(
                lambda: metrics.gauge("scheduler_pending_binds") == 0.0, 5
            )
            assert_bind_invariants(store)
    finally:
        sched.stop()
        pool.stop()
