"""REST façade + client + kubectl: the full HTTP path.

Mirrors the reference's integration topology (test/integration/: real
in-process apiserver over HTTP, real components as clients) — here the
scheduler itself runs against the REST client to prove every component
works across the wire, not just in-process.
"""

import io
import json
import time
import urllib.request
from contextlib import redirect_stdout

import pytest

from kubernetes_tpu.api import serialization as codec
from kubernetes_tpu.api.objects import (
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.apiserver import RESTClient, serve
from kubernetes_tpu.client.apiserver import AlreadyExists, NotFound
from kubernetes_tpu.cmd.kubectl import main as kubectl_main
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler


@pytest.fixture
def rest():
    srv, port, store = serve(port=0)
    yield RESTClient(f"http://127.0.0.1:{port}"), store, port
    srv.shutdown()


def make_node(name):
    return Node(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={"cpu": "4", "memory": "32Gi", "pods": 110}),
    )


def make_pod(name):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(containers=[Container(requests={"cpu": "100m"})]),
    )


def test_rest_crud_roundtrip(rest):
    client, _store, _port = rest
    client.create("nodes", make_node("n0"))
    got = client.get("nodes", "", "n0")
    assert got.metadata.name == "n0"
    assert got.status.allocatable["cpu"] == "4"
    with pytest.raises(AlreadyExists):
        client.create("nodes", make_node("n0"))

    def mutate(n):
        n.spec.unschedulable = True
        return n

    client.guaranteed_update("nodes", "", "n0", mutate)
    assert client.get("nodes", "", "n0").spec.unschedulable is True
    objs, rv = client.list("nodes")
    assert len(objs) == 1 and rv > 0
    client.delete("nodes", "", "n0")
    with pytest.raises(NotFound):
        client.get("nodes", "", "n0")


def test_rest_watch_streams_events(rest):
    client, _store, _port = rest
    w = client.watch("pods")
    time.sleep(0.2)
    client.create("pods", make_pod("a"))
    ev = w.get(timeout=5)
    assert ev is not None and ev.type == "ADDED"
    assert ev.object.metadata.name == "a"
    client.delete("pods", "default", "a")
    types = set()
    for _ in range(2):
        ev = w.get(timeout=5)
        if ev:
            types.add(ev.type)
    assert "DELETED" in types
    w.stop()


def test_scheduler_runs_over_rest(rest):
    client, _store, _port = rest
    for i in range(3):
        client.create("nodes", make_node(f"n{i}"))
    sched = Scheduler(client, KubeSchedulerConfiguration())
    sched.start()
    try:
        client.create("pods", make_pod("p"))
        deadline = time.time() + 20
        while time.time() < deadline:
            if client.get("pods", "default", "p").spec.node_name:
                break
            time.sleep(0.05)
        assert client.get("pods", "default", "p").spec.node_name
    finally:
        sched.stop()


def test_binding_subresource(rest):
    client, _store, port = rest
    client.create("nodes", make_node("n0"))
    client.create("pods", make_pod("p"))
    body = json.dumps(
        {"podName": "p", "podNamespace": "default", "targetNode": "n0"}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/namespaces/default/pods/p/binding",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    resp = urllib.request.urlopen(req, timeout=5)
    assert resp.status == 201
    assert client.get("pods", "default", "p").spec.node_name == "n0"


def test_kubectl_get_apply_taint(rest, tmp_path):
    client, _store, port = rest
    server_flag = f"--server=http://127.0.0.1:{port}"
    manifest = tmp_path / "node.json"
    manifest.write_text(
        json.dumps(codec.encode(make_node("kn")))
    )
    assert kubectl_main([server_flag, "apply", "-f", str(manifest)]) == 0
    out = io.StringIO()
    with redirect_stdout(out):
        assert kubectl_main([server_flag, "get", "nodes"]) == 0
    assert "kn" in out.getvalue()
    assert (
        kubectl_main(
            [server_flag, "taint", "nodes", "kn", "dedicated=infra:NoSchedule"]
        )
        == 0
    )
    assert client.get("nodes", "", "kn").spec.taints[0].key == "dedicated"
    assert kubectl_main([server_flag, "cordon", "kn"]) == 0
    assert client.get("nodes", "", "kn").spec.unschedulable is True
    out = io.StringIO()
    with redirect_stdout(out):
        assert kubectl_main([server_flag, "-o", "json", "get", "nodes", "kn"]) == 0
    assert json.loads(out.getvalue())["metadata"]["name"] == "kn"
    assert kubectl_main([server_flag, "delete", "nodes", "kn"]) == 0


def test_serializer_roundtrip_pod_affinity():
    from kubernetes_tpu.api.objects import (
        Affinity,
        PodAffinityTerm,
        PodAntiAffinity,
        Toleration,
    )
    from kubernetes_tpu.api.selectors import LabelSelector

    pod = Pod(
        metadata=ObjectMeta(name="p", labels={"app": "x"}),
        spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": "100m"})],
            affinity=Affinity(
                pod_anti_affinity=PodAntiAffinity(
                    required=(
                        PodAffinityTerm(
                            label_selector=LabelSelector.make(
                                match_labels={"app": "x"}
                            ),
                            topology_key="zone",
                        ),
                    )
                )
            ),
            tolerations=[Toleration(key="k", operator="Exists")],
        ),
    )
    wire = json.dumps(codec.encode(pod))
    back = codec.decode("pods", json.loads(wire))
    term = back.spec.affinity.pod_anti_affinity.required[0]
    assert term.topology_key == "zone"
    assert term.label_selector.matches({"app": "x"})
    assert back.spec.tolerations[0].operator == "Exists"
    # cluster-scoped namespace survives
    node_wire = json.dumps(codec.encode(make_node("n")))
    assert codec.decode("nodes", json.loads(node_wire)).metadata.namespace == ""
