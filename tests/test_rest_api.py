"""REST façade + client + kubectl: the full HTTP path.

Mirrors the reference's integration topology (test/integration/: real
in-process apiserver over HTTP, real components as clients) — here the
scheduler itself runs against the REST client to prove every component
works across the wire, not just in-process.
"""

import io
import json
import time
import urllib.request
from contextlib import redirect_stdout

import pytest

from kubernetes_tpu.api import serialization as codec
from kubernetes_tpu.api.objects import (
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from kubernetes_tpu.apiserver import RESTClient, serve
from kubernetes_tpu.client.apiserver import AlreadyExists, NotFound
from kubernetes_tpu.cmd.kubectl import main as kubectl_main
from kubernetes_tpu.scheduler import KubeSchedulerConfiguration, Scheduler


@pytest.fixture
def rest():
    srv, port, store = serve(port=0)
    yield RESTClient(f"http://127.0.0.1:{port}"), store, port
    srv.shutdown()


def make_node(name):
    return Node(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={"cpu": "4", "memory": "32Gi", "pods": 110}),
    )


def make_pod(name):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(containers=[Container(requests={"cpu": "100m"})]),
    )


def test_rest_crud_roundtrip(rest):
    client, _store, _port = rest
    client.create("nodes", make_node("n0"))
    got = client.get("nodes", "", "n0")
    assert got.metadata.name == "n0"
    assert got.status.allocatable["cpu"] == "4"
    with pytest.raises(AlreadyExists):
        client.create("nodes", make_node("n0"))

    def mutate(n):
        n.spec.unschedulable = True
        return n

    client.guaranteed_update("nodes", "", "n0", mutate)
    assert client.get("nodes", "", "n0").spec.unschedulable is True
    objs, rv = client.list("nodes")
    assert len(objs) == 1 and rv > 0
    client.delete("nodes", "", "n0")
    with pytest.raises(NotFound):
        client.get("nodes", "", "n0")


def test_rest_watch_streams_events(rest):
    client, _store, _port = rest
    w = client.watch("pods")
    time.sleep(0.2)
    client.create("pods", make_pod("a"))
    ev = w.get(timeout=5)
    assert ev is not None and ev.type == "ADDED"
    assert ev.object.metadata.name == "a"
    client.delete("pods", "default", "a")
    types = set()
    for _ in range(2):
        ev = w.get(timeout=5)
        if ev:
            types.add(ev.type)
    assert "DELETED" in types
    w.stop()


def test_scheduler_runs_over_rest(rest):
    client, _store, _port = rest
    for i in range(3):
        client.create("nodes", make_node(f"n{i}"))
    sched = Scheduler(client, KubeSchedulerConfiguration())
    sched.start()
    try:
        client.create("pods", make_pod("p"))
        deadline = time.time() + 20
        while time.time() < deadline:
            if client.get("pods", "default", "p").spec.node_name:
                break
            time.sleep(0.05)
        assert client.get("pods", "default", "p").spec.node_name
    finally:
        sched.stop()


def test_binding_subresource(rest):
    client, _store, port = rest
    client.create("nodes", make_node("n0"))
    client.create("pods", make_pod("p"))
    body = json.dumps(
        {"podName": "p", "podNamespace": "default", "targetNode": "n0"}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/namespaces/default/pods/p/binding",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    resp = urllib.request.urlopen(req, timeout=5)
    assert resp.status == 201
    assert client.get("pods", "default", "p").spec.node_name == "n0"


def test_kubectl_get_apply_taint(rest, tmp_path):
    client, _store, port = rest
    server_flag = f"--server=http://127.0.0.1:{port}"
    manifest = tmp_path / "node.json"
    manifest.write_text(
        json.dumps(codec.encode(make_node("kn")))
    )
    assert kubectl_main([server_flag, "apply", "-f", str(manifest)]) == 0
    out = io.StringIO()
    with redirect_stdout(out):
        assert kubectl_main([server_flag, "get", "nodes"]) == 0
    assert "kn" in out.getvalue()
    assert (
        kubectl_main(
            [server_flag, "taint", "nodes", "kn", "dedicated=infra:NoSchedule"]
        )
        == 0
    )
    assert client.get("nodes", "", "kn").spec.taints[0].key == "dedicated"
    assert kubectl_main([server_flag, "cordon", "kn"]) == 0
    assert client.get("nodes", "", "kn").spec.unschedulable is True
    out = io.StringIO()
    with redirect_stdout(out):
        assert kubectl_main([server_flag, "-o", "json", "get", "nodes", "kn"]) == 0
    assert json.loads(out.getvalue())["metadata"]["name"] == "kn"
    assert kubectl_main([server_flag, "delete", "nodes", "kn"]) == 0


def test_serializer_roundtrip_pod_affinity():
    from kubernetes_tpu.api.objects import (
        Affinity,
        PodAffinityTerm,
        PodAntiAffinity,
        Toleration,
    )
    from kubernetes_tpu.api.selectors import LabelSelector

    pod = Pod(
        metadata=ObjectMeta(name="p", labels={"app": "x"}),
        spec=PodSpec(
            containers=[Container(name="c", requests={"cpu": "100m"})],
            affinity=Affinity(
                pod_anti_affinity=PodAntiAffinity(
                    required=(
                        PodAffinityTerm(
                            label_selector=LabelSelector.make(
                                match_labels={"app": "x"}
                            ),
                            topology_key="zone",
                        ),
                    )
                )
            ),
            tolerations=[Toleration(key="k", operator="Exists")],
        ),
    )
    wire = json.dumps(codec.encode(pod))
    back = codec.decode("pods", json.loads(wire))
    term = back.spec.affinity.pod_anti_affinity.required[0]
    assert term.topology_key == "zone"
    assert term.label_selector.matches({"app": "x"})
    assert back.spec.tolerations[0].operator == "Exists"
    # cluster-scoped namespace survives
    node_wire = json.dumps(codec.encode(make_node("n")))
    assert codec.decode("nodes", json.loads(node_wire)).metadata.namespace == ""


# -- leadership fencing over REST (ISSUE 10) ---------------------------------


def _make_lease(store, holder="sched-a", transitions=3):
    from kubernetes_tpu.client.leaderelection import Lease

    lease = Lease(
        metadata=ObjectMeta(name="kube-scheduler", namespace="kube-system"),
        holder_identity=holder,
        lease_duration_seconds=15.0,
        renew_time=time.monotonic(),
        lease_transitions=transitions,
    )
    store.create("leases", lease)
    return lease


def _fence(identity="sched-a", transitions=3, name="kube-scheduler"):
    from kubernetes_tpu.client.leaderelection import BindFence

    return BindFence(
        namespace="kube-system",
        name=name,
        identity=identity,
        transitions=transitions,
    )


def test_rest_bind_fence_valid_and_rejections(rest):
    """The /binding route validates X-Leadership-Fence against the live
    lease: a matching token binds, a stale-transitions token, an
    identity mismatch, and a fence naming a lease the server has never
    seen all reject with LeaderFenced — and nothing applies."""
    from kubernetes_tpu.client.apiserver import LeaderFenced

    client, store, _port = rest
    client.create("nodes", make_node("n0"))
    _make_lease(store, holder="sched-a", transitions=3)
    for i in range(4):
        client.create("pods", make_pod(f"fp{i}"))
    from kubernetes_tpu.api.objects import Binding

    def binding(i):
        return Binding(
            pod_name=f"fp{i}", pod_namespace="default", target_node="n0"
        )

    # matching fence: binds land
    assert client.bind_pods([binding(0)], fence=_fence()) == [None]
    assert client.get("pods", "default", "fp0").spec.node_name == "n0"
    # stale transitions (a takeover bumped the lease since this token)
    with pytest.raises(LeaderFenced):
        client.bind_pods([binding(1)], fence=_fence(transitions=2))
    # identity mismatch (someone else holds the lease)
    with pytest.raises(LeaderFenced):
        client.bind_pods([binding(1)], fence=_fence(identity="sched-b"))
    # fence on a lease the server has never seen
    with pytest.raises(LeaderFenced):
        client.bind_pods([binding(1)], fence=_fence(name="no-such-lease"))
    # single-pod surface rejects identically
    with pytest.raises(LeaderFenced):
        client.bind_pod(binding(2), fence=_fence(transitions=99))
    # none of the rejected binds applied
    for i in (1, 2, 3):
        assert client.get("pods", "default", f"fp{i}").spec.node_name == ""


def test_rest_bind_fence_malformed_header_is_400(rest):
    """A garbage fence header must 400, never silently degrade to an
    UNfenced bind."""
    client, store, port = rest
    client.create("nodes", make_node("n0"))
    client.create("pods", make_pod("mp0"))
    from kubernetes_tpu.client.leaderelection import FENCE_HEADER

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/namespaces/default/pods/mp0/binding",
        data=json.dumps(
            {"podName": "mp0", "podNamespace": "default", "targetNode": "n0"}
        ).encode(),
        method="POST",
        headers={
            "Content-Type": "application/json",
            FENCE_HEADER: "not json at all",
        },
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 400
    assert client.get("pods", "default", "mp0").spec.node_name == ""


def test_rest_fenced_mid_batch_leaves_prefix_applied_once(rest):
    """A fenced 409 arriving mid-batch raises (the remaining bindings
    are never attempted) while the bindings that landed before the
    takeover stay applied exactly once."""
    from kubernetes_tpu.client.apiserver import LeaderFenced
    from kubernetes_tpu.api.objects import Binding

    client, store, _port = rest
    client.create("nodes", make_node("n0"))
    _make_lease(store, holder="sched-a", transitions=3)
    for i in range(3):
        client.create("pods", make_pod(f"bp{i}"))
    applied = []
    orig_bind = store.bind_pods

    def bind_and_then_takeover(bindings, fence=None):
        errs = orig_bind(bindings, fence=fence)
        applied.extend(
            b.pod_name for b, e in zip(bindings, errs) if e is None
        )
        if len(applied) == 1:
            # a standby takes over between this request and the next:
            # holder + transitions move on
            lease = store.get("leases", "kube-system", "kube-scheduler")
            lease.holder_identity = "sched-b"
            lease.lease_transitions += 1
            store.update("leases", lease)
        return errs

    store.bind_pods = bind_and_then_takeover
    bindings = [
        Binding(pod_name=f"bp{i}", pod_namespace="default", target_node="n0")
        for i in range(3)
    ]
    with pytest.raises(LeaderFenced):
        client.bind_pods(bindings, fence=_fence())
    store.bind_pods = orig_bind
    # the pre-takeover prefix applied exactly once; nothing after it
    assert applied == ["bp0"]
    assert client.get("pods", "default", "bp0").spec.node_name == "n0"
    assert client.get("pods", "default", "bp1").spec.node_name == ""
    assert client.get("pods", "default", "bp2").spec.node_name == ""


def test_leader_elector_over_rest(rest):
    """LeaderElector driven through the RESTClient: acquire/renew/release
    work over the wire, and a degraded store (503 Degraded), a fenced
    store (503 without Retry-After -> NotPrimary), and a transport
    failure all classify as COUNTED SKIPS — the holder keeps leading
    within renew_deadline, exactly the in-process contract."""
    from kubernetes_tpu.client.leaderelection import (
        COUNTER_DEGRADED_SKIPS,
        LeaderElectionConfig,
        LeaderElector,
    )
    from kubernetes_tpu.utils.metrics import metrics

    client, store, _port = rest

    class _Gate:
        degraded = False

        def check_writable(self):
            if self.degraded:
                from kubernetes_tpu.runtime.consensus import DegradedWrites

                raise DegradedWrites("test: degraded")

    gate = _Gate()
    store.write_gate.attach_consensus(gate)
    cfg = LeaderElectionConfig(
        identity="rest-elector",
        lease_duration=4.0,
        renew_deadline=3.0,
        retry_period=0.5,
    )
    started = []
    elector = LeaderElector(
        client, cfg, on_started_leading=lambda: started.append(1)
    )
    # acquire over REST (lease create through the wire)
    assert elector._try_acquire_or_renew() is True
    lease = client.get("leases", "kube-system", "kube-scheduler")
    assert lease.holder_identity == "rest-elector"
    fence = elector.fence()
    assert fence.transitions == lease.lease_transitions

    def skips():
        return metrics.dump().get(f"{COUNTER_DEGRADED_SKIPS}{{}}", 0.0)

    # degraded store: renew is a counted skip, not an exception
    before = skips()
    gate.degraded = True
    assert elector._try_acquire_or_renew() is False
    assert skips() == before + 1
    gate.degraded = False
    assert elector._try_acquire_or_renew() is True
    # fenced store (503 without Retry-After -> NotPrimary): counted skip
    before = skips()
    store.write_gate.fenced = True
    assert elector._try_acquire_or_renew() is False
    assert skips() == before + 1
    store.write_gate.fenced = False
    # transport failure (nothing listening): counted skip, no exception
    dead = LeaderElector(
        RESTClient("http://127.0.0.1:9", timeout=0.5),
        LeaderElectionConfig(
            identity="dead",
            lease_duration=4.0,
            renew_deadline=3.0,
            retry_period=0.5,
        ),
        on_started_leading=lambda: None,
    )
    before = skips()
    assert dead._try_acquire_or_renew() is False
    assert skips() == before + 1
    # graceful release over REST: holder cleared, transitions bumped
    t0 = client.get("leases", "kube-system", "kube-scheduler").lease_transitions
    assert elector.release() is True
    lease = client.get("leases", "kube-system", "kube-scheduler")
    assert lease.holder_identity == ""
    assert lease.lease_transitions == t0 + 1
