"""kubemark hollow-node process entry.

Reference: cmd/kubemark/hollow-node.go — N hollow kubelets against a remote
API server (one process can host thousands; see kubemark/hollow_node.py).
"""

from __future__ import annotations

import argparse
import logging
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="hollow-node-tpu")
    parser.add_argument("--server", default="http://127.0.0.1:18080")
    parser.add_argument("--count", type=int, default=1)
    parser.add_argument("--name-prefix", default="hollow-node")
    parser.add_argument("--cpu", default="4")
    parser.add_argument("--memory", default="32Gi")
    parser.add_argument("-v", "--verbosity", type=int, default=1)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO
    )
    from ..apiserver.client import RESTClient
    from ..kubemark import HollowCluster

    client = RESTClient(args.server)
    cluster = HollowCluster(
        client, num_nodes=args.count, name_prefix=args.name_prefix
    )
    cluster.start()
    logging.getLogger("kubernetes_tpu.cmd.hollow_node").info(
        "registered %d hollow nodes", args.count
    )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        cluster.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
