"""kubectl-equivalent CLI over the REST API.

Reference: staging/src/k8s.io/kubectl/pkg/cmd — the core verbs a scheduler
user needs: get, describe, create/apply -f, delete, bind (debug helper),
cordon/uncordon, taint. Server address via --server or KUBECTL_TPU_SERVER.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ..api import serialization as codec
from ..apiserver.client import RESTClient
from ..client.apiserver import Conflict, NotFound


def _update_with_retry(client: RESTClient, resource: str, mutate, ns: str, name: str):
    """get → mutate → update, retrying version conflicts (the CLI's
    RetryOnConflict helper): controllers touch status concurrently."""
    for _ in range(5):
        obj = client.get(resource, ns, name)
        res = mutate(obj)
        if res is None:
            return obj
        try:
            return client.update(resource, obj)
        except Conflict:
            continue
    raise SystemExit(f"error: conflict updating {resource}/{name} persisted")

ALIASES = {
    "pod": "pods",
    "po": "pods",
    "node": "nodes",
    "no": "nodes",
    "svc": "services",
    "service": "services",
    "pv": "persistentvolumes",
    "pvc": "persistentvolumeclaims",
    "sc": "storageclasses",
    "ev": "events",
    "event": "events",
    "deployment": "deployments",
    "deploy": "deployments",
    "replicaset": "replicasets",
    "rs": "replicasets",
    "statefulset": "statefulsets",
    "sts": "statefulsets",
    "daemonset": "daemonsets",
    "ds": "daemonsets",
    "job": "jobs",
    "cronjob": "cronjobs",
    "cj": "cronjobs",
    "hpa": "horizontalpodautoscalers",
    "quota": "resourcequotas",
    "cm": "configmaps",
    "configmap": "configmaps",
    "secret": "secrets",
    "sa": "serviceaccounts",
    "crd": "customresourcedefinitions",
    "ns": "namespaces",
    "namespace": "namespaces",
}


# shared with the store's namespace normalization (one source of truth)
from ..api.serialization import CLUSTER_SCOPED  # noqa: E402


def _resource(arg: str) -> str:
    return ALIASES.get(arg, arg)


def _load_objects(path: str) -> List[tuple]:
    """File (JSON, JSON list, or YAML if available) → [(resource, obj)]."""
    if path == "-":
        text = sys.stdin.read()
    else:
        with open(path) as f:
            text = f.read()
    try:
        data = json.loads(text)
        docs = data if isinstance(data, list) else [data]
    except json.JSONDecodeError:
        try:
            import yaml  # type: ignore

            docs = [d for d in yaml.safe_load_all(text) if d]
        except ImportError:
            raise SystemExit("file is not JSON and PyYAML is unavailable")
    return [codec.decode_any(doc) for doc in docs]


def cmd_get(client: RESTClient, args) -> int:
    resource = _resource(args.resource)
    if args.name:
        try:
            obj = client.get(resource, args.namespace, args.name)
        except NotFound:
            obj = client.get(resource, "", args.name)
        if args.output == "json":
            print(json.dumps(codec.encode(obj), indent=2))
        else:
            _print_table(resource, [obj], wide=args.output == "wide")
        return 0
    objs, rv = client.list(resource)

    def _matches(o) -> bool:
        if (
            not getattr(args, "all_namespaces", False)
            and args.namespace
            and resource not in CLUSTER_SCOPED
            and o.metadata.namespace != args.namespace
        ):
            return False
        for term in (args.selector or "").split(","):
            if not term:
                continue
            if "!=" in term:
                k, _, want = term.partition("!=")
                if o.metadata.labels.get(k) == want:
                    return False
            elif "=" in term:
                k, _, want = term.partition("=")
                if o.metadata.labels.get(k.rstrip("=")) != want:
                    return False
            elif term not in o.metadata.labels:  # bare key: existence
                return False
        return True

    objs = [o for o in objs if _matches(o)]
    if args.output == "json":
        print(json.dumps([codec.encode(o) for o in objs], indent=2))
    else:
        _print_table(resource, objs, wide=args.output == "wide")
    if getattr(args, "watch", False):
        # stream subsequent changes (kubectl get -w), same filters as the
        # initial list; on 410 Gone re-list silently like the reflector
        from ..client.apiserver import Expired
        from ..runtime.watch import BOOKMARK

        try:
            w = client.watch(resource, from_version=rv)
        except Expired:
            objs, rv = client.list(resource)
            w = client.watch(resource, from_version=rv)
        try:
            while True:
                ev = w.get(timeout=1.0)
                if ev is None:
                    if w.stopped:
                        print("watch stream closed", file=sys.stderr)
                        return 1
                    continue
                if ev.type == BOOKMARK:
                    continue  # rv-only progress notify, nothing to print
                if _matches(ev.object):
                    print(f"{ev.type:<9} {ev.object.metadata.key}")
        except KeyboardInterrupt:
            pass
        finally:
            w.stop()
    return 0


def _print_table(resource: str, objs, wide: bool = False) -> None:
    if resource == "pods":
        if wide:
            print(
                f"{'NAMESPACE':<12} {'NAME':<40} {'NODE':<24} {'PHASE':<10} "
                f"{'READY':<6} {'IP':<16} {'RESTARTS'}"
            )
        else:
            print(f"{'NAMESPACE':<12} {'NAME':<40} {'NODE':<24} {'PHASE':<10}")
        for p in objs:
            line = (
                f"{p.metadata.namespace:<12} {p.metadata.name:<40} "
                f"{p.spec.node_name or '<none>':<24} {p.status.phase:<10}"
            )
            if wide:
                from ..api.objects import COND_POD_READY

                ready = next(
                    (
                        c.status
                        for c in p.status.conditions
                        if c.type == COND_POD_READY
                    ),
                    "-",
                )
                restarts = sum(
                    cs.restart_count for cs in p.status.container_statuses
                )
                line += (
                    f" {ready:<6} {p.status.pod_ip or '<none>':<16} {restarts}"
                )
            print(line)
    elif resource == "nodes":
        print(f"{'NAME':<28} {'UNSCHEDULABLE':<14} {'TAINTS':<5} {'CPU':<8}")
        for n in objs:
            print(
                f"{n.metadata.name:<28} {str(n.spec.unschedulable):<14} "
                f"{len(n.spec.taints):<5} "
                f"{n.status.allocatable.get('cpu', '?'):<8}"
            )
    elif resource == "events":
        print(f"{'TYPE':<8} {'REASON':<20} {'OBJECT':<40} {'NOTE'}")
        for e in objs:
            print(f"{e.type:<8} {e.reason:<20} {e.involved_key:<40} {e.note[:60]}")
    elif resource in ("deployments", "replicasets", "statefulsets"):
        print(f"{'NAMESPACE':<12} {'NAME':<32} {'DESIRED':<8} {'READY':<6} {'UPDATED'}")
        for o in objs:
            st = o.status
            print(
                f"{o.metadata.namespace:<12} {o.metadata.name:<32} "
                f"{o.spec.replicas:<8} {getattr(st, 'ready_replicas', 0):<6} "
                f"{getattr(st, 'updated_replicas', '-')}"
            )
    elif resource == "jobs":
        print(f"{'NAMESPACE':<12} {'NAME':<32} {'COMPLETIONS':<12} {'ACTIVE':<7} {'FAILED'}")
        for o in objs:
            want = o.spec.completions if o.spec.completions is not None else 1
            print(
                f"{o.metadata.namespace:<12} {o.metadata.name:<32} "
                f"{o.status.succeeded}/{want:<10} {o.status.active:<7} "
                f"{o.status.failed}"
            )
    elif resource == "services":
        print(f"{'NAMESPACE':<12} {'NAME':<32} {'TYPE':<12} {'CLUSTER-IP':<16} {'PORTS'}")
        for o in objs:
            ports = ",".join(f"{p[1]}/{p[0]}" for p in o.spec.ports) or "<none>"
            print(
                f"{o.metadata.namespace:<12} {o.metadata.name:<32} "
                f"{o.spec.type:<12} {o.spec.cluster_ip or '<none>':<16} {ports}"
            )
    else:
        print("NAME")
        for o in objs:
            print(o.metadata.name)


def cmd_describe(client: RESTClient, args) -> int:
    resource = _resource(args.resource)
    try:
        obj = client.get(resource, args.namespace, args.name)
    except NotFound:
        obj = client.get(resource, "", args.name)
    print(json.dumps(codec.encode(obj), indent=2))
    if resource == "pods":
        events, _ = client.list("events")
        key = obj.metadata.key
        related = [e for e in events if e.involved_key == key]
        if related:
            print("\nEvents:")
            for e in related:
                print(f"  {e.type} {e.reason}: {e.note} (x{e.count})")
    elif resource == "nodes":
        # describe node: allocated-resources summary (kubectl's
        # "Allocated resources" section)
        from ..api.objects import compute_pod_resource_request
        from ..api.resources import cpu_to_millis, parse_quantity

        pods, _ = client.list("pods")
        # terminal pods keep spec.nodeName until GC but hold no resources
        # (kubectl filters them from Allocated resources the same way)
        mine = [
            p
            for p in pods
            if p.spec.node_name == obj.metadata.name
            and p.status.phase not in ("Succeeded", "Failed")
        ]
        reqs = [compute_pod_resource_request(p) for p in mine]
        cpu_m = sum(r.get("cpu", 0) for r in reqs)  # already millicores
        mem_b = sum(r.get("memory", 0) for r in reqs)  # already bytes
        cpu_alloc = cpu_to_millis(obj.status.allocatable.get("cpu", 0)) or 1
        mem_alloc = parse_quantity(obj.status.allocatable.get("memory", 0)) or 1
        print("\nAllocated resources:")
        print(f"  pods:   {len(mine)}")
        print(f"  cpu:    {cpu_m}m ({100 * cpu_m / cpu_alloc:.0f}%)")
        print(f"  memory: {mem_b} ({100 * mem_b / mem_alloc:.0f}%)")
    return 0


def _normalized(resource: str, obj) -> dict:
    """Server-populated fields stripped so a diff shows only user intent
    (kubectl diff's dry-run comparison ignores the same machinery fields)."""
    doc = codec.encode(obj)
    meta = doc.get("metadata", {})
    for f in (
        "resource_version",
        "resourceVersion",
        "uid",
        "generation",
        "creation_timestamp",
        "creationTimestamp",
    ):
        meta.pop(f, None)
    doc.pop("status", None)
    return doc


def _file_or_kustomize_objects(args) -> List[tuple]:
    if getattr(args, "kustomize", None):
        return _kustomize_build(args.kustomize)
    if getattr(args, "filename", None):
        return _load_objects(args.filename)
    raise SystemExit("error: must specify -f FILE or -k DIRECTORY")


def cmd_diff(client: RESTClient, args) -> int:
    """kubectl diff (staging/src/k8s.io/kubectl/pkg/cmd/diff/diff.go):
    unified diff of each file object against its live counterpart; exit 1
    when any object differs (the reference's exit-code contract)."""
    import difflib

    objs = _file_or_kustomize_objects(args)
    changed = 0
    for resource, obj in objs:
        try:
            live = client.get(resource, obj.metadata.namespace, obj.metadata.name)
            live_doc = _normalized(resource, live)
        except NotFound:
            live_doc = None
        want_doc = _normalized(resource, obj)
        if live_doc == want_doc:
            continue
        changed += 1
        a = (
            json.dumps(live_doc, indent=2, sort_keys=True, default=str).splitlines()
            if live_doc is not None
            else []
        )
        b = json.dumps(want_doc, indent=2, sort_keys=True, default=str).splitlines()
        name = f"{resource}/{obj.metadata.namespace}/{obj.metadata.name}"
        for line in difflib.unified_diff(
            a, b, fromfile=f"LIVE {name}", tofile=f"MERGED {name}", lineterm=""
        ):
            print(line)
    return 1 if changed else 0


def _deep_merge(base: dict, patch: dict) -> dict:
    """Strategic-merge-lite: dicts merge recursively, everything else
    (lists included) replaces — the subset kustomize patchesStrategicMerge
    users rely on for spec tweaks."""
    out = dict(base)
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _kustomize_build(directory: str) -> List[tuple]:
    """kustomize-lite (…/cmd/kustomize; sigs.k8s.io/kustomize subset):
    kustomization.{json,yaml} with resources, namePrefix/nameSuffix,
    namespace, commonLabels, patchesStrategicMerge, and images overrides.
    `resources` entries may be files or nested kustomization dirs (bases).
    """
    for fname in ("kustomization.json", "kustomization.yaml", "kustomization.yml"):
        path = os.path.join(directory, fname)
        if os.path.exists(path):
            break
    else:
        raise SystemExit(f"no kustomization file in {directory}")
    with open(path) as f:
        text = f.read()
    try:
        kz = json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml  # type: ignore

            kz = yaml.safe_load(text)
        except ImportError:
            raise SystemExit("kustomization is not JSON and PyYAML is unavailable")

    docs: List[tuple] = []
    for res in kz.get("resources", []):
        rpath = os.path.join(directory, res)
        if os.path.isdir(rpath):
            docs.extend(_kustomize_build(rpath))  # base overlay
        else:
            docs.extend(_load_objects(rpath))

    patches = []
    for p in kz.get("patchesStrategicMerge", []):
        with open(os.path.join(directory, p)) as f:
            ptext = f.read()
        try:
            patches.append(json.loads(ptext))
        except json.JSONDecodeError:
            import yaml  # type: ignore

            patches.extend(d for d in yaml.safe_load_all(ptext) if d)

    images = {im["name"]: im for im in kz.get("images", [])}
    out: List[tuple] = []
    for resource, obj in docs:
        doc = codec.encode(obj)
        for patch in patches:
            pm = patch.get("metadata", {})
            if pm.get("name") == doc.get("metadata", {}).get("name") and patch.get(
                "kind", doc.get("kind")
            ) == doc.get("kind"):
                doc = _deep_merge(doc, patch)
        meta = doc.setdefault("metadata", {})
        if kz.get("namePrefix") or kz.get("nameSuffix"):
            meta["name"] = (
                kz.get("namePrefix", "") + meta.get("name", "") + kz.get("nameSuffix", "")
            )
        if kz.get("namespace"):
            meta["namespace"] = kz["namespace"]
        for k, v in kz.get("commonLabels", {}).items():
            meta.setdefault("labels", {})[k] = v
            # commonLabels also propagate to selectors + pod templates the
            # way kustomize wires them through workload kinds
            spec = doc.get("spec", {})
            if isinstance(spec.get("selector"), dict):
                sel = spec["selector"]
                tgt = sel.setdefault("match_labels", sel) if "match_labels" in sel else sel
                if isinstance(tgt, dict):
                    tgt[k] = v
            tpl = spec.get("template") if isinstance(spec, dict) else None
            if isinstance(tpl, dict):
                tpl.setdefault("metadata", {}).setdefault("labels", {})[k] = v
        for c in doc.get("spec", {}).get("containers", []) or []:
            base, sep, suffix = _split_image_ref(c.get("image", ""))
            im = images.get(base)
            if im:
                new_base = im.get("newName", base)
                if im.get("newTag"):
                    c["image"] = f"{new_base}:{im['newTag']}"
                elif im.get("digest"):
                    c["image"] = f"{new_base}@{im['digest']}"
                else:
                    c["image"] = new_base + sep + suffix
        out.append(codec.decode_any(doc))
    return out


def _split_image_ref(ref: str):
    """(name, sep, tag_or_digest) for an image reference — the tag ':' is
    only a separator AFTER the last '/', so registry ports
    (localhost:5000/app) survive, and '@sha256:…' digests split on '@'
    (kustomize image transformer semantics)."""
    if "@" in ref:
        name, _, digest = ref.partition("@")
        return name, "@", digest
    slash = ref.rfind("/")
    colon = ref.rfind(":")
    if colon > slash:
        return ref[:colon], ":", ref[colon + 1:]
    return ref, "", ""


def cmd_kustomize(client: RESTClient, args) -> int:
    rendered = [codec.encode(obj) for _res, obj in _kustomize_build(args.directory)]
    print(json.dumps(rendered, indent=2, default=str))
    return 0


def cmd_apply(client: RESTClient, args) -> int:
    for resource, obj in _file_or_kustomize_objects(args):
        try:
            client.create(resource, obj)
            print(f"{resource}/{obj.metadata.name} created")
        except Exception:
            cur = client.get(
                resource, obj.metadata.namespace, obj.metadata.name
            )
            obj.metadata.resource_version = cur.metadata.resource_version
            obj.metadata.uid = cur.metadata.uid
            client.update(resource, obj)
            print(f"{resource}/{obj.metadata.name} configured")
    return 0


def cmd_create(client: RESTClient, args) -> int:
    for resource, obj in _load_objects(args.filename):
        client.create(resource, obj)
        print(f"{resource}/{obj.metadata.name} created")
    return 0


def cmd_delete(client: RESTClient, args) -> int:
    resource = _resource(args.resource)
    try:
        client.delete(resource, args.namespace, args.name)
    except NotFound:
        client.delete(resource, "", args.name)
    print(f"{resource}/{args.name} deleted")
    return 0


def _update_node(client: RESTClient, name: str, mutate) -> None:
    """Nodes are cluster-scoped, but ObjectMeta defaults their store key
    under "default" — try both (shared by cordon/uncordon/taint/drain)."""
    try:
        client.guaranteed_update("nodes", "", name, mutate)
    except NotFound:
        client.guaranteed_update("nodes", "default", name, mutate)


def cmd_logs(client: RESTClient, args) -> int:
    """kubectl logs: GET pods/{name}/log (kubectl/pkg/cmd/logs; served by
    the apiserver's log subresource routing to the pod's kubelet)."""
    sub = f"{args.name}/log"
    if args.tail is not None:
        sub += f"?tailLines={args.tail}"
    try:
        sys.stdout.write(client.get_text("pods", args.namespace, sub))
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_exec(client: RESTClient, args) -> int:
    """kubectl exec: POST pods/{name}/exec (ExecSync through the pod's
    kubelet; kubectl/pkg/cmd/exec)."""
    try:
        sys.stdout.write(
            client.post_text(
                "pods",
                args.namespace,
                f"{args.name}/exec",
                {"command": args.command},
            )
        )
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_cordon(client: RESTClient, args, unschedulable=True) -> int:
    def mutate(n):
        n.spec.unschedulable = unschedulable
        return n

    _update_node(client, args.name, mutate)
    print(f"node/{args.name} {'cordoned' if unschedulable else 'uncordoned'}")
    return 0


def cmd_taint(client: RESTClient, args) -> int:
    # kubectl taint nodes NAME key=value:Effect (suffix '-' removes)
    from ..api.objects import Taint

    spec = args.taint
    remove = spec.endswith("-")
    spec = spec.rstrip("-")
    kv, _, effect = spec.partition(":")
    key, _, value = kv.partition("=")

    def mutate(n):
        n.spec.taints = [t for t in n.spec.taints if t.key != key]
        if not remove:
            n.spec.taints.append(Taint(key, value, effect))
        return n

    _update_node(client, args.name, mutate)
    print(f"node/{args.name} {'untainted' if remove else 'tainted'}")
    return 0


def cmd_top(client: RESTClient, args) -> int:
    """kubectl top nodes|pods (metrics.k8s.io, kubectl/pkg/cmd/top)."""
    what = _resource(args.resource)
    if what == "nodes":
        data = client.get_raw("/apis/metrics.k8s.io/v1beta1/nodes")
        print(f"{'NAME':32} {'CPU(cores)':>12} {'MEMORY(bytes)':>16}")
        for it in data.get("items", []):
            print(
                f"{it['metadata']['name']:32} {it['usage']['cpu']:>12} "
                f"{it['usage']['memory']:>16}"
            )
        return 0
    data = client.get_raw(
        f"/apis/metrics.k8s.io/v1beta1/namespaces/{args.namespace}/pods"
    )
    print(f"{'NAME':40} {'CPU(cores)':>12} {'MEMORY(bytes)':>16}")
    for it in data.get("items", []):
        print(
            f"{it['metadata']['name']:40} {it['usage']['cpu']:>12} "
            f"{it['usage']['memory']:>16}"
        )
    return 0


SCALABLE = {"deployments", "replicasets", "statefulsets", "jobs"}


def cmd_scale(client: RESTClient, args) -> int:
    """kubectl scale <resource> <name> --replicas=N (cmd/scale)."""
    resource = _resource(args.resource)
    if resource not in SCALABLE:
        print(f"error: {resource} is not scalable", file=sys.stderr)
        return 1
    obj = client.get(resource, args.namespace, args.name)
    if resource == "jobs":
        obj.spec.parallelism = args.replicas
    else:
        obj.spec.replicas = args.replicas
    client.update(resource, obj)
    print(f"{resource}/{args.name} scaled")
    return 0


def cmd_rollout_status(client: RESTClient, args) -> int:
    """kubectl rollout status deployment/<name> (cmd/rollout): poll until
    updated == desired and available == desired."""
    import time as _time

    kind, _, name = args.target.partition("/")
    resource = _resource(kind)
    if resource != "deployments":
        print("error: rollout status supports deployments", file=sys.stderr)
        return 1
    deadline = _time.time() + args.timeout
    while _time.time() < deadline:
        d = client.get(resource, args.namespace, name)
        want = d.spec.replicas
        if (
            d.status.updated_replicas >= want
            and d.status.available_replicas >= want
            and d.status.replicas == want
        ):
            print(f'deployment "{name}" successfully rolled out')
            return 0
        _time.sleep(0.2)
    print(f'error: deployment "{name}" rollout timed out', file=sys.stderr)
    return 1


def _kv_edits(pairs: List[str]) -> tuple:
    """kubectl's key=val / key- syntax → (sets dict, removes list)."""
    sets, removes = {}, []
    for p in pairs:
        if p.endswith("-") and "=" not in p:
            removes.append(p[:-1])
        elif "=" in p:
            k, _, val = p.partition("=")
            sets[k] = val
        else:
            raise SystemExit(f"invalid key=value pair: {p!r}")
    return sets, removes


def cmd_label(client: RESTClient, args, field: str = "labels") -> int:
    """kubectl label/annotate <resource> <name> k=v ... k- (cmd/label,
    cmd/annotate)."""
    resource = _resource(args.resource)
    sets, removes = _kv_edits(args.pairs)
    clobbered: List[str] = []

    def mutate(obj):
        target = getattr(obj.metadata, field)
        if not args.overwrite:
            clobbered[:] = [
                k for k in sets if k in target and target[k] != sets[k]
            ]
            if clobbered:
                return None
        for k in removes:
            target.pop(k, None)
        target.update(sets)
        return obj

    _update_with_retry(client, resource, mutate, args.namespace, args.name)
    if clobbered:
        print(
            f"error: {clobbered[0]} already has a value; --overwrite to replace",
            file=sys.stderr,
        )
        return 1
    print(f"{resource}/{args.name} {'labeled' if field == 'labels' else 'annotated'}")
    return 0


def _merge_patch(obj, patch: dict) -> None:
    """RFC 7386 merge-patch onto a decoded object (strategic-merge-lite:
    dicts merge recursively, null deletes, everything else replaces).
    Unknown fields are an error, as for a strategic merge on typed
    objects — silently dropping them would report success for a typo."""
    for key, val in patch.items():
        snake = codec._snake(key)
        if not hasattr(obj, snake):
            raise SystemExit(
                f"error: unknown field {key!r} in patch for {type(obj).__name__}"
            )
        cur = getattr(obj, snake)
        if isinstance(val, dict) and hasattr(cur, "__dataclass_fields__"):
            _merge_patch(cur, val)
        elif isinstance(val, dict) and isinstance(cur, dict):
            for k, v in val.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
        else:
            import typing as _t

            hints = _t.get_type_hints(type(obj))
            setattr(obj, snake, codec.from_dict(hints[snake], val))


def cmd_patch(client: RESTClient, args) -> int:
    """kubectl patch <resource> <name> -p '<json>' (cmd/patch)."""
    resource = _resource(args.resource)
    try:
        patch = json.loads(args.patch)
    except json.JSONDecodeError as e:
        print(f"error: invalid patch JSON: {e}", file=sys.stderr)
        return 1
    def mutate(obj):
        _merge_patch(obj, patch)
        return obj

    _update_with_retry(client, resource, mutate, args.namespace, args.name)
    print(f"{resource}/{args.name} patched")
    return 0


def _deployment_rses(client: RESTClient, ns: str, name: str):
    """The deployment's owned ReplicaSets, oldest first."""
    rses, _ = client.list("replicasets", namespace=ns)
    owned = [
        rs
        for rs in rses
        if any(
            r.kind == "Deployment" and r.name == name and r.controller
            for r in rs.metadata.owner_references
        )
    ]
    owned.sort(key=lambda rs: rs.metadata.creation_timestamp)
    return owned


def cmd_rollout_history(client: RESTClient, args) -> int:
    kind, _, name = args.target.partition("/")
    if _resource(kind) != "deployments":
        print("error: rollout supports deployments", file=sys.stderr)
        return 1
    print("REVISION  REPLICASET  TEMPLATE-HASH  REPLICAS")
    for i, rs in enumerate(_deployment_rses(client, args.namespace, name), 1):
        h = rs.metadata.labels.get("pod-template-hash", "")
        print(f"{i:<9} {rs.metadata.name:<11} {h:<14} {rs.spec.replicas}")
    return 0


def cmd_rollout_restart(client: RESTClient, args) -> int:
    """Bump the restartedAt template annotation: a new template hash rolls
    every pod through the ordinary rolling-update machinery."""
    import time as _time

    kind, _, name = args.target.partition("/")
    if _resource(kind) != "deployments":
        print("error: rollout supports deployments", file=sys.stderr)
        return 1
    def mutate(d):
        d.spec.template.metadata.annotations[
            "kubectl.kubernetes.io/restartedAt"
        ] = str(_time.time())
        return d

    _update_with_retry(client, "deployments", mutate, args.namespace, name)
    print(f'deployment.apps/{name} restarted')
    return 0


def cmd_rollout_undo(client: RESTClient, args) -> int:
    """Roll the deployment template back to the previous ReplicaSet's
    (cmd/rollout undo)."""
    import copy as _copy

    kind, _, name = args.target.partition("/")
    if _resource(kind) != "deployments":
        print("error: rollout supports deployments", file=sys.stderr)
        return 1
    d = client.get("deployments", args.namespace, name)
    from ..controller.deployment import template_hash

    cur_hash = template_hash(d.spec.template)
    history = [
        rs
        for rs in _deployment_rses(client, args.namespace, name)
        if rs.metadata.labels.get("pod-template-hash") != cur_hash
    ]
    if not history:
        print("error: no rollout history found", file=sys.stderr)
        return 1
    prev = history[-1]  # newest non-current revision

    def mutate(dep):
        tmpl = _copy.deepcopy(prev.spec.template)
        tmpl.metadata.labels.pop("pod-template-hash", None)
        dep.spec.template = tmpl
        return dep

    _update_with_retry(client, "deployments", mutate, args.namespace, name)
    print(f"deployment.apps/{name} rolled back")
    return 0


def cmd_expose(client: RESTClient, args) -> int:
    """kubectl expose deployment/<name> --port N (cmd/expose): create a
    Service selecting the workload's pods."""
    from ..api import objects as v1

    kind, _, name = args.target.partition("/")
    resource = _resource(kind)
    if resource not in ("deployments", "replicasets", "replicationcontrollers"):
        print(f"error: cannot expose {resource}", file=sys.stderr)
        return 1
    obj = client.get(resource, args.namespace, name)
    selector = dict(obj.spec.selector)
    selector.pop("pod-template-hash", None)
    svc = v1.Service(
        metadata=v1.ObjectMeta(name=args.name or name, namespace=args.namespace),
        spec=v1.ServiceSpec(
            selector=selector, ports=[(args.protocol, args.port)]
        ),
    )
    client.create("services", svc)
    print(f"service/{svc.metadata.name} exposed")
    return 0


def cmd_wait(client: RESTClient, args) -> int:
    """kubectl wait <resource> <name> --for=delete|condition=X[=V]
    (cmd/wait)."""
    import time as _time

    resource = _resource(args.resource)
    spec = args.wait_for
    if spec != "delete" and not spec.startswith("condition="):
        print(
            f"error: unsupported --for {spec!r} (use delete or "
            "condition=<Type>[=<Value>])",
            file=sys.stderr,
        )
        return 1
    deadline = _time.time() + args.timeout
    while _time.time() < deadline:
        try:
            obj = client.get(resource, args.namespace, args.name)
        except NotFound:
            if spec == "delete":
                print(f"{resource}/{args.name} condition met")
                return 0
            _time.sleep(0.2)
            continue
        if spec != "delete" and spec.startswith("condition="):
            _, _, cond = spec.partition("=")
            cond, _, want = cond.partition("=")
            want = want or "True"
            conds = getattr(obj.status, "conditions", [])
            if any(c.type == cond and c.status == want for c in conds):
                print(f"{resource}/{args.name} condition met")
                return 0
        _time.sleep(0.2)
    print(f"error: timed out waiting for {spec}", file=sys.stderr)
    return 1


def cmd_explain(client: RESTClient, args) -> int:
    """kubectl explain <resource>[.field...]: field names + types from the
    dataclass model (the build's OpenAPI stand-in)."""
    import dataclasses as _dc
    import typing as _t

    path = args.resource.split(".")
    resource = _resource(path[0])
    cls = codec.RESOURCE_KINDS.get(resource)
    if cls is None:
        print(f"error: unknown resource {path[0]}", file=sys.stderr)
        return 1
    for seg in path[1:]:
        hints = _t.get_type_hints(cls)
        if seg not in hints:
            print(f"error: field {seg!r} not found in {cls.__name__}", file=sys.stderr)
            return 1
        nxt = hints[seg]
        origin = _t.get_origin(nxt)
        if origin in (list, tuple, dict):
            nxt = (_t.get_args(nxt) or (object,))[-1]
        if _t.get_origin(nxt) is _t.Union:
            nxt = next(a for a in _t.get_args(nxt) if a is not type(None))
        cls = nxt
    print(f"KIND: {cls.__name__ if hasattr(cls, '__name__') else cls}")
    if _dc.is_dataclass(cls):
        print("FIELDS:")
        hints = _t.get_type_hints(cls)
        for f in _dc.fields(cls):
            tp = hints[f.name]
            tname = getattr(tp, "__name__", None) or str(tp).replace("typing.", "")
            print(f"  {f.name:<28} <{tname}>")
    return 0


def cmd_certificate(client: RESTClient, args) -> int:
    """kubectl certificate approve|deny <name> (cmd/certificates): sets the
    condition the signer/cleaner controllers act on."""
    from ..api import objects as v1

    cond = "Approved" if args.subverb == "approve" else "Denied"

    def mutate(csr):
        if any(c.type == cond and c.status == "True" for c in csr.status.conditions):
            return None
        csr.status.conditions.append(
            v1.PodCondition(type=cond, status="True", reason="KubectlCertificate")
        )
        return csr

    _update_with_retry(
        client, "certificatesigningrequests", mutate, "", args.name
    )
    past = {"approve": "approved", "deny": "denied"}[args.subverb]
    print(f"certificatesigningrequest.certificates.k8s.io/{args.name} {past}")
    return 0


def cmd_api_resources(client: RESTClient, args) -> int:
    """kubectl api-resources: the served resource catalogue."""
    print(f"{'NAME':<36} {'KIND'}")
    for res, cls in sorted(codec.RESOURCE_KINDS.items()):
        print(f"{res:<36} {cls.__name__}")
    return 0


def cmd_drain(client: RESTClient, args) -> int:
    """kubectl drain: cordon, then EVICT every non-daemon pod off the node
    through the PDB-respecting eviction subresource, retrying 429s until
    --timeout (kubectl/pkg/drain)."""
    import time as _time
    import urllib.error

    def mutate(n):
        n.spec.unschedulable = True
        return n

    _update_node(client, args.name, mutate)
    print(f"node/{args.name} cordoned")
    deadline = _time.time() + args.timeout
    while True:
        pods, _ = client.list("pods")
        victims = [
            p
            for p in pods
            if p.spec.node_name == args.name
            and p.metadata.deletion_timestamp is None
            and not any(
                r.controller and r.kind == "DaemonSet"
                for r in p.metadata.owner_references
            )
        ]
        if not victims:
            print(f"node/{args.name} drained")
            return 0
        blocked = 0
        for p in victims:
            try:
                client._request(
                    "POST",
                    client.base
                    + f"/api/v1/namespaces/{p.metadata.namespace}/pods/"
                    + f"{p.metadata.name}/eviction",
                    {"kind": "Eviction"},
                )
                print(f"pod/{p.metadata.name} evicted")
            except NotFound:
                continue  # vanished between list and eviction: already gone
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    blocked += 1  # PDB: retry after the controller catches up
                else:
                    raise
        # deadline + pacing apply to EVERY round — a workload recreating
        # pods as fast as they evict must hit the timeout, not spin forever
        if _time.time() > deadline:
            remaining = blocked or len(victims)
            print(
                f"error: {remaining} pods still on the node "
                f"({blocked} blocked by disruption budgets)",
                file=sys.stderr,
            )
            return 1
        _time.sleep(0.5 if blocked else 0.05)


def cmd_auth_can_i(client: RESTClient, args) -> int:
    """kubectl auth can-i VERB RESOURCE (SelfSubjectAccessReview)."""
    out = client._request(
        "POST",
        client.base + "/api/v1/selfsubjectaccessreviews",
        {
            "kind": "SelfSubjectAccessReview",
            "spec": {
                "resourceAttributes": {
                    "verb": args.can_verb,
                    "resource": _resource(args.can_resource),
                    "namespace": args.namespace,
                }
            },
        },
    )
    allowed = bool(out.get("status", {}).get("allowed"))
    print("yes" if allowed else "no")
    return 0 if allowed else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kubectl-tpu")
    parser.add_argument(
        "--server",
        default=os.environ.get("KUBECTL_TPU_SERVER", "http://127.0.0.1:18080"),
    )
    parser.add_argument(
        "--token",
        default=os.environ.get("KUBECTL_TPU_TOKEN", ""),
        help="bearer token for secured clusters",
    )
    parser.add_argument("-n", "--namespace", default="default")
    parser.add_argument("-o", "--output", default="table", choices=["table", "json", "wide"])
    sub = parser.add_subparsers(dest="verb", required=True)

    p_get = sub.add_parser("get")
    p_get.add_argument("resource")
    p_get.add_argument("name", nargs="?")
    p_get.add_argument("-l", "--selector", default="")
    p_get.add_argument("-A", "--all-namespaces", action="store_true")
    p_get.add_argument("-w", "--watch", action="store_true")
    p_desc = sub.add_parser("describe")
    p_desc.add_argument("resource")
    p_desc.add_argument("name")
    p_apply = sub.add_parser("apply")
    p_apply.add_argument("-f", "--filename")
    p_apply.add_argument("-k", "--kustomize")
    p_diff = sub.add_parser("diff")
    p_diff.add_argument("-f", "--filename")
    p_diff.add_argument("-k", "--kustomize")
    p_kust = sub.add_parser("kustomize")
    p_kust.add_argument("directory")
    p_logs = sub.add_parser("logs")
    p_logs.add_argument("name")
    p_logs.add_argument("--tail", type=int, default=None)
    p_exec = sub.add_parser("exec")
    p_exec.add_argument("name")
    p_exec.add_argument("command", nargs="+")
    p_create = sub.add_parser("create")
    p_create.add_argument("-f", "--filename", required=True)
    p_del = sub.add_parser("delete")
    p_del.add_argument("resource")
    p_del.add_argument("name")
    p_cord = sub.add_parser("cordon")
    p_cord.add_argument("name")
    p_uncord = sub.add_parser("uncordon")
    p_uncord.add_argument("name")
    p_taint = sub.add_parser("taint")
    p_taint.add_argument("nodes")  # literal "nodes"
    p_taint.add_argument("name")
    p_taint.add_argument("taint")
    p_top = sub.add_parser("top")
    p_top.add_argument("resource")  # nodes | pods
    p_scale = sub.add_parser("scale")
    p_scale.add_argument("resource")
    p_scale.add_argument("name")
    p_scale.add_argument("--replicas", type=int, required=True)
    p_roll = sub.add_parser("rollout")
    p_roll.add_argument("action")  # status | history | restart | undo
    p_roll.add_argument("target")  # deployment/<name>
    p_roll.add_argument("--timeout", type=float, default=60.0)
    p_label = sub.add_parser("label")
    p_label.add_argument("resource")
    p_label.add_argument("name")
    p_label.add_argument("pairs", nargs="+")  # k=v or k-
    p_label.add_argument("--overwrite", action="store_true")
    p_ann = sub.add_parser("annotate")
    p_ann.add_argument("resource")
    p_ann.add_argument("name")
    p_ann.add_argument("pairs", nargs="+")
    p_ann.add_argument("--overwrite", action="store_true")
    p_patch = sub.add_parser("patch")
    p_patch.add_argument("resource")
    p_patch.add_argument("name")
    p_patch.add_argument("-p", "--patch", required=True)
    p_expose = sub.add_parser("expose")
    p_expose.add_argument("target")  # deployment/<name>
    p_expose.add_argument("--port", type=int, required=True)
    p_expose.add_argument("--protocol", default="TCP")
    p_expose.add_argument("--name", default="")
    p_wait = sub.add_parser("wait")
    p_wait.add_argument("resource")
    p_wait.add_argument("name")
    p_wait.add_argument("--for", dest="wait_for", required=True)
    p_wait.add_argument("--timeout", type=float, default=30.0)
    p_explain = sub.add_parser("explain")
    p_explain.add_argument("resource")  # resource[.field.path]
    p_cert = sub.add_parser("certificate")
    p_cert.add_argument("subverb", choices=["approve", "deny"])
    p_cert.add_argument("name")
    sub.add_parser("api-resources")
    p_drain = sub.add_parser("drain")
    p_drain.add_argument("name")
    p_drain.add_argument("--timeout", type=float, default=60.0)
    p_can = sub.add_parser("auth")
    p_can.add_argument("subverb")  # can-i
    p_can.add_argument("can_verb")
    p_can.add_argument("can_resource")

    args = parser.parse_args(argv)
    if args.token:
        from ..apiserver.client import AuthRESTClient

        client = AuthRESTClient(args.server, token=args.token)
    else:
        client = RESTClient(args.server)
    try:
        if args.verb == "get":
            return cmd_get(client, args)
        if args.verb == "describe":
            return cmd_describe(client, args)
        if args.verb == "diff":
            return cmd_diff(client, args)
        if args.verb == "kustomize":
            return cmd_kustomize(client, args)
        if args.verb == "logs":
            return cmd_logs(client, args)
        if args.verb == "exec":
            return cmd_exec(client, args)
        if args.verb == "apply":
            return cmd_apply(client, args)
        if args.verb == "create":
            return cmd_create(client, args)
        if args.verb == "delete":
            return cmd_delete(client, args)
        if args.verb == "cordon":
            return cmd_cordon(client, args, True)
        if args.verb == "uncordon":
            return cmd_cordon(client, args, False)
        if args.verb == "taint":
            return cmd_taint(client, args)
        if args.verb == "top":
            return cmd_top(client, args)
        if args.verb == "scale":
            return cmd_scale(client, args)
        if args.verb == "rollout":
            if args.action == "status":
                return cmd_rollout_status(client, args)
            if args.action == "history":
                return cmd_rollout_history(client, args)
            if args.action == "restart":
                return cmd_rollout_restart(client, args)
            if args.action == "undo":
                return cmd_rollout_undo(client, args)
            print(
                "error: rollout supports status|history|restart|undo",
                file=sys.stderr,
            )
            return 1
        if args.verb == "label":
            return cmd_label(client, args, "labels")
        if args.verb == "annotate":
            return cmd_label(client, args, "annotations")
        if args.verb == "patch":
            return cmd_patch(client, args)
        if args.verb == "expose":
            return cmd_expose(client, args)
        if args.verb == "wait":
            return cmd_wait(client, args)
        if args.verb == "explain":
            return cmd_explain(client, args)
        if args.verb == "certificate":
            return cmd_certificate(client, args)
        if args.verb == "api-resources":
            return cmd_api_resources(client, args)
        if args.verb == "drain":
            return cmd_drain(client, args)
        if args.verb == "auth":
            if args.subverb != "can-i":
                print("error: only 'auth can-i' is supported", file=sys.stderr)
                return 1
            return cmd_auth_can_i(client, args)
    except NotFound as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
