"""cloud-controller-manager process entry.

Reference: cmd/cloud-controller-manager/controller-manager.go — the cloud
loops (service LB, routes, cloud-node init, cloud-node lifecycle) run as
their OWN binary against the API server, decoupled from
kube-controller-manager so cloud-provider code stays out of the core
(the out-of-tree cloud provider split). The provider here is the fake
in-memory cloud; a real provider implements the same four-method
surfaces (LoadBalancer / Routes / Instances).
"""

from __future__ import annotations

import argparse
import logging
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="cloud-controller-manager-tpu")
    parser.add_argument(
        "--server", default="http://127.0.0.1:18080", help="API server URL"
    )
    parser.add_argument(
        "--node-monitor-period", type=float, default=5.0,
        help="instance-existence sweep period (seconds)",
    )
    parser.add_argument("-v", "--verbosity", type=int, default=1)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO
    )
    from ..apiserver.client import RESTClient
    from ..controller.cloud import (
        CloudNodeController,
        CloudNodeLifecycleController,
        FakeCloudProvider,
        RouteController,
        ServiceLBController,
    )

    client = RESTClient(args.server)
    cloud = FakeCloudProvider()
    ctrls = [
        ServiceLBController(client, cloud=cloud),
        RouteController(client, cloud=cloud),
        CloudNodeController(client, cloud=cloud),
        CloudNodeLifecycleController(
            client, cloud=cloud, period_s=args.node_monitor_period
        ),
    ]
    for c in ctrls:
        c.start()
    logging.info("cloud-controller-manager running against %s", args.server)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        for c in ctrls:
            c.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
