"""ktpu-backup: fenced backup / disaster-restore operator tool.

Reference shape: `etcdctl snapshot save` / `etcdutl snapshot restore` —
backup is online and consistent, restore mints a NEW cluster epoch (our
analogue of etcd's new-cluster-id + member bump is the lease-transition
bump plus the replication term bump, see runtime/backup.py).

    ktpu-backup save    --wal /var/lib/ktpu/store --out backup.json
    ktpu-backup save    --url http://primary:18080 --out backup.json
    ktpu-backup restore --backup backup.json --wal /var/lib/ktpu/restored
    ktpu-backup inspect --backup backup.json
"""

from __future__ import annotations

import argparse
import json
import logging
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="ktpu-backup")
    parser.add_argument("-v", "--verbosity", type=int, default=1)
    sub = parser.add_subparsers(dest="cmd", required=True)

    save = sub.add_parser("save", help="write a consistent backup image")
    save.add_argument("--out", required=True, help="backup file to write")
    src = save.add_mutually_exclusive_group(required=True)
    src.add_argument("--wal", default="", help="WAL base path (offline)")
    src.add_argument("--url", default="", help="live apiserver URL (online)")

    restore = sub.add_parser(
        "restore", help="materialize a backup as a fresh fenced WAL"
    )
    restore.add_argument("--backup", required=True)
    restore.add_argument("--wal", required=True, help="WAL base path to create")
    restore.add_argument(
        "--force", action="store_true",
        help="overwrite an existing non-empty WAL at the target",
    )

    inspect = sub.add_parser("inspect", help="print a backup image summary")
    inspect.add_argument("--backup", required=True)

    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO
    )
    from ..runtime import backup as bk

    if args.cmd == "save":
        if args.wal:
            image = bk.backup_from_wal(args.wal, args.out)
        else:
            # online: snapshot a LIVE server through its REST surface
            from ..apiserver.client import RESTClient

            image = RESTClient(args.url).backup_state()
            bk.write_backup(image, args.out)
        print(
            f"saved {args.out}: rv={image['rv']} commit={image['commit']} "
            f"term={image['term']} kinds={len(image['objects'])}"
        )
        if image.get("source_corrupt"):
            print(
                "WARNING: source WAL was mid-log corrupt; image holds the "
                "longest valid prefix and may be missing acked writes",
                file=sys.stderr,
            )
            return 3
        return 0

    if args.cmd == "restore":
        image = bk.load_backup(args.backup)
        summary = bk.restore_into(image, args.wal, force=args.force)
        print(
            f"restored {args.wal}: rv={summary['rv']} "
            f"term={summary['term']} objects={summary['objects']} "
            f"fenced_leases={summary['fenced_leases']}"
        )
        return 0

    image = bk.load_backup(args.backup)
    out = {k: v for k, v in image.items() if k != "objects"}
    out["kinds"] = {k: len(v) for k, v in image["objects"].items()}
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
