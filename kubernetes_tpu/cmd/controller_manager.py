"""kube-controller-manager process entry.

Reference: cmd/kube-controller-manager/app/controllermanager.go — runs the
reconcile loops against the API server (remote REST or in-process).
"""

from __future__ import annotations

import argparse
import logging
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kube-controller-manager-tpu")
    parser.add_argument(
        "--server", default="http://127.0.0.1:18080", help="API server URL"
    )
    parser.add_argument(
        "--controllers",
        default="*",
        help="comma-separated controller names, * = all",
    )
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument(
        "--debug-port",
        type=int,
        default=None,
        help="serve /metrics (Prometheus text) and /debug/traces on this "
        "loopback port (default off; 0 = ephemeral)",
    )
    parser.add_argument("-v", "--verbosity", type=int, default=1)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO
    )
    if args.debug_port is not None:
        from ..utils.debugserver import serve_debug

        serve_debug(args.debug_port)
    from ..apiserver.client import RESTClient
    from ..client.leaderelection import LeaderElectionConfig
    from ..controller.manager import ControllerManager

    client = RESTClient(args.server)
    names = None if args.controllers == "*" else args.controllers.split(",")
    le = (
        LeaderElectionConfig(lock_name="kube-controller-manager")
        if args.leader_elect
        else None
    )
    mgr = ControllerManager(client, controllers=names, leader_election=le)
    mgr.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        mgr.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
