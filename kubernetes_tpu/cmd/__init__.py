"""Process entry points (reference cmd/: kube-scheduler, …)."""
