"""kube-scheduler process entry.

Reference: cmd/kube-scheduler/app/server.go — runCommand/Setup (:302),
Run (:142): healthz server (:10251, server.go:160-171), metrics mux
(:237-268 with the debug DELETE reset), leader election gating sched.Run
(:196-210 — losing leadership is fatal), SIGUSR2 cache debugger.

The API backend is the in-process store; a REST-backed client lands with
the apiserver façade.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..client.apiserver import APIServer
from ..client.leaderelection import LeaderElectionConfig, LeaderElector
from ..scheduler import KubeSchedulerConfiguration, Scheduler
from ..scheduler.apis_config import load_config_file
from ..scheduler.cache.debugger import CacheDebugger
from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.cmd.scheduler")


class _HealthHandler(BaseHTTPRequestHandler):
    server_version = "kube-scheduler-tpu"

    def log_message(self, *args):
        pass

    def _respond(self, code: int, body: bytes, ctype="text/plain"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path in ("/healthz", "/livez", "/readyz"):
            ok = self.server.health_check()
            self._respond(200 if ok else 500, b"ok" if ok else b"unhealthy")
        elif self.path == "/metrics":
            # content negotiation: Prometheus exposition text by default
            # (what the reference's legacyregistry serves); JSON on request
            if "application/json" in (self.headers.get("Accept") or ""):
                body = json.dumps(metrics.dump(), indent=1).encode()
                self._respond(200, body, "application/json")
            else:
                self._respond(
                    200,
                    metrics.render_prometheus().encode(),
                    "text/plain; version=0.0.4",
                )
        else:
            self._respond(404, b"not found")

    def do_DELETE(self):
        # debug handler: DELETE /metrics resets (server.go:237-247)
        if self.path == "/metrics":
            metrics.reset()
            self._respond(200, b"metrics reset\n")
        else:
            self._respond(404, b"not found")


def serve_health(port: int, health_check) -> ThreadingHTTPServer:
    srv = ThreadingHTTPServer(("0.0.0.0", port), _HealthHandler)
    srv.health_check = health_check
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def run(
    server: Optional[APIServer] = None,
    config: Optional[KubeSchedulerConfiguration] = None,
    healthz_port: int = 10251,
    block: bool = True,
    autoscaler_catalog=None,
    autoscaler_kwargs: Optional[dict] = None,
) -> Scheduler:
    """app.Run (server.go:142): health endpoints → informers → leader
    election (optional) → scheduling loops. autoscaler_catalog (a
    NodeGroupCatalog) additionally runs the kernel-driven cluster
    autoscaler against this scheduler's snapshot — it follows the
    scheduler's leadership (starts with scheduling, stops with it)."""
    server = server or APIServer()
    cfg = config or KubeSchedulerConfiguration()
    sched = Scheduler(server, cfg)
    healthy = threading.Event()
    if healthz_port:
        serve_health(healthz_port, lambda: healthy.is_set())
    CacheDebugger(sched).listen_for_signal()

    stop = threading.Event()
    autoscaler = None
    if autoscaler_catalog is not None:
        from ..autoscaler import ClusterAutoscaler

        autoscaler = ClusterAutoscaler(
            server, sched, autoscaler_catalog, **(autoscaler_kwargs or {})
        )
        sched._autoscaler = autoscaler

    def start_scheduling():
        sched.start()
        if autoscaler is not None:
            autoscaler.start()
        healthy.set()

    if cfg.leader_election is not None:
        def on_stopped():
            # leaderelection.go: losing the lease is fatal for the process
            logger.error("leader election lost; shutting down scheduling")
            healthy.clear()
            if autoscaler is not None:
                autoscaler.stop()
            sched.stop()
            stop.set()

        elector = LeaderElector(
            server,
            cfg.leader_election,
            on_started_leading=start_scheduling,
            on_stopped_leading=on_stopped,
        )
        threading.Thread(target=elector.run, daemon=True).start()
        sched._elector = elector
    else:
        start_scheduling()

    if block:
        try:
            while not stop.is_set():
                stop.wait(1.0)
        except KeyboardInterrupt:
            pass
        finally:
            if autoscaler is not None:
                autoscaler.stop()
            sched.stop()
    return sched


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kube-scheduler-tpu")
    parser.add_argument("--config", help="ComponentConfig or Policy file")
    parser.add_argument("--healthz-port", type=int, default=10251)
    parser.add_argument(
        "--leader-elect", action="store_true", default=False
    )
    parser.add_argument(
        "--platform",
        default="",
        help="force a JAX platform (e.g. 'cpu' to run without the TPU — "
        "the device-failure fallback path)",
    )
    parser.add_argument(
        "--autoscale-shapes",
        default="",
        help="enable the kernel-driven cluster autoscaler with a shape "
        "catalog: semicolon-separated 'name:cpu,memory,maxPods,maxSize' "
        "entries (e.g. 'small:4,32Gi,110,100;big:32,256Gi,110,20')",
    )
    parser.add_argument("-v", "--verbosity", type=int, default=1)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO
    )
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    cfg = (
        load_config_file(args.config)
        if args.config
        else KubeSchedulerConfiguration()
    )
    if args.leader_elect and cfg.leader_election is None:
        cfg.leader_election = LeaderElectionConfig()
    catalog = None
    if args.autoscale_shapes:
        from ..autoscaler import NodeGroup, NodeGroupCatalog, machine_shape

        groups = []
        for entry in filter(None, args.autoscale_shapes.split(";")):
            name, spec = entry.split(":", 1)
            cpu, memory, max_pods, max_size = spec.split(",")
            groups.append(
                NodeGroup(
                    name=name.strip(),
                    template=machine_shape(
                        cpu=cpu.strip(),
                        memory=memory.strip(),
                        pods=int(max_pods),
                    ),
                    max_size=int(max_size),
                )
            )
        catalog = NodeGroupCatalog(groups)
    run(config=cfg, healthz_port=args.healthz_port, autoscaler_catalog=catalog)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
