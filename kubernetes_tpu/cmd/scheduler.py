"""kube-scheduler process entry.

Reference: cmd/kube-scheduler/app/server.go — runCommand/Setup (:302),
Run (:142): healthz server (:10251, server.go:160-171), metrics mux
(:237-268 with the debug DELETE reset), leader election gating sched.Run
(:196-210 — losing leadership is fatal), SIGUSR2 cache debugger.

The API backend is the in-process store by default; ``--server URL``
runs the replica against a remote apiserver process over REST (leases,
informer streams, and leadership-fenced binds all cross the wire — the
/binding route validates the X-Leadership-Fence header).
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..client.apiserver import APIServer
from ..client.leaderelection import LeaderElectionConfig, LeaderElector
from ..scheduler import KubeSchedulerConfiguration, Scheduler
from ..scheduler.apis_config import load_config_file
from ..scheduler.cache.debugger import CacheDebugger
from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.cmd.scheduler")


class _HealthHandler(BaseHTTPRequestHandler):
    server_version = "kube-scheduler-tpu"

    def log_message(self, *args):
        pass

    def _respond(self, code: int, body: bytes, ctype="text/plain"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path in ("/healthz", "/livez"):
            # liveness: the process is serving — a WARM STANDBY is alive
            # (reference kube-scheduler serves healthz OK while waiting
            # for the lease; a liveness probe must not restart-loop every
            # standby replica out of its warm state)
            ok = self.server.health_check()
            self._respond(200 if ok else 500, b"ok" if ok else b"unhealthy")
        elif self.path == "/readyz":
            # readiness: actually leading (scheduling loops running)
            ok = self.server.ready_check()
            self._respond(200 if ok else 500, b"ok" if ok else b"standby")
        elif self.path == "/metrics":
            # content negotiation: Prometheus exposition text by default
            # (what the reference's legacyregistry serves); JSON on request
            from ..utils.debugserver import metrics_payload

            if "application/json" in (self.headers.get("Accept") or ""):
                from ..utils.tracing import tracer

                tracer.publish_gauges()  # tracing series are batch-published
                body = json.dumps(metrics.dump(), indent=1).encode()
                self._respond(200, body, "application/json")
            else:
                self._respond(200, *metrics_payload())
        else:
            self._respond(404, b"not found")

    def do_DELETE(self):
        # debug handler: DELETE /metrics resets (server.go:237-247)
        if self.path == "/metrics":
            metrics.reset()
            self._respond(200, b"metrics reset\n")
        else:
            self._respond(404, b"not found")


def serve_health(port: int, health_check, ready_check=None) -> ThreadingHTTPServer:
    srv = ThreadingHTTPServer(("0.0.0.0", port), _HealthHandler)
    srv.health_check = health_check
    srv.ready_check = ready_check or health_check
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def run(
    server: Optional[APIServer] = None,
    config: Optional[KubeSchedulerConfiguration] = None,
    healthz_port: int = 10251,
    block: bool = True,
    autoscaler_catalog=None,
    autoscaler_kwargs: Optional[dict] = None,
    watch_cache: bool = True,
    debug_port: Optional[int] = None,
    deschedule: bool = False,
    descheduler_kwargs: Optional[dict] = None,
) -> Scheduler:
    """app.Run (server.go:142): health endpoints → informers → leader
    election (optional) → scheduling loops. autoscaler_catalog (a
    NodeGroupCatalog) additionally runs the kernel-driven cluster
    autoscaler against this scheduler's snapshot — it follows the
    scheduler's leadership (starts with scheduling, stops with it).

    watch_cache: point the scheduler's informers at a shared Cacher
    (apiserver/cacher.py) instead of direct store watches — N scheduler
    replicas (leader + warm standbys) then cost ONE store watch per kind
    total; writes pass through to the store untouched.

    With leader election configured the process starts as a WARM STANDBY
    (informers tailing, HBM snapshot + kernels warm, nothing scheduling)
    and the election winner promotes: it adopts the dead leader's
    in-flight wave from store read-back and arms the leadership bind
    fence so a zombie ex-leader's late binds are rejected."""
    server = server or APIServer()
    cfg = config or KubeSchedulerConfiguration()
    backend = server
    if watch_cache:
        from ..apiserver.cacher import Cacher

        backend = Cacher(server)
    sched = Scheduler(backend, cfg)
    if backend is not server:
        sched._owned_read_cache = backend  # torn down by sched.stop()
    # live = the process is serving (a warm standby IS live); ready =
    # actually leading. Split so a liveness probe never restart-loops a
    # standby replica out of its warm state.
    live = threading.Event()
    ready = threading.Event()
    if healthz_port:
        serve_health(
            healthz_port, lambda: live.is_set(), lambda: ready.is_set()
        )
    if debug_port is not None:
        # /metrics + /debug/traces for THIS scheduler process (the
        # SIGUSR2 dump's HTTP twin — trace lookups without log access)
        from ..utils.debugserver import serve_debug

        serve_debug(debug_port)
    CacheDebugger(sched).listen_for_signal()

    stop = threading.Event()
    # ONE process-wide eviction token bucket: nodelifecycle drains,
    # autoscaler scale-down, preemption victim deletes, and descheduler
    # consolidation all draw from the same qps+burst — three storms can't
    # triple the eviction rate (controller/evictionbudget.py)
    from ..controller.evictionbudget import EvictionBudget

    a_kwargs = dict(autoscaler_kwargs or {})
    budget = a_kwargs.get("eviction_budget") or EvictionBudget(
        a_kwargs.get("eviction_qps", 10.0),
        a_kwargs.get("eviction_burst", 5),
    )
    a_kwargs["eviction_budget"] = budget
    sched.eviction_budget = budget
    autoscaler = None
    if autoscaler_catalog is not None:
        from ..autoscaler import ClusterAutoscaler

        autoscaler = ClusterAutoscaler(
            server, sched, autoscaler_catalog, **a_kwargs
        )
        sched._autoscaler = autoscaler
    descheduler = None
    if deschedule:
        # the descheduler follows scheduler leadership exactly like the
        # autoscaler, shares its eviction budget, and talks to the RAW
        # store (evictions and cordons are fenced writes, never cached)
        from ..descheduler import Descheduler

        descheduler = Descheduler(
            server,
            sched,
            budget,
            catalog=autoscaler_catalog,
            **(descheduler_kwargs or {}),
        )
        sched._descheduler = descheduler
    tuner = None
    if cfg.tune_policy:
        # the policy gym follows leadership like the autoscaler: only the
        # leader records waves, replays candidates, and promotes. It
        # talks to the RAW store (never the cacher) — the persisted
        # ScorePolicy object is the failover-adoption authority
        from ..tuner.controller import PolicyTuner

        tuner = PolicyTuner(sched, server)
        sched._tuner = tuner

    def start_scheduling():
        sched.start()
        if autoscaler is not None:
            autoscaler.start()
        if descheduler is not None:
            descheduler.start()
        if tuner is not None:
            tuner.start()
        live.set()
        ready.set()

    elector = None
    elector_thread = None
    if cfg.leader_election is not None:
        # warm standby FIRST: by the time the election resolves (instant
        # for the first replica, a failover later for the rest) the cache,
        # the HBM snapshot, and the compiled kernels are already hot
        sched.start_standby(identity=cfg.leader_election.identity)
        live.set()  # a warm standby is live (not yet ready)

        def on_started():
            sched.promote(fence=elector.fence())
            if autoscaler is not None:
                autoscaler.start()
            if descheduler is not None:
                descheduler.start()
            if tuner is not None:
                tuner.start()
            ready.set()

        def on_stopped():
            # leaderelection.go: losing the lease is fatal for the process
            logger.error("leader election lost; shutting down scheduling")
            ready.clear()
            live.clear()
            if tuner is not None:
                tuner.stop()
            if descheduler is not None:
                descheduler.stop()
            if autoscaler is not None:
                autoscaler.stop()
            sched.stop()
            stop.set()

        # the elector talks to the raw store: lease reads/writes are the
        # fencing authority and must never be served from a cache
        elector = LeaderElector(
            server,
            cfg.leader_election,
            on_started_leading=on_started,
            on_stopped_leading=on_stopped,
        )
        elector_thread = threading.Thread(target=elector.run, daemon=True)
        elector_thread.start()
        sched._elector = elector
        sched._elector_thread = elector_thread
    else:
        start_scheduling()

    if block:
        try:
            while not stop.is_set():
                stop.wait(1.0)
        except KeyboardInterrupt:
            pass
        finally:
            if elector is not None:
                # graceful shutdown RELEASES the lease (ReleaseOnCancel):
                # the standby promotes in retry-periods, not after waiting
                # out lease_duration — join so the release lands before
                # the process exits
                elector.stop()
                if elector_thread is not None:
                    elector_thread.join(timeout=5.0)
            if tuner is not None:
                tuner.stop()
            if descheduler is not None:
                descheduler.stop()
            if autoscaler is not None:
                autoscaler.stop()
            sched.stop()
    return sched


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kube-scheduler-tpu")
    parser.add_argument("--config", help="ComponentConfig or Policy file")
    parser.add_argument("--healthz-port", type=int, default=10251)
    parser.add_argument(
        "--debug-port",
        type=int,
        default=None,
        help="serve /metrics (Prometheus text) and /debug/traces "
        "(slowest-N / by-trace-id lookup) on this loopback port "
        "(default off; 0 = ephemeral)",
    )
    parser.add_argument(
        "--leader-elect", action="store_true", default=False
    )
    parser.add_argument(
        "--leader-elect-identity",
        default="",
        help="lease holder identity for this replica (default "
        "hostname_uuid); replicas past the first start as warm standbys",
    )
    parser.add_argument(
        "--no-watch-cache",
        action="store_true",
        default=False,
        help="informers watch the store directly instead of the shared "
        "watch cache (one store watch per kind per replica)",
    )
    parser.add_argument(
        "--server",
        default="",
        help="API server base URL (e.g. http://127.0.0.1:18080): run this "
        "replica against a remote apiserver process over REST instead of "
        "an in-process store. Leader election and bind fencing work "
        "end-to-end over the wire (the /binding route validates the "
        "X-Leadership-Fence header)",
    )
    parser.add_argument(
        "--platform",
        default="",
        help="force a JAX platform (e.g. 'cpu' to run without the TPU — "
        "the device-failure fallback path)",
    )
    parser.add_argument(
        "--autoscale-shapes",
        default="",
        help="enable the kernel-driven cluster autoscaler with a shape "
        "catalog: semicolon-separated 'name:cpu,memory,maxPods,maxSize' "
        "entries (e.g. 'small:4,32Gi,110,100;big:32,256Gi,110,20')",
    )
    parser.add_argument(
        "--deschedule",
        action="store_true",
        default=False,
        help="run the verified descheduler: consolidation plans proven on "
        "the what-if overlay before any eviction, executed in budgeted "
        "waves with drift re-simulation, PDB re-checks, gang quorum, and "
        "uncordon rollback (shares the process-wide eviction budget)",
    )
    parser.add_argument(
        "--score-policy",
        default="",
        help="named score policy (ops/lattice.WEIGHT_PROFILES: 'default', "
        "'pack', 'cheapest', 'energy', ...): a runtime weight VECTOR over "
        "the score components — swapping policies never recompiles the "
        "kernels (Scheduler.set_score_policy swaps live)",
    )
    parser.add_argument(
        "--tune-policy",
        action="store_true",
        default=False,
        help="run the policy gym (tuner/): record real scheduling waves, "
        "replay candidate weight vectors against them in the background, "
        "and promote winners through a shadow A/B gate — the promoted "
        "vector persists as the ScorePolicy API object so failover adopts "
        "it instead of reverting to the default",
    )
    parser.add_argument("-v", "--verbosity", type=int, default=1)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO
    )
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    # persistent compilation cache, on by default: the generational
    # snapshot made donation safe against deserialized executables (see
    # utils/compilation_cache.py), so a replica restart or a standby
    # promotion deserializes its kernels instead of paying the cold-start
    # compile storm. KTPU_NO_COMPILATION_CACHE=1 opts out.
    from ..utils.compilation_cache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    cfg = (
        load_config_file(args.config)
        if args.config
        else KubeSchedulerConfiguration()
    )
    if args.leader_elect and cfg.leader_election is None:
        cfg.leader_election = LeaderElectionConfig()
    if args.leader_elect_identity and cfg.leader_election is not None:
        cfg.leader_election.identity = args.leader_elect_identity
    if args.score_policy:
        cfg.score_policy = args.score_policy
        cfg.validate()  # unknown names fail here, not mid-wave
    if args.tune_policy:
        cfg.tune_policy = True
    catalog = None
    if args.autoscale_shapes:
        from ..autoscaler import NodeGroup, NodeGroupCatalog, machine_shape

        groups = []
        for entry in filter(None, args.autoscale_shapes.split(";")):
            name, spec = entry.split(":", 1)
            cpu, memory, max_pods, max_size = spec.split(",")
            groups.append(
                NodeGroup(
                    name=name.strip(),
                    template=machine_shape(
                        cpu=cpu.strip(),
                        memory=memory.strip(),
                        pods=int(max_pods),
                    ),
                    max_size=int(max_size),
                )
            )
        catalog = NodeGroupCatalog(groups)
    server = None
    if args.server:
        from ..apiserver.client import RESTClient

        server = RESTClient(args.server)
    run(
        server=server,
        config=cfg,
        healthz_port=args.healthz_port,
        autoscaler_catalog=catalog,
        watch_cache=not args.no_watch_cache,
        debug_port=args.debug_port,
        deschedule=args.deschedule,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
