"""kube-apiserver process entry: the REST façade as a standalone process.

Reference: cmd/kube-apiserver/app/server.go — one process serving the core
group, CRD-defined groups (apiextensions path), and aggregated groups
(APIService proxying), with optional authn/authz via apiserver/auth.py.
"""

from __future__ import annotations

import argparse
import logging
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kube-apiserver-tpu")
    parser.add_argument("--port", type=int, default=18080)
    parser.add_argument("-v", "--verbosity", type=int, default=1)
    # the watch cache (apiserver/cacher.py): --watch-cache=0 falls back
    # to per-client store watches; --watch-cache-window sizes the
    # RV replay ring; --bookmark-period the progress-notify cadence
    parser.add_argument("--watch-cache", type=int, default=1)
    parser.add_argument("--watch-cache-window", type=int, default=0)
    parser.add_argument("--bookmark-period", type=float, default=2.0)
    # serving-tier scale-out (apiserver/frontend.py): --frontend-of runs
    # this process as a STATELESS frontend over a remote primary (own
    # watch cache, writes delegated upstream); --follower-of tails a
    # primary's replication listener and serves commit-gated follower
    # reads (requires --primary for the write/point-get delegate);
    # --repl-port/--cluster-size arm the primary's replication listener
    # so followers/frontend fleets have something to attach to.
    parser.add_argument("--frontend-of", default="")
    parser.add_argument("--follower-of", default="",
                        help="primary replication address host:port")
    parser.add_argument("--primary", default="",
                        help="primary REST url (follower mode)")
    parser.add_argument("--node-id", type=int, default=1)
    parser.add_argument("--repl-port", type=int, default=0)
    parser.add_argument("--cluster-size", type=int, default=0)
    # TLS on the serving hop: both given -> the REST port (and the relay
    # workers, in frontend mode) serve https
    parser.add_argument("--tls-cert", default="")
    parser.add_argument("--tls-key", default="")
    # watch-relay tier (frontend mode only, kubernetes_tpu/relay/):
    # --relay-workers N spawns N SO_REUSEPORT fan-out workers over a
    # shared-memory frame ring fed by this frontend's watch cache
    parser.add_argument("--relay-workers", type=int, default=0)
    parser.add_argument("--relay-port", type=int, default=0)
    args = parser.parse_args(argv)
    if bool(args.tls_cert) != bool(args.tls_key):
        parser.error("--tls-cert and --tls-key must be given together")
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO
    )
    # the apiserver itself is host-only, but control-plane helpers it
    # hosts (e.g. an in-process scheduler replica in tests, tooling that
    # imports through this entry) share the process: point JAX at the
    # persistent compilation cache up front so any kernel they compile
    # lands in (or comes from) the shared cache. Safe post-generational
    # snapshot; KTPU_NO_COMPILATION_CACHE=1 opts out.
    from ..utils.compilation_cache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    log = logging.getLogger("kubernetes_tpu.cmd.apiserver")
    serve_kwargs = dict(
        port=args.port,
        watch_cache=bool(args.watch_cache),
        watch_cache_window=args.watch_cache_window,
        bookmark_period_s=args.bookmark_period,
        tls_cert=args.tls_cert or None,
        tls_key=args.tls_key or None,
    )
    if args.frontend_of:
        from ..apiserver.frontend import serve_frontend

        srv, port, _client = serve_frontend(
            args.frontend_of,
            relay_workers=args.relay_workers,
            relay_port=args.relay_port,
            **serve_kwargs,
        )
        if getattr(srv, "relay", None) is not None:
            log.info(
                "watch relay on :%d (%d workers%s)",
                srv.relay.port, args.relay_workers,
                ", tls" if srv.relay.tls else "",
            )
        log.info(
            "serving /api/v1 on :%d (stateless frontend of %s)",
            port, args.frontend_of,
        )
    elif args.follower_of:
        if not args.primary:
            # no derivable fallback exists: --follower-of names the
            # REPLICATION listener, whose port says nothing about the
            # primary's REST port
            parser.error("--follower-of requires --primary (the primary's "
                         "REST url for the write/point-get delegate)")
        host, _, rport = args.follower_of.partition(":")
        from ..apiserver.frontend import serve_follower_frontend
        from ..runtime.replication import Follower

        follower = Follower((host, int(rport)), node_id=args.node_id).start()
        if not follower.wait_synced(30.0):
            log.error("follower never synced to %s", args.follower_of)
            return 1
        srv, port, _store = serve_follower_frontend(
            follower, args.primary, **serve_kwargs,
        )
        log.info(
            "serving /api/v1 on :%d (follower reads of %s)",
            port, args.follower_of,
        )
    else:
        from ..apiserver.rest import serve

        srv, port, store = serve(**serve_kwargs)
        if args.repl_port or args.cluster_size:
            from ..runtime.replication import ReplicationListener

            listener = ReplicationListener(
                port=args.repl_port,
                cluster_size=args.cluster_size or None,
            )
            listener.attach(store)
            log.info("replication listener on :%d", listener.address[1])
        log.info("serving /api/v1 on :%d", port)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
