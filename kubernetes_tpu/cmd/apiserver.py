"""kube-apiserver process entry: the REST façade as a standalone process.

Reference: cmd/kube-apiserver/app/server.go — one process serving the core
group, CRD-defined groups (apiextensions path), and aggregated groups
(APIService proxying), with optional authn/authz via apiserver/auth.py.
"""

from __future__ import annotations

import argparse
import logging
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kube-apiserver-tpu")
    parser.add_argument("--port", type=int, default=18080)
    parser.add_argument("-v", "--verbosity", type=int, default=1)
    # the watch cache (apiserver/cacher.py): --watch-cache=0 falls back
    # to per-client store watches; --watch-cache-window sizes the
    # RV replay ring; --bookmark-period the progress-notify cadence
    parser.add_argument("--watch-cache", type=int, default=1)
    parser.add_argument("--watch-cache-window", type=int, default=0)
    parser.add_argument("--bookmark-period", type=float, default=2.0)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO
    )
    # the apiserver itself is host-only, but control-plane helpers it
    # hosts (e.g. an in-process scheduler replica in tests, tooling that
    # imports through this entry) share the process: point JAX at the
    # persistent compilation cache up front so any kernel they compile
    # lands in (or comes from) the shared cache. Safe post-generational
    # snapshot; KTPU_NO_COMPILATION_CACHE=1 opts out.
    from ..utils.compilation_cache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    from ..apiserver.rest import serve

    srv, port, _store = serve(
        port=args.port,
        watch_cache=bool(args.watch_cache),
        watch_cache_window=args.watch_cache_window,
        bookmark_period_s=args.bookmark_period,
    )
    logging.getLogger("kubernetes_tpu.cmd.apiserver").info(
        "serving /api/v1 on :%d", port
    )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
