"""kubeadm-lite: phased cluster bootstrap.

Reference: cmd/kubeadm/app/cmd/phases/init/ — `kubeadm init` runs named
phases (certs → kubeconfig → control-plane → bootstrap-token → addons) and
prints a join command; `kubeadm join` registers a node using the bootstrap
token. Here the control plane is the in-process stack (store+WAL → REST
facade with authn/RBAC/admission → scheduler → controller-manager), the
"certs" phase is the bearer-token trust root (no x509 in this build), and
join starts a kubelet against the API over its token.

Programmatic surface (used by tests and the CLI):
    handle = init_cluster(data_dir, port)   # phases, returns running stack
    join_node(server_url, token, node_name) # register + run a node agent
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

logger = logging.getLogger("kubernetes_tpu.cmd.kubeadm")

BOOTSTRAP_TOKEN_SECRET = "bootstrap-token"
ADMIN_CONF = "admin.conf.json"


@dataclass
class ClusterHandle:
    store: object
    http_server: object
    port: int
    scheduler: object
    controller_manager: object
    admin_token: str
    bootstrap_token: str
    data_dir: str
    _joined: List[object] = field(default_factory=list)
    replication: object = None  # ReplicationListener when HA is enabled

    @property
    def server_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def replication_address(self):
        return self.replication.address if self.replication else None

    def stop(self) -> None:
        for pool in self._joined:
            pool.stop()
        self.controller_manager.stop()
        self.scheduler.stop()
        if self.replication is not None:
            self.replication.close()
        self.http_server.shutdown()
        audit = getattr(self.http_server, "audit", None)
        if audit is not None:
            audit.stop()  # drain + close the audit writer


def assemble_security(store, admin_token=None, bootstrap_token=None):
    """The apiserver's trust + admission assembly, shared by init and the
    standby's promotion (a failover must NOT downgrade the cluster to an
    unauthenticated, admission-free API server). Returns (authn, authz)
    and installs the admit-hook chain on the store."""
    from ..apiserver.admission import (
        CertificateApprovalAdmission,
        CertificateSigningAdmission,
        CertificateSubjectRestrictionAdmission,
        DefaultIngressClassAdmission,
        ExtendedResourceTolerationAdmission,
        NodeRestrictionAdmission,
        PodNodeSelectorAdmission,
        PodSecurityPolicyAdmission,
        PodTolerationRestrictionAdmission,
        PVCResizeAdmission,
        RuntimeClassAdmission,
        StorageObjectInUseProtectionAdmission,
        TaintNodesByConditionAdmission,
    )
    from ..apiserver.auth import (
        MASTERS_GROUP,
        AdmissionChain,
        DefaultStorageClassAdmission,
        DefaultTolerationSecondsAdmission,
        LimitRangerAdmission,
        NamespaceLifecycleAdmission,
        PriorityAdmission,
        QuotaAdmission,
        RBACAuthorizer,
        ServiceAccountAdmission,
        TokenAuthenticator,
        make_rule,
    )
    from ..apiserver.webhook import (
        MutatingWebhookAdmission,
        ValidatingWebhookAdmission,
    )
    from ..proxy import ClusterIPAllocator

    authn = TokenAuthenticator(server=store, allow_anonymous=False)
    if admin_token:
        authn.add_token(
            admin_token, "kubernetes-admin", groups=(MASTERS_GROUP,)
        )
    if bootstrap_token:
        authn.add_token(
            bootstrap_token, "system:bootstrap", groups=("system:bootstrappers",)
        )
    # server-backed: ClusterRole/ClusterRoleBinding objects created via the
    # API feed authorization alongside the programmatic bootstrap policy;
    # node identities (system:node:*) route through the node authorizer's
    # decision table instead (plugin/pkg/auth/authorizer/node)
    from ..apiserver.nodeauth import NodeAwareAuthorizer

    authz = NodeAwareAuthorizer(RBACAuthorizer(server=store), store)
    # bootstrappers run node agents: register + heartbeat, sync pods, and
    # feed the node-side service dataplane (the system:node role shape)
    authz.bind(
        "system:bootstrappers",
        make_rule(["create", "update", "get"], ["nodes", "leases"]),
    )
    authz.bind(
        "system:bootstrappers",
        make_rule(["get", "list", "watch", "update"], ["pods"]),
    )
    authz.bind(
        "system:bootstrappers",
        make_rule(["get", "list", "watch"], ["services", "endpoints"]),
    )
    # token discovery: joining nodes read the signed cluster-info document
    authz.bind(
        "system:bootstrappers", make_rule(["get"], ["configmaps"], ["kube-public"])
    )
    # TLS bootstrap: file + poll the kubelet CSR (the reference's
    # system:node-bootstrapper ClusterRole)
    authz.bind(
        "system:bootstrappers",
        make_rule(["create", "get"], ["certificatesigningrequests"]),
    )
    store.admit_hooks.append(ClusterIPAllocator())
    # mutators first, then validators (admission/chain.go ordering); the
    # per-phase sequence follows the reference's recommended order
    # (pkg/kubeapiserver/options/plugins.go:64 AllOrderedPlugins). Notable
    # reference-faithful consequences: DefaultTolerationSeconds' injected
    # tolerations ARE subject to a namespace whitelist (it precedes
    # PodTolerationRestriction) while ExtendedResourceToleration's are
    # not (it follows); ResourceQuota runs last, after the webhooks.
    store.admit_hooks.append(
        AdmissionChain(
            mutating=[
                LimitRangerAdmission(store),
                ServiceAccountAdmission(),
                TaintNodesByConditionAdmission(),
                PodNodeSelectorAdmission(store),
                PriorityAdmission(store),
                DefaultTolerationSecondsAdmission(),
                PodTolerationRestrictionAdmission(store),
                ExtendedResourceTolerationAdmission(),
                DefaultStorageClassAdmission(store),
                StorageObjectInUseProtectionAdmission(),
                RuntimeClassAdmission(store),
                DefaultIngressClassAdmission(store),
                MutatingWebhookAdmission(store),
            ],
            validating=[
                NamespaceLifecycleAdmission(store),
                LimitRangerAdmission(store),
                NodeRestrictionAdmission(),
                PodSecurityPolicyAdmission(store),
                PVCResizeAdmission(store),
                CertificateApprovalAdmission(authz, store),
                CertificateSigningAdmission(authz, store),
                CertificateSubjectRestrictionAdmission(),
                ValidatingWebhookAdmission(store),
                QuotaAdmission(store),
            ],
        )
    )
    return authn, authz


def init_cluster(
    data_dir: str,
    port: int = 0,
    controllers: Optional[List[str]] = None,
    replication: bool = False,
) -> ClusterHandle:
    """Run every init phase; returns the live control plane. With
    replication=True the store also serves a replication endpoint
    (runtime/replication.py) so standby control planes can tail it —
    handle.replication_address is what `kubeadm standby` dials."""
    from ..apiserver.rest import serve
    from ..client.apiserver import APIServer
    from ..controller.manager import ControllerManager
    from ..runtime.wal import WriteAheadLog
    from ..scheduler import KubeSchedulerConfiguration, Scheduler

    os.makedirs(data_dir, exist_ok=True)

    # -- phase certs: trust material (bearer tokens stand in for x509) ------
    admin_token = secrets.token_urlsafe(24)
    # bootstrap token in the reference's <id>.<secret> form
    # (cluster-bootstrap/token/util): the id is public (names the JWS
    # signature key on cluster-info), the secret half proves possession
    token_id = secrets.token_hex(3)
    token_secret = secrets.token_urlsafe(16)
    bootstrap_token = f"{token_id}.{token_secret}"
    logger.info("[certs] generated admin + bootstrap tokens")

    # -- phase etcd/control-plane: durable store + REST facade --------------
    store = APIServer(wal=WriteAheadLog(os.path.join(data_dir, "cluster")))
    repl = None
    if replication:
        from ..runtime.replication import ReplicationListener

        repl = ReplicationListener()
        repl.attach(store)
        logger.info(
            "[etcd] replication endpoint on %s:%d", *repl.address
        )
    authn, authz = assemble_security(store, admin_token, bootstrap_token)
    from ..apiserver.audit import AuditLogger

    http_server, port, _ = serve(
        store=store,
        port=port,
        authenticator=authn,
        authorizer=authz,
        audit=AuditLogger(path=os.path.join(data_dir, "audit.jsonl")),
    )
    logger.info("[control-plane] apiserver on :%d (WAL at %s)", port, data_dir)

    # -- phase kubeconfig ----------------------------------------------------
    conf = {
        "server": f"http://127.0.0.1:{port}",
        "token": admin_token,
        "user": "kubernetes-admin",
    }
    with open(os.path.join(data_dir, ADMIN_CONF), "w") as f:
        json.dump(conf, f, indent=2)
    logger.info("[kubeconfig] wrote %s", ADMIN_CONF)

    # -- phase control-plane components -------------------------------------
    sched = Scheduler(store, KubeSchedulerConfiguration())
    sched.start()
    cm = ControllerManager(store, controllers=controllers)
    cm.start()
    logger.info("[control-plane] scheduler + controller-manager running")

    # -- phase bootstrap-token: discoverable join secret ---------------------
    from ..api import objects as v1

    store.create(
        "secrets",
        v1.Secret(
            metadata=v1.ObjectMeta(
                name=BOOTSTRAP_TOKEN_SECRET, namespace="kube-system"
            ),
            type="bootstrap.kubernetes.io/token",
            data={
                "token": bootstrap_token.encode(),
                "token-id": token_id.encode(),
                "token-secret": token_secret.encode(),
                "usage-bootstrap-signing": b"true",
            },
        ),
    )
    logger.info("[bootstrap-token] join token stored")

    # -- phase upload-config: kubeadm-config (upgrade's source of truth) -----
    from .. import __version__ as _cluster_version

    store.create(
        "configmaps",
        v1.ConfigMap(
            metadata=v1.ObjectMeta(name="kubeadm-config", namespace="kube-system"),
            data={
                "ClusterConfiguration": json.dumps(
                    {"kubernetesVersion": _cluster_version}
                )
            },
        ),
    )

    # -- phase upload-config/addons: public discovery document ---------------
    # cluster-info in kube-public carries ONLY the server location (no
    # credentials); the bootstrapsigner controller attaches per-token JWS
    # signatures so a joining node can verify it with just its token
    store.create(
        "configmaps",
        v1.ConfigMap(
            metadata=v1.ObjectMeta(name="cluster-info", namespace="kube-public"),
            data={"kubeconfig": json.dumps({"server": f"http://127.0.0.1:{port}"})},
        ),
    )
    logger.info("[upload-config] cluster-info published to kube-public")

    return ClusterHandle(
        store=store,
        http_server=http_server,
        port=port,
        scheduler=sched,
        controller_manager=cm,
        admin_token=admin_token,
        bootstrap_token=bootstrap_token,
        data_dir=data_dir,
        replication=repl,
    )


def standby_cluster(
    primary_addr,
    data_dir: str,
    lease_s: float = 1.0,
    port: int = 0,
    controllers: Optional[List[str]] = None,
    admin_token: Optional[str] = None,
    insecure: bool = False,
):
    """`kubeadm standby`: a warm control plane behind a replica store.

    Tails the primary's replication stream (full state + live records,
    persisted to its own WAL); when the primary's lease lapses — or
    promote() is called — the replica becomes a LIVE control plane with
    the SAME trust + admission assembly as init (failover must not
    downgrade security; pass the cluster's admin token, or insecure=True
    for the dev port), fences the old primary best-effort (higher-term
    hello — a merely-stalled primary steps down read-only instead of
    splitting the brain), and the scheduler re-lists from the replicated
    state. Returns a StandbyHandle with .wait_promoted()/.promote()."""
    from ..runtime.replication import Follower
    from ..runtime.wal import WriteAheadLog

    if admin_token is None and not insecure:
        raise ValueError(
            "standby_cluster needs the cluster admin token (or insecure=True):"
            " a promoted control plane must keep authenticating"
        )
    os.makedirs(data_dir, exist_ok=True)

    class StandbyHandle:
        def __init__(self):
            self.follower = None
            self.cluster: Optional[ClusterHandle] = None
            self.promote_error: Optional[BaseException] = None
            self._promoted = threading.Event()

        def wait_promoted(self, timeout: float = 30.0) -> bool:
            return self._promoted.wait(timeout)

        def promote(self) -> ClusterHandle:
            self.follower.promote()
            if not self.wait_promoted():
                raise RuntimeError("standby promotion timed out")
            if self.cluster is None:
                raise RuntimeError(
                    f"standby promotion failed: {self.promote_error}"
                )
            return self.cluster

        def stop(self):
            self.follower.stop()
            if self.cluster is not None:
                self.cluster.stop()

    handle = StandbyHandle()

    def on_promote(server):
        try:
            from ..apiserver.audit import AuditLogger
            from ..apiserver.rest import serve
            from ..controller.manager import ControllerManager
            from ..scheduler import KubeSchedulerConfiguration, Scheduler

            if insecure:
                authn = authz = None
            else:
                authn, authz = assemble_security(server, admin_token)
            http_server, bound_port, _ = serve(
                store=server,
                port=port,
                authenticator=authn,
                authorizer=authz,
                audit=AuditLogger(path=os.path.join(data_dir, "audit.jsonl")),
            )
            sched = Scheduler(server, KubeSchedulerConfiguration())
            sched.start()
            cmgr = ControllerManager(server, controllers=controllers)
            cmgr.start()
            handle.cluster = ClusterHandle(
                store=server,
                http_server=http_server,
                port=bound_port,
                scheduler=sched,
                controller_manager=cmgr,
                admin_token=admin_token or "",
                bootstrap_token="",
                data_dir=data_dir,
            )
            logger.warning(
                "[standby] promoted: control plane serving on :%d", bound_port
            )
        except BaseException as e:  # surfaced by StandbyHandle.promote
            handle.promote_error = e
            raise
        finally:
            handle._promoted.set()

    handle.follower = Follower(
        primary_addr,
        lease_s=lease_s,
        wal=WriteAheadLog(os.path.join(data_dir, "cluster")),
        on_promote=on_promote,
    ).start()
    return handle


def discover_cluster_info(
    server_url: str, token: str, timeout: float = 10.0
) -> dict:
    """Bootstrap token discovery (cmd/kubeadm/app/discovery/token): fetch
    the kube-public cluster-info document and verify its detached JWS
    signature with the `<id>.<secret>` token before trusting anything in
    it. Raises PermissionError on a missing or wrong signature — an
    unsigned endpoint could be an impostor control plane."""
    import time as _time

    from ..apiserver.client import AuthRESTClient
    from ..controller.bootstrap import JWS_PREFIX, compute_detached_signature

    token_id, _, token_secret = token.partition(".")
    client = AuthRESTClient(server_url, token=token)
    deadline = _time.monotonic() + timeout
    last = "cluster-info not found"
    while _time.monotonic() < deadline:
        try:
            cm = client.get("configmaps", "kube-public", "cluster-info")
            content = cm.data.get("kubeconfig", "")
            sig = cm.data.get(JWS_PREFIX + token_id, "")
            if sig and content:
                want = compute_detached_signature(content, token_id, token_secret)
                if sig == want:
                    return json.loads(content)
                raise PermissionError(
                    "cluster-info signature mismatch for token id "
                    f"{token_id!r}: refusing to join"
                )
            last = f"no signature yet for token id {token_id!r}"
        except PermissionError:
            raise
        except Exception as e:  # not served yet / signer still reconciling
            last = str(e)
        _time.sleep(0.2)
    raise PermissionError(f"cluster-info discovery failed: {last}")


def _request_node_credential(
    client, node_name: str, timeout_s: float = 10.0
) -> str:
    """File a kubelet CSR and wait for the signed credential ('' on
    timeout). Reference flow: kubelet TLS bootstrap — CSR with
    CN=system:node:<name>, O=system:nodes, auto-approved (sarapprove) and
    signed (certificates signer)."""
    from ..api import objects as v1
    from ..client.apiserver import AlreadyExists

    csr_name = f"node-csr-{node_name}"
    csr = v1.CertificateSigningRequest(
        metadata=v1.ObjectMeta(name=csr_name, namespace=""),
        spec=v1.CertificateSigningRequestSpec(
            request=node_name,
            username=f"system:node:{node_name}",
            groups=["system:nodes"],
            usages=["client auth"],
            signer_name="kubernetes.io/kube-apiserver-client-kubelet",
        ),
    )
    try:
        client.create("certificatesigningrequests", csr)
    except AlreadyExists:
        pass
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            cur = client.get("certificatesigningrequests", "", csr_name)
        except Exception:
            cur = None
        cert = cur.status.certificate if cur is not None else ""
        if cert:
            return cert
        time.sleep(0.1)
    return ""


def join_node(
    server_url: str,
    token: str,
    node_name: str,
    cpu: str = "8",
    memory: str = "32Gi",
    handle: Optional[ClusterHandle] = None,
):
    """`kubeadm join`: register the node over the bootstrap token and run a
    node agent against the API (remote client, same kubelet code path).
    When `handle` is given (in-process clusters), the pool is owned by it
    and stops with ClusterHandle.stop()."""
    from ..apiserver.client import AuthRESTClient
    from ..client.apiserver import AlreadyExists
    from ..kubelet.kubelet import NodeAgentPool
    from ..kubemark.hollow_node import make_hollow_node

    if "." in token:
        # <id>.<secret> form: verify the control plane's identity via the
        # signed discovery document before registering with it
        info = discover_cluster_info(server_url, token)
        server_url = info.get("server", server_url)
    client = AuthRESTClient(server_url, token=token)
    node = make_hollow_node(node_name, cpu=cpu, memory=memory)
    try:
        client.create("nodes", node)
    except AlreadyExists:
        pass  # re-join of a registered node
    # TLS-bootstrap analogue (kubeadm's kubelet-start phase): trade the
    # shared bootstrap token for a per-node identity. The CSR auto-approve
    # + signing controllers issue the credential; the authenticator's
    # signed-CSR index then maps it to system:node:<name> in the
    # system:nodes group, where the node authorizer's decision table
    # applies. If the control plane runs without those controllers, stay
    # on the bootstrap token (degraded but functional).
    try:
        cred = _request_node_credential(client, node_name)
        if cred:
            client._headers["Authorization"] = f"Bearer {cred}"
            logger.info("[join] node %s holds its node identity", node_name)
    except Exception:
        logger.exception("node credential bootstrap failed; keeping token")
    pool = NodeAgentPool(client)
    pool.add_node(node_name, register=False)
    pool.start()
    if handle is not None:
        handle._joined.append(pool)
    logger.info("[join] node %s registered and heartbeating", node_name)
    return pool


def upgrade_plan(server) -> dict:
    """`kubeadm upgrade plan` (cmd/kubeadm/app/cmd/upgrade/plan.go):
    compare the cluster's recorded version (kubeadm-config) with the
    version this binary ships."""
    from .. import __version__ as target

    try:
        cm = server.get("configmaps", "kube-system", "kubeadm-config")
        current = json.loads(cm.data.get("ClusterConfiguration", "{}")).get(
            "kubernetesVersion", "unknown"
        )
    except Exception:
        current = "unknown"
    return {
        "current": current,
        "target": target,
        "upgrade_available": current != target,
    }


def upgrade_apply(server, target: Optional[str] = None) -> dict:
    """`kubeadm upgrade apply` (…/upgrade/apply.go): refuse downgrades and
    migrate the stored cluster configuration to the new version — the
    config-migration half of the reference's apply (component manifests
    don't exist in an in-process control plane). Idempotent."""
    from .. import __version__ as binary_version

    target = target or binary_version
    plan = upgrade_plan(server)
    current = plan["current"]

    def _key(vs: str):
        try:
            return tuple(int(x) for x in vs.lstrip("v").split("-")[0].split("."))
        except ValueError:
            return ()

    if _key(target) < _key(current):
        raise ValueError(
            f"downgrade {current} -> {target} is not supported "
            "(upgrade/apply.go version skew policy)"
        )

    def mutate(cm):
        cfg = json.loads(cm.data.get("ClusterConfiguration", "{}"))
        if cfg.get("kubernetesVersion") == target:
            return None
        cfg["kubernetesVersion"] = target
        cm.data["ClusterConfiguration"] = json.dumps(cfg)
        return cm

    from ..client.apiserver import NotFound

    try:
        server.guaranteed_update(
            "configmaps", "kube-system", "kubeadm-config", mutate
        )
    except NotFound:
        from ..api import objects as v1

        server.create(
            "configmaps",
            v1.ConfigMap(
                metadata=v1.ObjectMeta(
                    name="kubeadm-config", namespace="kube-system"
                ),
                data={
                    "ClusterConfiguration": json.dumps(
                        {"kubernetesVersion": target}
                    )
                },
            ),
        )
    logger.info("[upgrade] cluster %s -> %s", current, target)
    return {"from": current, "to": target}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kubeadm-tpu")
    sub = parser.add_subparsers(dest="verb", required=True)
    p_init = sub.add_parser("init")
    p_init.add_argument("--data-dir", default="./kubeadm-data")
    p_init.add_argument("--port", type=int, default=18080)
    p_init.add_argument("--with-replication", action="store_true")
    p_join = sub.add_parser("join")
    p_join.add_argument("server")
    p_join.add_argument("--token", required=True)
    p_join.add_argument("--node-name", default="node-joined")
    p_standby = sub.add_parser("standby")
    p_standby.add_argument("primary")  # host:port of the replication endpoint
    p_standby.add_argument("--data-dir", default="./kubeadm-standby")
    p_standby.add_argument("--lease-seconds", type=float, default=2.0)
    p_standby.add_argument(
        "--token",
        default="",
        help="cluster admin token the promoted plane authenticates with "
        "(omitting it serves the promoted plane on the insecure port)",
    )
    p_up = sub.add_parser("upgrade")
    p_up.add_argument("phase", choices=["plan", "apply"])
    p_up.add_argument("server")
    p_up.add_argument("--token", required=True)
    p_up.add_argument("--version", default=None)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    if args.verb == "init":
        handle = init_cluster(
            args.data_dir, args.port, replication=args.with_replication
        )
        print(
            "cluster initialized.\n"
            f"  admin conf: {os.path.join(args.data_dir, ADMIN_CONF)}\n"
            "join nodes with:\n"
            f"  kubeadm-tpu join {handle.server_url} --token {handle.bootstrap_token}"
        )
        if handle.replication_address:
            host, rport = handle.replication_address
            print(f"standby control planes: kubeadm-tpu standby {host}:{rport}")
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            handle.stop()
        return 0
    if args.verb == "join":
        pool = join_node(args.server, args.token, args.node_name)
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pool.stop()
        return 0
    if args.verb == "standby":
        host, _, port_s = args.primary.rpartition(":")
        if not host or not port_s.isdigit():
            parser.error(f"standby target must be HOST:PORT, got {args.primary!r}")
        handle = standby_cluster(
            (host, int(port_s)),
            args.data_dir,
            lease_s=args.lease_seconds,
            admin_token=args.token or None,
            insecure=not args.token,
        )
        print(f"standby tailing {args.primary}; promotes on lease expiry")
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            handle.stop()
        return 0
    if args.verb == "upgrade":
        from ..apiserver.client import AuthRESTClient

        client = AuthRESTClient(args.server, token=args.token)
        if args.phase == "plan":
            print(json.dumps(upgrade_plan(client), indent=2))
        else:
            print(json.dumps(upgrade_apply(client, args.version), indent=2))
        return 0
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
