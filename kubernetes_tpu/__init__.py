"""kubernetes_tpu — a TPU-native cluster-orchestration framework.

A ground-up re-design of the reference Kubernetes control plane (≈v1.18) with
the kube-scheduler as the north star: the per-pod scheduling cycle
(prefilter → filter → score → normalize → select; see reference
pkg/scheduler/core/generic_scheduler.go:150) becomes a batched pods×nodes
JAX/XLA data plane over an HBM-resident, delta-updated columnar NodeInfo
snapshot, sharded over a TPU mesh on the node axis.

Layout (mirrors SURVEY.md §7 build plan):
  api/        — object model: Pod/Node/quantities/selectors (apimachinery-lite)
  runtime/    — scheme/watch/store primitives
  client/     — in-memory API server, informers, workqueue, leader election
  scheduler/  — cache, queue, framework (plugin API + host plugins), top loop
  ops/        — device kernels: columnar encoding + filter/score lattice
  parallel/   — mesh construction + node-axis sharded scheduling step
  utils/      — featuregates, metrics, trace, backoff
  perf/       — scheduler_perf-equivalent benchmark harness
"""

__version__ = "0.1.0"
