"""Authentication, authorization, and admission for the API layer.

Reference shape (staging/src/k8s.io/apiserver/pkg/server/config.go:660,
DefaultBuildHandlerChain): requests pass authn → authz before reaching the
REST storage; write requests then run the ADMISSION chain (mutating plugins
first, then validating — apiserver/pkg/admission/chain.go) before
persisting. Here:

  * ``TokenAuthenticator`` — bearer-token table (the reference's
    --token-auth-file static tokens + ServiceAccount token secrets from the
    tokens controller);
  * ``RBACAuthorizer`` — RBAC-lite: rules (verbs × resources × namespaces)
    bound to users/groups, with ``system:masters`` always allowed
    (plugin/pkg/auth/authorizer/rbac);
  * ``AdmissionChain`` — ordered mutating → validating plugins, installed
    as a store admit hook so in-process clients and the HTTP façade pass
    through the same gate;
  * ``QuotaAdmission`` — the first real validating plugin: rejects pod
    creates that would exceed any ResourceQuota hard limit
    (plugin/pkg/admission/resourcequota).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from ..api import objects as v1
from ..api.resources import CPU, MEMORY, cpu_to_millis, to_int_value

logger = logging.getLogger("kubernetes_tpu.apiserver.auth")

ANONYMOUS = "system:anonymous"
MASTERS_GROUP = "system:masters"
ALL = "*"


@dataclass(frozen=True)
class UserInfo:
    name: str
    groups: Tuple[str, ...] = ()


class Forbidden(PermissionError):
    pass


class Unauthorized(PermissionError):
    pass


class AdmissionDenied(ValueError):
    pass


# ---------------------------------------------------------------------------
# authn
# ---------------------------------------------------------------------------


class TokenAuthenticator:
    """Static bearer tokens + ServiceAccount token secrets.

    ``authenticate`` returns a UserInfo, or None for requests with no
    credentials (the caller decides whether anonymous is allowed)."""

    def __init__(self, server=None, allow_anonymous: bool = True):
        self._tokens: Dict[str, UserInfo] = {}
        self._server = server  # for ServiceAccount token secret lookup
        self.allow_anonymous = allow_anonymous
        self._lock = threading.Lock()
        # SA-token index: rebuilt at most every _sa_ttl seconds, so the
        # authn hot path is an O(1) dict hit instead of a full secret list
        # + linear scan per request
        self._sa_index: Dict[str, UserInfo] = {}
        self._sa_built_at = float("-inf")
        self._sa_ttl = 2.0
        self._csr_index: Dict[str, UserInfo] = {}
        self._csr_built_at = float("-inf")

    def add_token(self, token: str, user: str, groups: Sequence[str] = ()) -> None:
        with self._lock:
            self._tokens[token] = UserInfo(user, tuple(groups))

    def _sa_tokens(self) -> Dict[str, UserInfo]:
        """ServiceAccount token index (tokens controller secrets): identity
        system:serviceaccount:<ns>:<name>."""
        import time

        now = time.monotonic()
        with self._lock:
            if now - self._sa_built_at < self._sa_ttl:
                return self._sa_index
        idx: Dict[str, UserInfo] = {}
        secrets, _ = self._server.list("secrets")
        for s in secrets:
            if s.type != "kubernetes.io/service-account-token":
                continue
            tok = s.data.get("token", b"")
            tok = tok.decode() if isinstance(tok, bytes) else str(tok)
            if not tok:
                continue
            sa = s.metadata.annotations.get(
                "kubernetes.io/service-account.name", "default"
            )
            idx[tok] = UserInfo(
                f"system:serviceaccount:{s.metadata.namespace}:{sa}",
                ("system:serviceaccounts",),
            )
        with self._lock:
            self._sa_index = idx
            self._sa_built_at = now
        return idx

    def _csr_tokens(self, force: bool = False) -> Dict[str, UserInfo]:
        """Signed-CSR credential index: the CSR signer issues an HMAC
        credential (controller/certificates.py CSRSigningController); a
        bearer presenting it authenticates as the CSR's username — the
        kubelet client-cert flow with tokens standing in for x509. Node
        usernames (system:node:*) get the system:nodes group, which routes
        them into the node authorizer (apiserver/nodeauth.py)."""
        import time

        now = time.monotonic()
        with self._lock:
            if not force and now - self._csr_built_at < self._sa_ttl:
                return self._csr_index
        try:
            csrs, _ = self._server.list("certificatesigningrequests")
        except Exception:
            # transient store failure: keep serving the stale index rather
            # than caching an empty one (which would 401 every node
            # credential for a TTL)
            logger.exception("rebuilding CSR token index failed; serving stale")
            with self._lock:
                self._csr_built_at = now
                return self._csr_index
        from .admission import NODE_USER_PREFIX, NODES_GROUP

        idx: Dict[str, UserInfo] = {}
        for c in csrs:
            cert = c.status.certificate
            if not cert:
                continue
            groups = tuple(c.spec.groups)
            if c.spec.username.startswith(NODE_USER_PREFIX):
                groups = tuple(sorted(set(groups) | {NODES_GROUP}))
            idx[cert] = UserInfo(c.spec.username, groups)
        with self._lock:
            self._csr_index = idx
            self._csr_built_at = now
        return idx

    def authenticate_token(self, token: str) -> Optional[UserInfo]:
        with self._lock:
            ui = self._tokens.get(token)
        if ui is not None:
            return ui
        if self._server is not None:
            ui = self._sa_tokens().get(token)
            if ui is not None:
                return ui
            ui = self._csr_tokens().get(token)
            if ui is None:
                # a freshly signed credential can be newer than the cached
                # index (a node joining within the TTL of the last rebuild
                # would be 401'd and its informer threads killed) — a miss
                # triggers one immediate rebuild before rejecting
                ui = self._csr_tokens(force=True).get(token)
            return ui
        return None

    def authenticate_header(self, authorization: str) -> Optional[UserInfo]:
        if not authorization:
            return None
        scheme, _, cred = authorization.partition(" ")
        if scheme.lower() != "bearer" or not cred:
            return None
        return self.authenticate_token(cred.strip())


# ---------------------------------------------------------------------------
# authz (RBAC-lite)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Rule:
    verbs: FrozenSet[str]  # get/list/watch/create/update/delete or *
    resources: FrozenSet[str]  # resource names or *
    namespaces: FrozenSet[str] = frozenset({ALL})
    # specific object names (PolicyRule.resourceNames). A name-restricted
    # rule never matches unnamed requests (list/watch/create), matching
    # the reference's RuleAllows
    names: FrozenSet[str] = frozenset({ALL})

    def allows(
        self, verb: str, resource: str, namespace: str, name: str = ""
    ) -> bool:
        return (
            (ALL in self.verbs or verb in self.verbs)
            and (ALL in self.resources or resource in self.resources)
            and (ALL in self.namespaces or namespace in self.namespaces)
            and (ALL in self.names or (bool(name) and name in self.names))
        )


def make_rule(
    verbs: Sequence[str],
    resources: Sequence[str],
    namespaces: Sequence[str] = (ALL,),
    names: Sequence[str] = (ALL,),
) -> Rule:
    return Rule(
        frozenset(verbs),
        frozenset(resources),
        frozenset(namespaces),
        frozenset(names),
    )


# the verbs read-only roles get (rbac bootstrap "view")
READ_VERBS = ("get", "list", "watch")


class RBACAuthorizer:
    """Subject (user or group) → list of rules. ``system:masters`` is the
    reference's superuser group (rbac bootstrap cluster-admin binding).

    Two rule sources: programmatic ``bind`` calls (the bootstrap policy,
    plugin/pkg/auth/authorizer/rbac/bootstrappolicy) and — when built with
    a server — ClusterRole/ClusterRoleBinding API objects, rebuilt into a
    subject index on a short TTL like the SA-token index (the reference's
    RBAC authorizer resolves through informer caches)."""

    def __init__(self, server=None):
        self._subjects: Dict[str, List[Rule]] = {}
        self._lock = threading.Lock()
        self._server = server
        self._obj_index: Dict[str, List[Rule]] = {}
        self._obj_built_at = float("-inf")
        self._obj_ttl = 2.0

    def bind(self, subject: str, rule: Rule) -> None:
        with self._lock:
            self._subjects.setdefault(subject, []).append(rule)

    def _object_rules(self) -> Dict[str, List[Rule]]:
        import time

        now = time.monotonic()
        with self._lock:
            if now - self._obj_built_at < self._obj_ttl:
                return self._obj_index
        idx: Dict[str, List[Rule]] = {}
        try:
            roles = {
                r.metadata.name: r
                for r in self._server.list("clusterroles")[0]
            }
            for b in self._server.list("clusterrolebindings")[0]:
                role = roles.get(b.role_ref.name)
                if role is None:
                    continue
                rules = [
                    make_rule(
                        r.verbs,
                        r.resources,
                        names=r.resource_names or (ALL,),
                    )
                    for r in role.rules
                ]
                for s in b.subjects:
                    if s.kind == "ServiceAccount":
                        name = f"system:serviceaccount:{s.namespace}:{s.name}"
                    else:  # User and Group subjects are both plain keys
                        name = s.name
                    idx.setdefault(name, []).extend(rules)
        except Exception:
            # transient store failure: keep serving the stale index rather
            # than caching an empty one (which would 403 every
            # object-bound subject for a TTL); built_at still advances so
            # a broken store isn't hammered per request
            logger.exception(
                "rebuilding RBAC object index failed; serving stale index"
            )
            with self._lock:
                self._obj_built_at = now
                return self._obj_index
        with self._lock:
            self._obj_index = idx
            self._obj_built_at = now
        return idx

    def authorize(
        self,
        user: Optional[UserInfo],
        verb: str,
        resource: str,
        namespace: str,
        name: str = "",
    ) -> bool:
        if user is None:
            return False
        if MASTERS_GROUP in user.groups:
            return True
        with self._lock:
            rules = list(self._subjects.get(user.name, []))
            for g in user.groups:
                rules.extend(self._subjects.get(g, []))
        if self._server is not None:
            obj = self._object_rules()
            rules.extend(obj.get(user.name, []))
            for g in user.groups:
                rules.extend(obj.get(g, []))
        return any(r.allows(verb, resource, namespace, name) for r in rules)


# ---------------------------------------------------------------------------
# admission
# ---------------------------------------------------------------------------


class AdmissionPlugin:
    """mutate() may modify obj in place; validate() raises AdmissionDenied."""

    name = "plugin"

    def mutate(self, verb: str, resource: str, obj) -> None:  # pragma: no cover
        pass

    def validate(self, verb: str, resource: str, obj) -> None:  # pragma: no cover
        pass


class AdmissionChain:
    """Ordered mutating phase, then validating phase (admission/chain.go:
    mutators run first so validators see final content). Installable as a
    store admit hook (APIServer.admit_hooks)."""

    def __init__(
        self,
        mutating: Sequence[AdmissionPlugin] = (),
        validating: Sequence[AdmissionPlugin] = (),
    ):
        self.mutating = list(mutating)
        self.validating = list(validating)

    def __call__(self, verb: str, resource: str, obj) -> None:
        for p in self.mutating:
            p.mutate(verb, resource, obj)
        for p in self.validating:
            p.validate(verb, resource, obj)


class QuotaAdmission(AdmissionPlugin):
    """Deny pod creates that would exceed any ResourceQuota hard limit in
    the namespace (plugin/pkg/admission/resourcequota). Check-and-reserve:
    usage is recomputed live and racing creates serialize through the
    plugin's own mutex, with an in-flight reservation ledger covering the
    window between a create passing admission and its pod appearing in the
    store — mirroring the reference's transactional quota reservation
    (racing creates cannot both pass a quota with room for one).
    Reservations clear as soon as the pod is visible, or after a short TTL
    when the create failed downstream of admission."""

    name = "ResourceQuota"

    def __init__(self, server, reserve_ttl_s: float = 5.0):
        self.server = server
        self._lock = threading.Lock()
        self._ttl = reserve_ttl_s
        self._reserved: dict = {}  # ns -> {pod_key: (delta, deadline)}

    def validate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        ns = obj.metadata.namespace
        quotas, _ = self.server.list("resourcequotas", namespace=ns)
        if not quotas:
            return
        from ..controller.resourcequota import compute_namespace_usage

        req = v1.compute_pod_resource_request(obj)
        delta = {
            "pods": 1,
            "requests.cpu": int(req.get(CPU, 0)),
            "cpu": int(req.get(CPU, 0)),
            "requests.memory": int(req.get(MEMORY, 0)),
            "memory": int(req.get(MEMORY, 0)),
        }
        from .admission import pod_matches_scopes

        with self._lock:
            # purge BEFORE computing usage: the other order can drop a
            # reservation whose pod landed between the usage read and the
            # purge, leaving it counted nowhere (review r4). This order can
            # only double-count (reservation kept + pod already in usage) —
            # a transient fail-closed, never an over-admission.
            now = time.monotonic()
            res = self._reserved.setdefault(ns, {})
            for key in list(res):
                _d, deadline, _rpod = res[key]
                # the pod landed (usage counts it now) or the create died
                # downstream of admission (TTL): drop the reservation
                if deadline < now or self._pod_exists(key):
                    del res[key]
            usage_by_scopes: dict = {}  # scopes tuple -> usage incl. reserved
            matched_any = False
            for q in quotas:
                scopes = tuple(q.spec.scopes)
                # a scoped quota constrains only matching pods
                if scopes and not pod_matches_scopes(obj, scopes):
                    continue
                matched_any = True
                usage = usage_by_scopes.get(scopes)
                if usage is None:
                    usage = compute_namespace_usage(self.server, ns, scopes)
                    for entry in res.values():
                        d, _deadline, rpod = entry
                        # a reservation counts toward this quota only if
                        # ITS pod matches the quota's scopes too
                        if scopes and not pod_matches_scopes(rpod, scopes):
                            continue
                        for rn, v in d.items():
                            usage[rn] = usage.get(rn, 0) + v
                    usage_by_scopes[scopes] = usage
                for res_name, hard in q.spec.hard.items():
                    # hard limits are k8s quantities ("2", "500m", "4Gi");
                    # usage is millicores/bytes/counts — same-unit parse
                    if "cpu" in res_name:
                        limit = cpu_to_millis(hard)
                    else:
                        limit = to_int_value(hard)
                    want = usage.get(res_name, 0) + delta.get(res_name, 0)
                    if want > limit:
                        raise AdmissionDenied(
                            f"exceeded quota {q.metadata.name}: requested "
                            f"{res_name}={delta.get(res_name, 0)}, used "
                            f"{usage.get(res_name, 0)}, limited {hard}"
                        )
            if matched_any:
                res[obj.metadata.key] = (delta, now + self._ttl, obj)

    def _pod_exists(self, key: str) -> bool:
        try:
            return self.server.exists("pods", key)
        except Exception:
            return False


class NamespaceLifecycleAdmission(AdmissionPlugin):
    """Reject creates in terminating or nonexistent namespaces
    (plugin/pkg/admission/namespace/lifecycle). System namespaces
    (default, kube-system) are implicit."""

    name = "NamespaceLifecycle"

    IMPLICIT = {"default", "kube-system", "kube-public", ""}
    CLUSTER_SCOPED = {"namespaces"}

    def __init__(self, server):
        self.server = server

    def validate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource in self.CLUSTER_SCOPED:
            return
        ns = obj.metadata.namespace
        if ns in self.IMPLICIT:
            return
        try:
            ns_obj = self.server.get("namespaces", "default", ns)
        except KeyError:
            try:
                ns_obj = self.server.get("namespaces", "", ns)
            except KeyError:
                raise AdmissionDenied(f"namespace {ns!r} not found") from None
        if ns_obj.metadata.deletion_timestamp is not None:
            raise AdmissionDenied(
                f"namespace {ns!r} is terminating: no new objects"
            )


class LimitRangerAdmission(AdmissionPlugin):
    """Apply LimitRange defaults and enforce min/max on pod containers
    (plugin/pkg/admission/limitranger): containers without requests get the
    range's defaultRequest; requests outside [min, max] are denied."""

    name = "LimitRanger"

    def __init__(self, server):
        self.server = server

    def _ranges(self, ns: str):
        # fail-CLOSED: an enforcement gate that cannot read its policy must
        # deny, not wave pods through — list errors propagate to the caller
        items, _ = self.server.list("limitranges", namespace=ns)
        return [
            item
            for lr in items
            for item in lr.spec.limits
            if item.type == "Container"
        ]

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        for item in self._ranges(obj.metadata.namespace):
            for c in obj.spec.containers:
                for res_name, q in item.default_request.items():
                    c.requests.setdefault(res_name, q)
                for res_name, q in item.default.items():
                    c.limits.setdefault(res_name, q)

    def validate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        from ..api.resources import cpu_to_millis, to_int_value

        def units(res_name, q):
            return cpu_to_millis(q) if "cpu" in res_name else to_int_value(q)

        for item in self._ranges(obj.metadata.namespace):
            for c in obj.spec.containers:
                for res_name, lo in item.min.items():
                    have = c.requests.get(res_name)
                    # absent request FAILS min (the reference denies when no
                    # value is specified against a min constraint — the
                    # mutating pass already applied any defaultRequest)
                    if have is None or units(res_name, have) < units(
                        res_name, lo
                    ):
                        raise AdmissionDenied(
                            f"minimum {res_name} usage per Container is {lo}"
                        )
                for res_name, hi in item.max.items():
                    # max binds requests AND limits: either exceeding it is
                    # a denial (limitranger checks both value classes)
                    for have in (c.requests.get(res_name), c.limits.get(res_name)):
                        if have is not None and units(res_name, have) > units(
                            res_name, hi
                        ):
                            raise AdmissionDenied(
                                f"maximum {res_name} usage per Container is {hi}"
                            )


class PriorityAdmission(AdmissionPlugin):
    """Resolve pod spec.priority_class_name -> spec.priority at create
    (plugin/pkg/admission/priority/admission.go): named class sets the
    value, a globalDefault class covers unnamed pods, and an unknown class
    name is rejected."""

    name = "Priority"

    def __init__(self, server):
        self.server = server

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        if obj.spec.priority is not None and not obj.spec.priority_class_name:
            return
        classes, _ = self.server.list("priorityclasses")
        if obj.spec.priority_class_name:
            pc = next(
                (
                    c
                    for c in classes
                    if c.metadata.name == obj.spec.priority_class_name
                ),
                None,
            )
            if pc is None:
                raise AdmissionDenied(
                    f"no PriorityClass {obj.spec.priority_class_name!r}"
                )
            obj.spec.priority = pc.value
            if obj.spec.preemption_policy is None:
                obj.spec.preemption_policy = pc.preemption_policy
            elif obj.spec.preemption_policy != pc.preemption_policy:
                # admission.go rejects the mismatch: a pod must not claim a
                # class's priority while discarding its preemption policy
                raise AdmissionDenied(
                    f"pod preemptionPolicy {obj.spec.preemption_policy!r} "
                    f"conflicts with PriorityClass "
                    f"{pc.metadata.name!r} policy {pc.preemption_policy!r}"
                )
            return
        default = next((c for c in classes if c.global_default), None)
        if default is not None and obj.spec.priority is None:
            obj.spec.priority = default.value
            obj.spec.priority_class_name = default.metadata.name
            if obj.spec.preemption_policy is None:
                obj.spec.preemption_policy = default.preemption_policy
            elif obj.spec.preemption_policy != default.preemption_policy:
                # same mismatch rule as the named-class branch: the
                # resolved class's policy binds
                raise AdmissionDenied(
                    f"pod preemptionPolicy {obj.spec.preemption_policy!r} "
                    f"conflicts with default PriorityClass "
                    f"{default.metadata.name!r} policy "
                    f"{default.preemption_policy!r}"
                )


class DefaultStorageClassAdmission(AdmissionPlugin):
    """PVCs created without a class get the cluster default
    (plugin/pkg/admission/storage/storageclass/setdefault): the
    StorageClass annotated storageclass.kubernetes.io/is-default-class."""

    name = "DefaultStorageClass"
    DEFAULT_ANNOTATION = "storageclass.kubernetes.io/is-default-class"

    def __init__(self, server):
        self.server = server

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "persistentvolumeclaims":
            return
        if obj.spec.storage_class_name is not None:
            return  # explicit class (or explicit "" = no dynamic provision)
        for sc in self.server.list("storageclasses")[0]:
            if (
                sc.metadata.annotations.get(self.DEFAULT_ANNOTATION, "").lower()
                == "true"
            ):
                obj.spec.storage_class_name = sc.metadata.name
                return


TAINT_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"


class DefaultTolerationSecondsAdmission(AdmissionPlugin):
    """Every pod tolerates not-ready/unreachable NoExecute taints for a
    bounded window (plugin/pkg/admission/defaulttolerationseconds): node
    failure doesn't instantly evict, but eviction isn't disabled either —
    the nodelifecycle evictor honors tolerationSeconds."""

    name = "DefaultTolerationSeconds"

    def __init__(self, toleration_seconds: int = 300):
        self.toleration_seconds = toleration_seconds

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        for key in (TAINT_NOT_READY, TAINT_UNREACHABLE):
            # Toleration.tolerates covers the wildcard key=""+Exists form:
            # a tolerate-everything pod must NOT get a bounded override
            taint = v1.Taint(key, "", v1.TAINT_NO_EXECUTE)
            if any(t.tolerates(taint) for t in obj.spec.tolerations):
                continue
            obj.spec.tolerations.append(
                v1.Toleration(
                    key=key,
                    operator=v1.TOLERATION_OP_EXISTS,
                    effect=v1.TAINT_NO_EXECUTE,
                    toleration_seconds=self.toleration_seconds,
                )
            )


class ServiceAccountAdmission(AdmissionPlugin):
    """Default pod spec.service_account to "default" (the mutating half of
    plugin/pkg/admission/serviceaccount, minus volume injection)."""

    name = "ServiceAccount"

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        if hasattr(obj.spec, "service_account_name") and not obj.spec.service_account_name:
            obj.spec.service_account_name = "default"
