"""REST API server façade + REST client.

Reference: the kube-apiserver HTTP layer (staging/src/k8s.io/apiserver,
composed at cmd/kube-apiserver/app/server.go:169) reduced to its
scheduling-relevant contract: CRUD + list + watch streams over the
versioned store, `/api/v1` paths, JSON wire format.
"""

from .rest import APIServerHTTP, serve  # noqa: F401
from .client import RESTClient  # noqa: F401
from .cacher import Cacher  # noqa: F401
