"""API request audit logging.

Reference: staging/src/k8s.io/apiserver/pkg/audit + the WithAudit filter
(server/config.go:668) — every API request emits a structured event with
the authenticated user, verb, resource, and response code. This build
writes one JSON line per completed request (the ResponseComplete stage;
the reference's RequestReceived stage adds little in-process) through an
async writer so auditing never blocks request handling, with an in-memory
ring for tests/debug endpoints.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Optional


class AuditLogger:
    def __init__(
        self,
        path: Optional[str] = None,
        ring_size: int = 1000,
        max_queue: int = 10000,
    ):
        self.path = path
        self.ring = collections.deque(maxlen=ring_size)
        # bounded like the ring: if the writer can't keep up (or died and
        # is backing off), oldest events drop instead of leaking memory
        self._q: "collections.deque[dict]" = collections.deque(maxlen=max_queue)
        self._cond = threading.Condition()
        self._stopped = False
        self._writer: Optional[threading.Thread] = None
        self._write_failures = 0
        self._disabled_until = 0.0  # writer crashed: back off, then retry

    def log(
        self,
        user: Optional[str],
        groups,
        verb: str,
        resource: str,
        namespace: str,
        name: str,
        code: int,
    ) -> None:
        ev = {
            "stage": "ResponseComplete",
            "timestamp": time.time(),
            "user": user or "system:anonymous",
            "groups": list(groups or ()),
            "verb": verb,
            "resource": resource,
            "namespace": namespace,
            "name": name,
            "code": code,
        }
        with self._cond:
            self.ring.append(ev)
            if self.path is not None and not self._stopped:
                self._q.append(ev)
                if self._writer is None and time.time() >= self._disabled_until:
                    self._writer = threading.Thread(
                        target=self._write_loop, daemon=True, name="audit-writer"
                    )
                    self._writer.start()
                self._cond.notify()

    def _write_loop(self) -> None:
        try:
            with open(self.path, "a", encoding="utf-8") as f:
                while True:
                    with self._cond:
                        while not self._q and not self._stopped:
                            self._cond.wait(timeout=1.0)
                        if not self._q:
                            return
                        batch = list(self._q)
                        self._q.clear()
                    for ev in batch:
                        f.write(json.dumps(ev) + "\n")
                    f.flush()
        except OSError:
            # unwritable path / disk error: clear the thread handle so a
            # later log() can restart the writer (after a backoff — a
            # permanently broken path must not spawn a thread per event),
            # rather than silently dropping audit forever
            with self._cond:
                self._write_failures += 1
                self._disabled_until = time.time() + min(
                    30.0 * self._write_failures, 300.0
                )
        finally:
            with self._cond:
                self._writer = None

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
