"""Watch cache: one store watch per kind, fanned out to N client watchers.

Reference: staging/src/k8s.io/apiserver/pkg/storage/cacher/cacher.go — the
layer that lets one apiserver serve production fleets of informers without
every watch (and every reconnect) touching the storage backend. Per kind:

  * the CURRENT object state (a map the cache keeps in lockstep with the
    store by consuming exactly ONE store watch), so lists with
    resourceVersion=0 / limit / continue are served from memory;
  * a resourceVersion-ordered ring buffer of recent events (the
    ``watchcache`` event window, watch_cache.go's cyclic buffer): a client
    reconnecting at an rv still inside the window replays the missed
    events from the buffer — no store touch, no re-list; a client older
    than the window gets a proper 410 Expired;
  * periodic BOOKMARK events (bookmark.go) that advance idle clients'
    resume positions so the window stays usable for them;
  * per-client bounded queues with slow-watcher termination
    (cacher.go's terminateAllWatchers discipline, per watcher): one stuck
    reader must never stall the dispatch loop for everyone else.

The cache is read-path only. Writes go straight to the store; the cache
learns of them through its own watch like any other watcher, which is why
a degraded (read-only) or briefly unreachable store never interrupts
cache-served reads and watches — the window simply stops growing.

``Cacher`` is interface-compatible with the store for the read surface
(list/watch/get) and delegates everything else, so a SharedInformer — or a
whole hollow-node fleet — can take a Cacher wherever it took an APIServer.
"""

from __future__ import annotations

import base64
import copy
import json
import logging
import queue
import threading
import time
from collections import OrderedDict, deque
from types import SimpleNamespace
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..client.apiserver import Expired
from ..runtime.watch import ADDED, BOOKMARK, DELETED, Event, Watcher
from ..testing.lockgraph import named_lock, track_attrs
from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.apiserver.cacher")

# event-window length per kind: how many events a disconnected client may
# miss and still resume without a re-list
DEFAULT_WINDOW = 8192
DEFAULT_BOOKMARK_PERIOD_S = 2.0
# continuation snapshots: at most this many in-flight paginated lists per
# kind, each good for CONTINUE_TTL_S (an expired token 410s, like the
# reference's expired continue tokens)
CONTINUE_MAX = 128
CONTINUE_TTL_S = 300.0

GAUGE_SIZE = "watch_cache_size"                       # {kind}
GAUGE_FANOUT = "watch_cache_fanout_clients"           # {kind}
GAUGE_WINDOW_FLOOR = "watch_cache_window_floor_rv"    # {kind}
COUNTER_REPLAYS = "watch_cache_replays_total"         # {kind}
COUNTER_EXPIRED = "watch_cache_expired_total"         # {kind}
COUNTER_EVENTS = "watch_cache_events_total"           # {kind}
COUNTER_BOOKMARKS = "watch_cache_bookmarks_total"     # {kind}
COUNTER_SLOW_EVICTED = "watch_cache_slow_watchers_evicted_total"  # {kind}
COUNTER_RESYNCS = "watch_cache_resyncs_total"         # {kind}
COUNTER_LIST_PAGES = "watch_cache_list_pages_total"   # {kind}
COUNTER_DISPATCH_ERRORS = "watch_cache_dispatch_errors_total"  # {kind}


def bookmark_object(kind: str, rv: int) -> Any:
    """The rv-only object a BOOKMARK event carries. Duck-typed with the
    fields naive watch consumers touch before they branch on event type
    (metadata.key/labels/owner_references, spec.node_name), so a consumer
    that merely ignores unknown types can't crash on the carrier."""
    return SimpleNamespace(
        metadata=SimpleNamespace(
            resource_version=rv,
            namespace=None,
            name="",
            key="",
            uid="",
            labels={},
            owner_references=(),
        ),
        spec=SimpleNamespace(node_name=""),
    )


class CacheWatcher(Watcher):
    """One client's view of a KindCache fan-out.

    Unlike the raw store Watcher (unbounded-ish, blocking push), the
    cache watcher is BOUNDED and the dispatch loop never blocks on it: a
    queue that fills — a reader not keeping up with the event rate —
    terminates the watcher instead (the client reconnects at its last rv
    and replays from the window; cacher.go does the same). min_rv filters
    replay duplicates for clients resuming at a future rv."""

    def __init__(self, min_rv: int = 0, maxsize: int = 0):
        super().__init__(maxsize=maxsize or (DEFAULT_WINDOW + 1024))
        self.min_rv = min_rv
        self.replay_count = 0  # events queued at watch() time (REST uses
        # this to know when the init phase — and its APF seat — is over)
        self.terminated_slow = False

    def push_nonblock(self, ev: Event) -> bool:
        """Fan-out push: False (and self-termination) when the queue is
        full. Never blocks the dispatch thread."""
        if self._stopped.is_set():
            return False
        if (
            ev.type != BOOKMARK
            and ev.resource_version
            and ev.resource_version <= self.min_rv
        ):
            # already seen by this client (future-rv resume). Bookmarks
            # bypass the filter: they carry no state and an idle client
            # AT its resume rv is exactly who needs the heartbeat
            return True
        try:
            self._q.put_nowait(ev)
            return True
        except queue.Full:
            self.terminated_slow = True
            self.stop()
            return False

    # stop() and __iter__ need no overrides anymore: the non-blocking
    # sentinel put and sentinel-free termination this class pioneered in
    # PR 6 now live in the base Watcher (runtime/watch.py), enforced
    # tree-wide by graftlint's blocking-call pass.


class _Continuation:
    """Held remainder of a paginated list: the snapshot keeps serving at
    its original rv even as the live cache (and the event window — a
    "compaction" of old events) moves on."""

    __slots__ = ("rv", "items", "created")

    def __init__(self, rv: int, items: List[Any]):
        self.rv = rv
        self.items = items
        self.created = time.monotonic()


class KindCache:
    """Current state + event window for one kind, fed by ONE store watch."""

    def __init__(
        self,
        store,
        kind: str,
        window: int = DEFAULT_WINDOW,
        watcher_queue_size: int = 0,
    ):
        self.store = store
        self.kind = kind
        self.window = window
        self._watcher_queue_size = watcher_queue_size
        # per-kind locks share ONE watchdog node ("cacher.kind"): the
        # order contract is per-class, and any path ordering a kind lock
        # against the store/cache/device locks records the same edge
        self._lock = threading.Condition(named_lock("cacher.kind"))
        self._objects: Dict[str, Any] = {}
        self._ring: deque = deque()
        # window floor: the MINIMUM from_rv a reconnecting client may
        # resume at. Starts at the initial list rv (events before the
        # cache existed are unprovable); each evicted event raises it to
        # evicted_rv + 1 — i.e. a client must still be positioned at or
        # after the oldest BUFFERED event. Deliberately ONE event
        # stricter than the raw store's `from_version < evicted` check:
        # a client at exactly the last-evicted rv is at the window edge
        # and about to fall out anyway — 410 it now (the PR-6 acceptance
        # contract: reconnect at the oldest buffered rv replays,
        # reconnect one before it expires)
        self._floor = 0
        self.rv = 0
        self._watchers: List[CacheWatcher] = []
        self._continuations: "OrderedDict[str, _Continuation]" = OrderedDict()
        self._cont_seq = 0
        self._stop = threading.Event()
        self._store_watcher = None
        self._ready = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"watchcache-{kind}", daemon=True
        )
        self._thread.start()
        self._ready.wait(10.0)

    # -- store-facing side ---------------------------------------------------

    def _list_and_seed(self) -> int:
        # the seed list blocks the dispatch thread by design: the cache
        # serves nothing until it exists, and _ready gates clients
        objs, rv = self.store.list(self.kind)  # graftlint: allow-blocking(seed list gates readiness; cache serves nothing before it)
        with self._lock:
            self._objects = {o.metadata.key: o for o in objs}
            self.rv = max(self.rv, rv)
            if not self._floor:
                self._floor = rv
            metrics.set_gauge(GAUGE_SIZE, len(self._objects), {"kind": self.kind})
            self._lock.notify_all()
        return rv

    def _run(self) -> None:
        """The ONE store watch per kind. A dying stream (store restart,
        history eviction under extreme lag) resyncs: re-list, reset the
        window, and terminate connected clients — they reconnect at
        their last rv, land outside the post-gap floor, and re-list
        (the reference's terminateAllWatchers on cache error).

        The loop survives ANY exception (a failed list mid-resync, a
        malformed event in _apply): log + count + backoff + resync. A
        silently dead dispatch thread would be the worst failure mode —
        the cache would keep answering from frozen state while the
        bookmark ticker kept telling every informer the stream is
        healthy."""
        backoff = 0.05
        seeded = False
        need_resync = False
        rv = 0
        while not self._stop.is_set():
            try:
                if not seeded:
                    rv = self._list_and_seed()
                    seeded = True
                    self._ready.set()
                elif need_resync:
                    metrics.inc(COUNTER_RESYNCS, {"kind": self.kind})
                    rv = self._resync()
                need_resync = True  # every path back here re-syncs
                try:
                    self._store_watcher = self.store.watch(  # graftlint: allow-blocking(re-arming the ONE upstream watch IS this thread's job)
                        self.kind, from_version=rv
                    )
                except Expired:
                    continue
                for ev in self._store_watcher:
                    if self._stop.is_set():
                        return
                    self._apply(ev)
                    rv = max(rv, ev.resource_version)
                    backoff = 0.05
            except Exception:
                if self._stop.is_set():
                    return
                logger.exception(
                    "watch cache for %s: dispatch error; resyncing",
                    self.kind,
                )
                metrics.inc(COUNTER_DISPATCH_ERRORS, {"kind": self.kind})
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 5.0)

    def _apply(self, ev: Event) -> None:
        if ev.type == BOOKMARK:
            # the upstream may itself be a cacher fan-out (a stateless
            # frontend watching the primary over REST): its bookmarks are
            # rv-only progress notifies, NOT state — storing the carrier
            # object would serve a ghost in every list. Advance the rv
            # (freshness waits see the progress) and drop the event; this
            # cache's own bookmark ticker keeps its clients advancing.
            with self._lock:
                self.rv = max(self.rv, ev.resource_version)
                self._lock.notify_all()
            return
        key = ev.object.metadata.key
        ev.ts = time.monotonic()
        with self._lock:
            if ev.type == DELETED:
                self._objects.pop(key, None)
            else:
                self._objects[key] = ev.object
            self._ring.append(ev)
            if len(self._ring) > self.window:
                evicted = self._ring.popleft()
                self._floor = max(self._floor, evicted.resource_version + 1)
                metrics.set_gauge(
                    GAUGE_WINDOW_FLOOR, self._floor, {"kind": self.kind}
                )
            self.rv = max(self.rv, ev.resource_version)
            metrics.inc(COUNTER_EVENTS, {"kind": self.kind})
            metrics.set_gauge(GAUGE_SIZE, len(self._objects), {"kind": self.kind})
            self._fanout(ev)
            self._lock.notify_all()

    def _resync(self) -> int:
        """Re-list and reset the window after the cache's own store
        stream died. The event gap cannot be reconstructed faithfully
        (synthetic diffs would share one rv — a client flapping mid-batch
        could resume past the undelivered remainder and desync forever),
        so this does what the reference does: floor jumps to the list rv
        and every connected watcher is TERMINATED. Clients reconnect at
        their pre-gap rv, get a 410, and re-list — a visible, bounded
        cost instead of a silent inconsistency."""
        objs, rv = self.store.list(self.kind)  # graftlint: allow-blocking(resync re-list: the cache is stale until it completes anyway)
        with self._lock:
            self._objects = {o.metadata.key: o for o in objs}
            self.rv = max(self.rv, rv)
            self._ring.clear()
            self._floor = max(self._floor, rv)
            metrics.set_gauge(GAUGE_WINDOW_FLOOR, self._floor, {"kind": self.kind})
            metrics.set_gauge(GAUGE_SIZE, len(self._objects), {"kind": self.kind})
            for w in self._watchers:
                w.stop()
            self._watchers.clear()
            metrics.set_gauge(GAUGE_FANOUT, 0, {"kind": self.kind})
            self._lock.notify_all()
        return rv

    def _fanout(self, ev: Event) -> None:
        """Push to every live client queue; drop the dead and terminate
        the stuck. Caller holds the lock."""
        dead: List[CacheWatcher] = []
        for w in self._watchers:
            if w.stopped or not w.push_nonblock(ev):
                dead.append(w)
        if dead:
            for w in dead:
                if w.terminated_slow:
                    metrics.inc(COUNTER_SLOW_EVICTED, {"kind": self.kind})
                try:
                    self._watchers.remove(w)
                except ValueError:
                    pass
            metrics.set_gauge(
                GAUGE_FANOUT, len(self._watchers), {"kind": self.kind}
            )

    # -- client-facing side --------------------------------------------------

    def watch(
        self, from_version: int = 0, queue_size: int = 0
    ) -> CacheWatcher:
        """A fan-out watcher, with RV-windowed replay.

        from_version=0: the reference's rv="0" watch — the CURRENT cached
        state is delivered first as synthetic ADDED events (key order),
        then live events follow; a connect racing the writes still sees
        every object exactly once.
        from_version >= window floor: buffered events with rv >
        from_version replay from the ring (no store touch, no re-list).
        from_version < floor: Expired (410) — the client must re-list."""
        with self._lock:
            if from_version and from_version < self._floor:
                metrics.inc(COUNTER_EXPIRED, {"kind": self.kind})
                raise Expired(
                    f"{self.kind} resourceVersion {from_version} is outside "
                    f"the watch-cache window (floor rv {self._floor})"
                )
            if from_version:
                replay = [
                    ev
                    for ev in self._ring
                    if ev.resource_version > from_version
                ]
                if replay:
                    metrics.inc(COUNTER_REPLAYS, {"kind": self.kind})
            else:
                now = time.monotonic()
                replay = [
                    Event(ADDED, obj, obj.metadata.resource_version, ts=now)
                    for _key, obj in sorted(self._objects.items())
                ]
                if self.rv:
                    # close the initial state with a bookmark at the CACHE
                    # rv: surviving objects' rvs can lag it (deletions),
                    # and a client that flapped before the first periodic
                    # bookmark would otherwise resume below the state it
                    # already saw and replay ghost events from the ring
                    replay.append(
                        Event(
                            BOOKMARK,
                            bookmark_object(self.kind, self.rv),
                            self.rv,
                            ts=now,
                        )
                    )
            # the queue must FIT the initial replay: the slow-watcher
            # bound protects the live fan-out, but self-terminating
            # inside one's own replay would silently truncate initial
            # state (e.g. an rv=0 watch of a 10k-object kind). The
            # configured size bounds the LIVE backlog on top of it.
            w = CacheWatcher(
                min_rv=from_version,
                maxsize=len(replay)
                + (
                    queue_size
                    or self._watcher_queue_size
                    or (DEFAULT_WINDOW + 1024)
                ),
            )
            for ev in replay:
                w.push_nonblock(ev)
            w.replay_count = len(replay)
            self._watchers.append(w)
            metrics.set_gauge(
                GAUGE_FANOUT, len(self._watchers), {"kind": self.kind}
            )
            return w

    def bookmark(self) -> None:
        """Push one BOOKMARK carrying the cache's current rv to every
        client (bookmark.go's periodic progress notify): idle clients'
        resume positions advance past window evictions."""
        with self._lock:
            if not self._watchers:
                return
            ev = Event(
                BOOKMARK,
                bookmark_object(self.kind, self.rv),
                self.rv,
                ts=time.monotonic(),
            )
            metrics.inc(
                COUNTER_BOOKMARKS, {"kind": self.kind}, by=len(self._watchers)
            )
            self._fanout(ev)

    def wait_until_fresh(self, rv: int, timeout: float = 5.0) -> bool:
        """Block until the cache has seen rv (waitUntilFreshAndList): a
        consistent read served from memory instead of the store."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self.rv < rv:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    return self.rv >= rv
                self._lock.wait(remaining)
        return True

    def list_snapshot(
        self,
        namespace: Optional[str] = None,
        pred: Optional[Callable[[Any], bool]] = None,
    ) -> Tuple[List[Any], int]:
        """Matching objects (cache references — treat as read-only),
        key-sorted, plus the single rv the whole list is consistent at."""
        with self._lock:
            objs = [
                o
                for _k, o in sorted(self._objects.items())
                if (namespace is None or o.metadata.namespace == namespace)
                and (pred is None or pred(o))
            ]
            return objs, self.rv

    def list_page(
        self,
        namespace: Optional[str] = None,
        pred: Optional[Callable[[Any], bool]] = None,
        limit: int = 0,
        continue_token: Optional[str] = None,
    ) -> Tuple[List[Any], int, Optional[str]]:
        """(items, rv, next_continue). Pagination is consistent at a
        single rv: the first page snapshots the matching set; later pages
        serve the HELD snapshot, so object churn — and event-window
        compaction — between pages never tears the list. An unknown or
        expired token raises Expired (the client restarts the list),
        matching the reference's expired-continue contract."""
        with self._lock:
            self._expire_continuations()
            if continue_token:
                cont = self._continuations.pop(continue_token, None)
                if cont is None:
                    metrics.inc(COUNTER_EXPIRED, {"kind": self.kind})
                    raise Expired(
                        f"{self.kind} continue token is expired or unknown"
                    )
                items, rv, rest = (
                    cont.items[:limit] if limit else cont.items,
                    cont.rv,
                    cont.items[limit:] if limit else [],
                )
            else:
                objs, rv = self.list_snapshot(namespace, pred)
                items = objs[:limit] if limit else objs
                rest = objs[limit:] if limit else []
            metrics.inc(COUNTER_LIST_PAGES, {"kind": self.kind})
            if not rest:
                return items, rv, None
            self._cont_seq += 1
            token = base64.urlsafe_b64encode(
                json.dumps({"rv": rv, "c": self._cont_seq}).encode()
            ).decode()
            self._continuations[token] = _Continuation(rv, rest)
            while len(self._continuations) > CONTINUE_MAX:
                self._continuations.popitem(last=False)
            return items, rv, token

    def _expire_continuations(self) -> None:
        now = time.monotonic()
        stale = [
            t
            for t, c in self._continuations.items()
            if now - c.created > CONTINUE_TTL_S
        ]
        for t in stale:
            self._continuations.pop(t, None)

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._objects.get(key)

    @property
    def floor(self) -> int:
        with self._lock:
            return self._floor

    @property
    def current_rv(self) -> int:
        """The cache's high-water rv, read under the kind lock (the bare
        `.rv` attribute is for lock-holding internals; the guarded-by
        contract keeps outside readers off it)."""
        with self._lock:
            return self.rv

    def stats_snapshot(self) -> dict:
        """One-lock snapshot of the per-kind observability counters —
        Cacher.stats() used to read `_objects`/`_ring` bare, exactly the
        unguarded minority access the lockset sanitizer now rejects.
        The fan-out prune/count folds into the SAME lock hold so the
        row's size/rv/fanout values all coexist at one instant."""
        with self._lock:
            self._watchers = [w for w in self._watchers if not w.stopped]
            metrics.set_gauge(
                GAUGE_FANOUT, len(self._watchers), {"kind": self.kind}
            )
            return {
                "size": len(self._objects),
                "rv": self.rv,
                "window_floor": self._floor,
                "window_used": len(self._ring),
                "fanout_clients": len(self._watchers),
            }

    def fanout_clients(self) -> int:
        return self.stats_snapshot()["fanout_clients"]

    def stop(self) -> None:
        self._stop.set()
        sw = self._store_watcher
        if sw is not None:
            sw.stop()
        with self._lock:
            for w in self._watchers:
                w.stop()
            self._watchers.clear()
            self._lock.notify_all()


class Cacher:
    """Per-kind KindCaches behind one store, plus the bookmark ticker.

    Read-surface compatible with APIServer (list/watch — the two calls a
    SharedInformer makes) and attribute-delegating for everything else,
    so read-heavy clients can be pointed at the cache wholesale."""

    def __init__(
        self,
        store,
        window: int = DEFAULT_WINDOW,
        bookmark_period_s: float = DEFAULT_BOOKMARK_PERIOD_S,
        watcher_queue_size: int = 0,
        freshness_timeout_s: float = 5.0,
    ):
        self._store = store
        self.window = window
        self.bookmark_period_s = bookmark_period_s
        # how long a consistent list waits for the cache to catch the
        # demanded rv before 504ing (follower frontends: the commit-
        # index wait) — configurable so lagging replicas fail fast
        # where the deployment wants them to
        self.freshness_timeout_s = freshness_timeout_s
        self._watcher_queue_size = watcher_queue_size
        self._caches: Dict[str, KindCache] = {}
        # named for the lock-order watchdog + lockset sanitizer
        self._lock = named_lock("cacher.top")
        self._stop = threading.Event()
        self._bookmark_thread = threading.Thread(
            target=self._bookmark_loop, name="watchcache-bookmarks", daemon=True
        )
        self._bookmark_thread.start()

    @property
    def store(self):
        return self._store

    def __getattr__(self, name: str):
        # write path / typed helpers pass straight through to the store
        return getattr(self._store, name)

    def cache_for(self, kind: str) -> KindCache:
        with self._lock:
            kc = self._caches.get(kind)
            if kc is None:
                kc = KindCache(
                    self._store,
                    kind,
                    window=self.window,
                    watcher_queue_size=self._watcher_queue_size,
                )
                self._caches[kind] = kc
            return kc

    def has_cache(self, kind: str) -> bool:
        with self._lock:
            return kind in self._caches

    # -- read surface --------------------------------------------------------

    def watch(self, kind: str, from_version: int = 0) -> CacheWatcher:
        return self.cache_for(kind).watch(from_version)

    def list(
        self, kind: str, namespace: Optional[str] = None
    ) -> Tuple[List[Any], int]:
        """Store-compatible list FROM CACHE (deep copies: callers mutate
        informer-cached objects). rv is the cache's own high-water mark —
        pair it with watch() on the same Cacher and no event is missed."""
        objs, rv = self.cache_for(kind).list_snapshot(namespace)
        return [copy.deepcopy(o) for o in objs], rv

    def list_page(
        self,
        kind: str,
        namespace: Optional[str] = None,
        pred: Optional[Callable[[Any], bool]] = None,
        limit: int = 0,
        continue_token: Optional[str] = None,
        fresh_rv: Optional[int] = None,
    ) -> Tuple[List[Any], int, Optional[str]]:
        kc = self.cache_for(kind)
        if fresh_rv and not kc.wait_until_fresh(
            fresh_rv, timeout=self.freshness_timeout_s
        ):
            # never serve stale data labeled consistent: the reference's
            # waitUntilFreshAndList times out ("Too large resource
            # version") instead — callers surface it as a retryable 504
            raise TimeoutError(
                f"{kind} watch cache not fresh: have rv "
                f"{kc.current_rv}, need {fresh_rv}"
            )
        return kc.list_page(
            namespace=namespace,
            pred=pred,
            limit=limit,
            continue_token=continue_token,
        )

    def current_rv(self, kind: str) -> int:
        return self.cache_for(kind).current_rv

    # -- bookmarks -----------------------------------------------------------

    def _bookmark_loop(self) -> None:
        while not self._stop.wait(self.bookmark_period_s):
            with self._lock:
                caches = list(self._caches.values())
            for kc in caches:
                try:
                    kc.bookmark()
                except Exception:  # never kill the ticker
                    pass

    # -- lifecycle / observability ------------------------------------------

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            caches = list(self._caches.values())
            self._caches.clear()
        for kc in caches:
            kc.stop()

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            caches = dict(self._caches)
        return {kind: kc.stats_snapshot() for kind, kc in caches.items()}


# lockset sanitizer (testing/lockgraph.py Eraser mode): per-kind cache
# state is written by the ONE dispatch thread and read by every client
# list/watch/stats path — all under `cacher.kind`; the Cacher's kind map
# under `cacher.top`. Chaos readpath storms assert the locksets never
# go empty.
track_attrs(
    KindCache,
    "_objects",
    "_ring",
    "_floor",
    "rv",
    "_watchers",
    "_continuations",
    "_cont_seq",
)
track_attrs(Cacher, "_caches")


def readpath_health_lines() -> List[str]:
    """watch-cache + flow-control read-path state for the SIGUSR2 dump:
    cache sizes, fan-out widths, replay/expiry counters, and APF seat
    occupancy — a read storm is diagnosable from one signal. Empty when
    no cache has served anything yet."""
    lines: List[str] = []
    for snap in (
        metrics.snapshot_gauges("watch_cache_"),
        metrics.snapshot_counters("watch_cache_"),
        metrics.snapshot_gauges("apiserver_flowcontrol_seats"),
        metrics.snapshot_gauges("apiserver_watch_streams"),
        # the whole informer_ family (bookmarks, relists, resumes): a
        # per-counter list here is exactly the drift the metrics lint
        # exists to catch — resumes was missing until it did
        metrics.snapshot_counters("informer_"),
    ):
        for name, labels, value in snap:
            lines.append(metrics.format_series_line(name, labels, value))
    return lines
