"""HTTP REST façade over the in-process versioned store.

Paths follow the core-group conventions the reference serves
(staging/src/k8s.io/apiserver; handler chain config.go:660 — here reduced
to panic recovery + optional admit hooks):

  GET    /healthz | /readyz | /livez
  GET    /api/v1/{resource}                     (cluster list)
  (authn/authz: optional bearer-token authenticator + RBAC-lite authorizer
  run before every resource verb — apiserver/auth.py; admission runs inside
  the store's admit hooks so HTTP and in-process clients share the gate)
  GET    /api/v1/{resource}?watch=1&resourceVersion=N   (watch stream)
  GET    /api/v1/namespaces/{ns}/{resource}
  GET    /api/v1/namespaces/{ns}/{resource}/{name}
  POST   /api/v1/namespaces/{ns}/{resource} | /api/v1/{resource}
  PUT    /api/v1/namespaces/{ns}/{resource}/{name}
  DELETE /api/v1/namespaces/{ns}/{resource}/{name}
  POST   /api/v1/namespaces/{ns}/pods/{name}/binding     (bind subresource)

Watch responses stream newline-delimited JSON events
({"type": "ADDED"|"MODIFIED"|"DELETED", "object": {...}}), the same wire
shape client-go's Reflector consumes.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..api import serialization as codec
from ..api.objects import Binding
from ..client.apiserver import (
    AlreadyExists,
    APIServer,
    Conflict,
    Expired,
    LeaderFenced,
    NotFound,
    NotPrimary,
)
from ..runtime.consensus import (
    DegradedWrites,
    DiskFailed,
    DiskPressure,
    QuorumLost,
)
from ..api.validation import ValidationError
from .auth import AdmissionDenied

_WATCH_POLL_S = 0.5


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kube-apiserver-tpu"
    # TCP_NODELAY on every accepted socket: response header/body go out
    # as separate small writes, and with Nagle on the second stalls
    # behind the client's delayed ACK (~40 ms per request — measured as
    # the dominant pooled-bind cost before the serving-tier work)
    disable_nagle_algorithm = True

    def log_message(self, *args):
        pass

    def send_response(self, code, message=None):
        self._last_code = code  # recorded for the audit event
        super().send_response(code, message)

    # -- helpers -------------------------------------------------------------

    @property
    def store(self) -> APIServer:
        return self.server.store

    def _json(self, code: int, payload, extra_headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        for h, v in (extra_headers or {}).items():
            self.send_header(h, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _wants_proto(self) -> bool:
        from ..api.protocodec import CONTENT_TYPE

        return CONTENT_TYPE in (self.headers.get("Accept") or "")

    def _respond_obj(self, code: int, obj) -> None:
        """Single-object response with content negotiation: the binary
        envelope when the client asked for application/vnd.kubernetes.
        protobuf (reference protobuf.go serializer), JSON otherwise.
        Custom resources are JSON-only (as in the reference: protobuf is
        unsupported for CRDs)."""
        from ..api import objects as v1api
        from ..api import protocodec

        if self._wants_proto() and not isinstance(obj, v1api.Unstructured):
            body = protocodec.encode_obj(obj)
            self.send_response(code)
            self.send_header("Content-Type", protocodec.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self._json(code, codec.encode(obj))

    def _status_error(
        self,
        code: int,
        reason: str,
        message: str,
        retry_after_s: Optional[float] = None,
    ) -> None:
        # retry_after_s -> Retry-After header: a degraded read-only store
        # (503) tells well-behaved clients when to come back (client.py's
        # RESTClient honors it)
        self._json(
            code,
            {
                "kind": "Status",
                "apiVersion": "v1",
                "status": "Failure",
                "reason": reason,
                "message": message,
                "code": code,
            },
            extra_headers=(
                {"Retry-After": str(max(1, round(retry_after_s)))}
                if retry_after_s is not None
                else None
            ),
        )

    def _degraded_error(self, e: DegradedWrites) -> None:
        """Degraded-store write rejection: 503 + Retry-After. The reason
        distinguishes the two retry contracts: "Degraded" (the gate
        refused BEFORE applying anything — safe to replay verbatim) vs
        "WriteQuorumLost" (THIS write applied locally but missed quorum;
        its outcome is unknown — a blind replay of a create would 409
        AlreadyExists against its own first attempt once followers catch
        up, so the client must surface it instead of auto-retrying).
        Disk states get their own reasons so clients can tell a replica
        that will NEVER write again ("DiskFailed": fail-stopped sink,
        recovery is leader failover) from transient volume pressure
        ("DiskPressure": lifts when space frees). Reads and watches keep
        serving — only mutations land here."""
        if isinstance(e, DiskFailed):
            reason = "DiskFailed"
        elif isinstance(e, DiskPressure):
            reason = "DiskPressure"
        elif isinstance(e, QuorumLost):
            reason = "WriteQuorumLost"
        else:
            reason = "Degraded"
        self._status_error(
            503,
            reason,
            str(e),
            retry_after_s=getattr(e, "retry_after_s", 1.0),
        )

    def _parse(self) -> Tuple[Optional[str], Optional[str], Optional[str], dict]:
        """(resource, namespace, name, query) or (None, ...) on bad path.

        Serves the core group (/api/v1/...) and named groups
        (/apis/{group}/{version}/... — the apiextensions/aggregator path;
        group routing is decided by _serve_group before this is used)."""
        u = urlparse(self.path)
        parts = [p for p in u.path.split("/") if p]
        query = {k: v[-1] for k, v in parse_qs(u.query).items()}
        if len(parts) >= 2 and parts[0] == "api" and parts[1] == "v1":
            rest = parts[2:]
        elif len(parts) >= 3 and parts[0] == "apis":
            rest = parts[3:]  # /apis/{group}/{version}/...
        else:
            return None, None, None, query
        if not rest:
            return None, None, None, query
        if rest[0] == "namespaces" and len(rest) >= 3:
            ns = rest[1]
            resource = rest[2]
            name = rest[3] if len(rest) > 3 else None
            sub = rest[4] if len(rest) > 4 else None
            return resource, ns, name if not sub else f"{name}/{sub}", query
        resource = rest[0]
        name = rest[1] if len(rest) > 1 else None
        return resource, None, name, query

    def _group_of_path(self) -> Optional[str]:
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if len(parts) >= 2 and parts[0] == "apis":
            return parts[1]
        return None

    def _version_of_path(self) -> Optional[str]:
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if len(parts) >= 3 and parts[0] == "apis":
            return parts[2]
        return None

    def _cr_write_gate(self, resource: str, body: dict) -> None:
        """Custom-resource write validation (apiextensions): when the
        resource is CRD-served, enforce per-version serving + the
        version's openAPIV3Schema, and rewrite the body to the storage
        apiVersion (conversion strategy None). No-op for built-ins."""
        if resource in codec.RESOURCE_KINDS:
            return
        from .crdschema import check_cr_write, find_crd

        crd = find_crd(self.store, resource, self._group_of_path())
        if crd is None:
            return
        body["apiVersion"] = check_cr_write(
            crd, self._version_of_path(), body
        )

    def _resource_served(self, resource: str) -> bool:
        """Group-aware serving gate: core-path (/api/v1) requests serve
        built-ins only; /apis/{group}/... serves a resource only when an
        established CRD claims that exact (group, plural). (CR storage is
        keyed by plural; two CRDs reusing one plural across groups is
        rejected at routing granularity, mirroring the reference's
        ambiguous-plural restrictions.)"""
        group = self._group_of_path()
        # close the late-registration import-order hole (events/leases
        # kinds live in client/*): a process whose import chain swallowed
        # the eager registration must not 404 those resources forever
        codec.ensure_late_registration()
        if group is None and resource in codec.RESOURCE_KINDS:
            # built-in fast path, BEFORE the CRD lookup: on a stateless
            # frontend the store is a RESTClient and that lookup is a
            # remote list — paying it per request would put the primary
            # back on every read's critical path
            return True
        try:
            crds, _ = self.store.list("customresourcedefinitions")
        except Exception:
            crds = []
        if group is None:
            # the core path also serves established CRD plurals: the typed
            # REST client and kubectl build /api/v1 paths for every
            # resource (single internal version — no per-group clients)
            return any(c.spec.names.plural == resource for c in crds)
        version = self._version_of_path()
        for c in crds:
            if c.spec.group != group or c.spec.names.plural != resource:
                continue
            # per-version serving (apiextensions served flag): an
            # unserved version 404s even though the CRD claims the group
            if version is not None:
                from .crdschema import version_entry

                entry = version_entry(c, version)
                return entry is not None and entry["served"]
            return True
        return False

    def _maybe_proxy(self) -> bool:
        """kube-aggregator: if an APIService claims this path's group with a
        backend URL, forward the request verbatim and relay the response
        (staging/src/k8s.io/kube-aggregator proxy handler). Returns True if
        the request was proxied."""
        group = self._group_of_path()
        if group is None:
            return False
        try:
            svcs, _ = self.store.list("apiservices")
        except Exception:
            return False
        svc = next(
            (
                s
                for s in sorted(svcs, key=lambda s: s.spec.priority)
                if s.spec.group == group and s.spec.service_url
            ),
            None,
        )
        if svc is None:
            return False
        backend = svc.spec.service_url
        # the aggregator AUTHENTICATES before proxying (authorization is the
        # backend's job, like the reference forwarding user headers); an
        # anonymous-rejecting front server must not leak a bypass
        user, ok = self._authenticate()
        if not ok:
            return True  # 401 already written
        import urllib.error
        import urllib.request

        url = backend.rstrip("/") + self.path
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else None
        req = urllib.request.Request(url, data=body, method=self.command)
        for h in ("Content-Type", "Authorization"):
            if self.headers.get(h):
                req.add_header(h, self.headers[h])
        # requestheader identity propagation (X-Remote-*): the backend
        # trusts these from the front proxy, so client-supplied values
        # must NEVER pass through (spoof protection) — urllib won't copy
        # them since only the allowlist above is forwarded — and the
        # authenticated identity is stamped fresh
        if user is not None:
            req.add_header("X-Remote-User", user.name)
            groups = getattr(user, "groups", ()) or ()
            if groups:
                # one comma-combined field (RFC 7230 §3.2.2) — urllib
                # cannot emit repeated headers
                req.add_header("X-Remote-Group", ",".join(groups))
        ctx = None
        if url.startswith("https:"):
            try:
                ctx = _backend_ssl_context(svc.spec)
            except Exception as e:
                # e.g. invalid base64 / garbage PEM in the caBundle: the
                # APIService is misconfigured, not the request
                self._status_error(
                    502, "BadGateway", f"apiservice caBundle invalid: {e}"
                )
                return True
        try:
            with urllib.request.urlopen(req, timeout=30, context=ctx) as resp:
                payload = resp.read()
                self.send_response(resp.status)
                for h, val in resp.headers.items():
                    if h.lower() in ("content-type",):
                        self.send_header(h, val)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
        except urllib.error.HTTPError as e:
            payload = e.read()
            self.send_response(e.code)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except OSError as e:
            self._status_error(502, "BadGateway", f"aggregated backend: {e}")
        return True

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b"{}"
        from ..api.protocodec import CONTENT_TYPE, MAGIC, decode_obj

        if CONTENT_TYPE in (
            self.headers.get("Content-Type") or ""
        ) and raw.startswith(MAGIC):
            # binary write body: decode the envelope, then re-encode to the
            # JSON-ready dict every downstream handler already consumes —
            # one negotiation point covers every write path
            try:
                return codec.encode(decode_obj(raw))
            except Exception as e:
                # truncated varints/frames surface as IndexError/
                # struct.error/ValueError — map to 400 like malformed JSON
                raise ValidationError(f"malformed binary body: {e}") from e
        return json.loads(raw or b"{}")

    _request_user = None  # per-request memo set by _limited's APF path

    def _authenticate(self):
        """(user, ok): resolve the request identity. ok=False means a 401
        was already written. user is None only on the insecure port (no
        authenticator configured). The resolved identity is published to
        in-process admission via the admission.request_user contextvar
        (admission.Attributes.GetUserInfo() equivalent — NodeRestriction
        reads it)."""
        from .admission import request_user as _admission_user

        if self._request_user is not None:
            _admission_user.set(self._request_user[0])
            return self._request_user
        authn = self.server.authenticator
        if authn is None:
            _admission_user.set(None)
            return None, True
        from .auth import ANONYMOUS, UserInfo

        user = authn.authenticate_header(self.headers.get("Authorization", ""))
        if user is None:
            if not authn.allow_anonymous:
                self._status_error(401, "Unauthorized", "authentication required")
                return None, False
            user = UserInfo(ANONYMOUS, ("system:unauthenticated",))
        _admission_user.set(user)
        return user, True

    def _authorize(
        self, verb: str, resource: str, ns: Optional[str], name: str = ""
    ) -> bool:
        """authn → authz (DefaultBuildHandlerChain order). True = proceed;
        False = a 401/403 response was already written. No authenticator
        configured = insecure port semantics (everything allowed)."""
        authz = self.server.authorizer
        user, ok = self._authenticate()
        if not ok:
            return False
        if user is None:
            return True
        # ns None = cluster-scoped / cluster-wide request: requires a rule
        # covering all namespaces (the ClusterRole analogue)
        if authz is not None and not authz.authorize(
            user, verb, resource, ns if ns is not None else "*", name
        ):
            self._status_error(
                403,
                "Forbidden",
                f'user "{user.name}" cannot {verb} resource "{resource}"',
            )
            return False
        return True

    # -- verbs ---------------------------------------------------------------

    def _serve_metrics_api(self) -> bool:
        """metrics.k8s.io equivalent (staging/src/k8s.io/metrics +
        metrics-server): node/pod usage. Usage comes from the pods'
        ``metrics.kubernetes.io/cpu-usage`` annotations when present (the
        same source the HPA reads), else falls back to requests — a
        deterministic synthetic signal, the hollow-cluster analogue of
        cAdvisor. Served locally unless an APIService claims the group."""
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        if len(parts) < 4 or parts[:2] != ["apis", "metrics.k8s.io"]:
            return False
        # usage data is cluster-visibility: authn/authz like any resource
        # (grant via Rule(resources={"metrics"}))
        if not self._authorize("get", "metrics", None):
            return True  # a 401/403 was written
        rest = parts[3:]
        from ..api.objects import compute_pod_resource_request
        from ..api.resources import CPU, MEMORY, cpu_to_millis

        def pod_usage(p):
            ann = p.metadata.annotations
            raw = ann.get("metrics.kubernetes.io/cpu-usage")
            raw_mem = ann.get("metrics.kubernetes.io/memory-usage")
            req = compute_pod_resource_request(p)
            try:
                cpu = cpu_to_millis(raw) if raw else int(req.get(CPU, 0))
            except ValueError:
                cpu = int(req.get(CPU, 0))
            try:
                mem = int(raw_mem) if raw_mem else int(req.get(MEMORY, 0))
            except ValueError:
                mem = int(req.get(MEMORY, 0))
            return {"cpu": f"{cpu}m", "memory": str(mem)}

        pods, _ = self.store.list("pods")
        running = [p for p in pods if p.spec.node_name]
        if rest and rest[0] == "nodes":
            per_node = {}
            for p in running:
                u = pod_usage(p)
                agg = per_node.setdefault(p.spec.node_name, [0, 0])
                agg[0] += int(u["cpu"][:-1])
                agg[1] += int(u["memory"])
            nodes, _ = self.store.list("nodes")
            items = [
                {
                    "metadata": {"name": n.metadata.name},
                    "usage": {
                        "cpu": f"{per_node.get(n.metadata.name, [0, 0])[0]}m",
                        "memory": str(per_node.get(n.metadata.name, [0, 0])[1]),
                    },
                }
                for n in nodes
                if not rest[1:] or n.metadata.name == rest[1]
            ]
            self._json(200, {"kind": "NodeMetricsList", "items": items})
            return True
        ns = None
        if rest and rest[0] == "namespaces" and len(rest) >= 3:
            ns, rest = rest[1], rest[2:]
        if rest and rest[0] == "pods":
            items = [
                {
                    "metadata": {
                        "name": p.metadata.name,
                        "namespace": p.metadata.namespace,
                    },
                    "usage": pod_usage(p),
                }
                for p in running
                if ns is None or p.metadata.namespace == ns
            ]
            self._json(200, {"kind": "PodMetricsList", "items": items})
            return True
        return False


    # -- max-in-flight (DefaultBuildHandlerChain's WithMaxInFlightLimit) ----

    def _is_long_running(self) -> bool:
        """Watch streams are exempt from in-flight limits (the reference's
        longRunningRequestCheck). ONLY GET watches qualify — a write with
        ?watch=1 appended is an ordinary request and must consume a slot,
        or the limiter is trivially bypassable."""
        if self.command != "GET":
            return False
        q = parse_qs(urlparse(self.path).query)
        return q.get("watch", ["0"])[-1] in ("1", "true")

    def _audited(self, handler):
        """WithAudit (config.go:668): one ResponseComplete event per
        request, recorded after the handler writes its code. Wraps the
        WHOLE chain so limiter 429s and authn 401s are audited too — the
        rejections are when the trail matters most."""
        aud = getattr(self.server, "audit", None)
        if aud is None:
            return handler()
        self._last_code = 0  # keep-alive reuses the handler: never carry a
        # previous request's code into this event
        try:
            return handler()
        finally:
            try:
                # _limited's APF path memoizes the authenticated user for
                # exactly this finally (it must outlive _limited's own
                # finally, which releases the flow-control slot; the memo
                # is cleared below — keep-alive connections reuse the
                # handler across requests)
                # identity WITHOUT response-writing: the memoized APF user
                # if present, else a silent header resolve (a failed authn
                # already wrote its 401; never write from a finally)
                if self._request_user is not None:
                    user = self._request_user[0]
                elif self.server.authenticator is not None:
                    user = self.server.authenticator.authenticate_header(
                        self.headers.get("Authorization", "")
                    )
                else:
                    user = None
                resource, ns, name, _q = self._parse()
                if resource is not None:
                    if self._is_long_running():
                        verb = "watch"  # logged when the stream ends
                    else:
                        verb = {
                            "GET": "get" if name else "list",
                            "POST": "create",
                            "PUT": "update",
                            "DELETE": "delete",
                        }.get(self.command, self.command.lower())
                    aud.log(
                        user.name if user else None,
                        user.groups if user else (),
                        verb,
                        resource,
                        ns or "",
                        name or "",
                        getattr(self, "_last_code", 0),
                    )
            except Exception:
                pass  # auditing must never break request handling
            finally:
                self._request_user = None

    _watch_seat = None  # (flow, level) held during watch INITIALIZATION

    def _release_watch_seat(self) -> None:
        """Release the APF seat a watch held for its init phase (list/
        window replay). Idempotent — called by _serve_watch as soon as
        the replay drains, and again by _limited's finally as a backstop
        for error paths that never reached the drain point."""
        seat = self._watch_seat
        if seat is not None:
            self._watch_seat = None
            fc, lv = seat
            fc.end(lv)

    def _flow_admit(self, fc, verb: str):
        """authn → classify → admit for APF. Returns the admitted level,
        or None when a response (401/429) was already written. Memoizes
        the classification's identity for this one request: the handler's
        _authorize and _audited's event reuse it instead of re-resolving
        the token. Cleared by _audited's outer finally (keep-alive
        connections reuse the handler across requests); when no audit is
        configured the caller's finally clears it."""
        from .flowcontrol import RequestRejected

        user, ok = self._authenticate()
        if not ok:
            return None
        resource, _, _, _ = self._parse()
        try:
            lv = fc.begin(user, resource or "", verb)
        except RequestRejected as e:
            self._status_error(429, "TooManyRequests", str(e))
            return None
        self._request_user = (user, True)
        return lv

    def _limited(self, handler):
        """WithPriorityAndFairness when a FlowController is configured,
        else WithMaxInFlightLimit, else unlimited (insecure dev port).
        Request order through the chain matches DefaultBuildHandlerChain:
        authn happens before flow classification, authz after.

        Watch streams are exempt from the per-request limiters for their
        LIFETIME, but their INITIALIZATION — the cache replay that makes a
        cold informer expensive — occupies a seat (watch-init seat
        accounting, the reference's APF watch-init cost): 10k informers
        reconnecting at once queue behind the watch-init pool instead of
        monopolizing the server. The seat is handed to _serve_watch via
        _watch_seat so it can release the moment the replay drains."""
        fc = getattr(self.server, "flow", None)
        if self._is_long_running():
            if fc is None:
                return handler()
            lv = self._flow_admit(fc, "watch")
            if lv is None:
                return
            self._watch_seat = (fc, lv)
            try:
                return handler()
            finally:
                if getattr(self.server, "audit", None) is None:
                    self._request_user = None
                self._release_watch_seat()
        if fc is not None:
            lv = self._flow_admit(fc, self.command.lower())
            if lv is None:
                return
            try:
                return handler()
            finally:
                if getattr(self.server, "audit", None) is None:
                    self._request_user = None
                fc.end(lv)
        sem = self.server.inflight
        if sem is None:
            return handler()
        if not sem.acquire(blocking=False):
            return self._status_error(
                429, "TooManyRequests", "max in-flight requests exceeded"
            )
        try:
            return handler()
        finally:
            sem.release()

    def do_GET(self):
        return self._audited(lambda: self._limited(self._handle_GET))

    def do_POST(self):
        return self._audited(lambda: self._limited(self._handle_POST))

    def do_PUT(self):
        return self._audited(lambda: self._limited(self._handle_PUT))

    def do_DELETE(self):
        return self._audited(lambda: self._limited(self._handle_DELETE))

    def _handle_GET(self):
        u = urlparse(self.path)
        if u.path in ("/healthz", "/readyz", "/livez"):
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if u.path == "/metrics":
            # Prometheus exposition for THIS process (the debug surface
            # every process family now shares — utils/debugserver.py is
            # the standalone listener for scheduler/controller-manager).
            # Authorized like the metrics.k8s.io route: on a secured API
            # port the registry is not an anonymous surface.
            if not self._authorize("get", "metrics", None):
                return
            from ..utils.debugserver import metrics_payload

            body, ctype = metrics_payload()
            self.send_response(200)
            self._last_code = 200
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if u.path == "/debug/backup":
            # online consistent backup image (runtime/backup.py writes it
            # out; `ktpu-backup save --url` is the operator entry). Same
            # authz gate as /metrics: the image is the whole cluster
            # state, emphatically not an anonymous surface.
            if not self._authorize("get", "metrics", None):
                return
            return self._json(200, self.store.backup_state())
        if u.path == "/debug/traces":
            # the trace ring's REST view: ?id=<trace_id> for one trace
            # (store-side stamps attached), else slowest-N (?n=, ?kind=).
            # Same authz gate as /metrics: traces carry pod identities.
            if not self._authorize("get", "metrics", None):
                return
            from ..utils.debugserver import traces_payload

            q = {k: v[-1] for k, v in parse_qs(u.query).items()}
            code, payload = traces_payload(q)
            return self._json(code, payload)
        if self._maybe_proxy():
            return
        if self._serve_metrics_api():
            return
        resource, ns, name, query = self._parse()
        if resource is None:
            return self._status_error(404, "NotFound", "unknown path")
        if not self._resource_served(resource):
            return self._status_error(404, "NotFound", f"no such resource {resource}")
        verb = (
            "get"
            if name
            else ("watch" if query.get("watch") in ("1", "true") else "list")
        )
        if not self._authorize(verb, resource, ns, name or ""):
            return
        try:
            if resource == "pods" and name and name.endswith("/log"):
                # pods/{name}/log subresource -> node's log provider (the
                # kubelet hop of kubectl logs); plain text like the
                # reference's log REST handler
                tail = query.get("tailLines")
                try:
                    tail_n = int(tail) if tail is not None else None
                except ValueError:
                    return self._status_error(
                        400, "BadRequest", f"invalid tailLines {tail!r}"
                    )
                text = self.store.pod_logs(
                    ns or "", name[: -len("/log")], tail_n
                )
                body = text.encode()
                self.send_response(200)
                self._last_code = 200
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if name:
                obj = self.store.get(resource, ns or "", name)
                return self._respond_obj(200, obj)
            if query.get("kindResourceVersion") in ("1", "true"):
                # cheap freshness probe (no object payload): the rv of
                # this kind's newest event — what a frontend's consistent
                # list waits for before serving from its cache. Forwarded
                # upstream when this server is itself a frontend
                # (RESTClient.kind_resource_version chains).
                return self._json(
                    200,
                    {
                        "kind": "KindResourceVersion",
                        "resource": resource,
                        "kindResourceVersion": self.store.kind_resource_version(
                            resource
                        ),
                    },
                )
            if query.get("watch") in ("1", "true"):
                return self._serve_watch(resource, ns, query)
            try:
                pred = _list_options_predicate(query)
            except ValueError as e:
                return self._status_error(400, "BadRequest", str(e))
            cacher = getattr(self.server, "cacher", None)
            limit_s = query.get("limit")
            try:
                limit = int(limit_s) if limit_s is not None else 0
            except ValueError:
                limit = -1
            if limit < 0:
                # negative limits would hit Python slice semantics in the
                # paginator (an endless 0-item continuation loop); the
                # reference rejects them too
                return self._status_error(
                    400, "BadRequest", f"invalid limit {limit_s!r}"
                )
            cont = query.get("continue")
            # list-from-cache (reference GetList via cacher): paginated
            # lists and resourceVersion=0 lists serve from the watch cache
            # at one consistent rv; a plain list stays a store quorum read
            if cacher is not None and (limit or cont or
                                       query.get("resourceVersion") == "0"):
                try:
                    items, rv, next_token = cacher.list_page(
                        resource,
                        namespace=ns,
                        pred=pred,
                        limit=limit,
                        continue_token=cont,
                        # a limit list without rv=0 is still a consistent
                        # read: wait for the cache to consume THIS KIND's
                        # newest event (the global rv would never converge
                        # for a quiet kind — other kinds keep advancing it)
                        fresh_rv=(
                            None
                            if query.get("resourceVersion") == "0" or cont
                            else self.store.kind_resource_version(resource)
                        ),
                    )
                except Expired as e:
                    return self._status_error(410, "Expired", str(e))
                except TimeoutError as e:
                    # cache could not catch the kind's newest event up in
                    # time — retryable, never a silent stale 200
                    return self._status_error(
                        504, "Timeout", str(e), retry_after_s=1.0
                    )
                meta = {"resourceVersion": str(rv)}
                if next_token:
                    meta["continue"] = next_token
                return self._json(
                    200,
                    {
                        "kind": "List",
                        "apiVersion": "v1",
                        "metadata": meta,
                        "items": [codec.encode(o) for o in items],
                    },
                )
            objs, rv = self.store.list(resource, namespace=ns)
            if pred is not None:
                objs = [o for o in objs if pred(o)]
            return self._json(
                200,
                {
                    "kind": "List",
                    "apiVersion": "v1",
                    "metadata": {"resourceVersion": str(rv)},
                    "items": [codec.encode(o) for o in objs],
                },
            )
        except NotFound as e:
            return self._status_error(404, "NotFound", str(e))
        except KeyError as e:
            return self._status_error(404, "NotFound", str(e))

    def _serve_watch(self, resource: str, ns: Optional[str], query: dict):
        from ..runtime.watch import BOOKMARK

        from_rv = int(query.get("resourceVersion", 0) or 0)
        cacher = getattr(self.server, "cacher", None)
        try:
            if cacher is not None:
                # the watch cache absorbs the fan-out: this stream is one
                # of N queue consumers on ONE store watch per kind, and a
                # from_rv inside the event window replays from memory
                watcher = cacher.watch(resource, from_version=from_rv)
            else:
                watcher = self.store.watch(resource, from_version=from_rv)
        except Expired as e:
            # 410 Gone ("resourceVersion too old"): the client must
            # re-list, exactly like the reference's etcd3 watcher
            return self._status_error(410, "Expired", str(e))
        try:
            pred = _list_options_predicate(query)
        except ValueError as e:
            watcher.stop()
            return self._status_error(400, "BadRequest", str(e))
        from ..utils.metrics import metrics

        metrics.inc("apiserver_watch_streams_started_total",
                    {"resource": resource})
        self.server.watch_streams_adjust(resource, +1)
        import time as _time

        bookmark_period = getattr(self.server, "bookmark_period_s", 2.0)
        # seat accounting: the APF watch-init seat covers the REPLAY phase
        # only; once the initial burst drains this stream is a cheap queue
        # consumer and the seat goes back to the pool
        replay_left = getattr(watcher, "replay_count", 0)
        if replay_left == 0:
            self._release_watch_seat()
        last_write = _time.monotonic()
        # rv of the last event actually WRITTEN to this stream: the idle
        # heartbeat must never advertise an rv ahead of what the client
        # has received — a cache rv read out-of-band can cover an event
        # still sitting undelivered in this watcher's queue, and a client
        # resuming past it would silently lose the event forever. RV
        # advancement for idle clients comes from the cacher's own
        # bookmarks, which flow queue-ordered with the events.
        last_rv_sent = from_rv

        # codec negotiation: a client offering the compact binary watch
        # codec in Accept gets length-prefixed frames (the object payload
        # encoded ONCE per event and shared across every stream of this
        # kind's fan-out — apiserver/watchcodec.py); everyone else gets
        # the newline-JSON wire, which stays the default and the
        # mixed-version fallback (an old client never offers, an old
        # server never answers binary)
        from . import watchcodec

        binary = watchcodec.WATCH_CONTENT_TYPE in (
            self.headers.get("Accept") or ""
        )

        def write_chunk(payload: bytes) -> None:
            nonlocal last_write
            self.wfile.write(b"%x\r\n%s\r\n" % (len(payload), payload))
            self.wfile.flush()
            last_write = _time.monotonic()

        def write_event(ev) -> None:
            if binary:
                write_chunk(watchcodec.event_frame(ev))
            else:
                write_chunk(
                    json.dumps(
                        {"type": ev.type, "object": codec.encode(ev.object)}
                    ).encode()
                    + b"\n"
                )

        def write_bookmark(rv: int) -> None:
            if binary:
                write_chunk(watchcodec.bookmark_frame(rv))
            else:
                write_chunk(
                    json.dumps(
                        {
                            "type": BOOKMARK,
                            "object": {"metadata": {"resourceVersion": rv}},
                        }
                    ).encode()
                    + b"\n"
                )

        # a watch client never speaks again after its request, so a
        # READABLE connection means EOF (orderly close) or garbage —
        # either way the stream is over. Peeking costs one syscall per
        # idle poll and turns "gauge leaks until the next heartbeat
        # tick" into detection within _WATCH_POLL_S. TLS sockets can't
        # MSG_PEEK through the record layer; they rely on the heartbeat.
        import select as _select
        import socket as _socket

        def client_gone() -> bool:
            sock = self.connection
            try:
                import ssl as _ssl

                if isinstance(sock, _ssl.SSLSocket):
                    return False
                readable, _, errored = _select.select([sock], [], [sock], 0)
                if errored:
                    return True
                if not readable:
                    return False
                return sock.recv(1, _socket.MSG_PEEK) == b""
            except (OSError, ValueError):
                return True

        # gauge unwind: exactly once, AT the failure site when a write
        # fails (the regression in ISSUE 20: waiting for finally meant
        # an abrupt disconnect mid-frame held the gauge until the next
        # heartbeat tick on other code paths), in finally otherwise
        gauge_open = True

        def gauge_close() -> None:
            nonlocal gauge_open
            if gauge_open:
                gauge_open = False
                self.server.watch_streams_adjust(resource, -1)

        try:
            self.send_response(200)
            self.send_header(
                "Content-Type",
                watchcodec.WATCH_CONTENT_TYPE if binary else "application/json",
            )
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            while not self.server.stopping.is_set():
                ev = watcher.get(timeout=_WATCH_POLL_S)
                if ev is None:
                    if watcher.stopped:
                        break
                    self._release_watch_seat()  # queue drained: init over
                    if client_gone():
                        break
                    # idle heartbeat: a stream with no events still emits
                    # a bookmark every bookmark_period_s, so a half-open
                    # TCP client (silently dropped connection) fails the
                    # write and this thread is reaped instead of leaking
                    if (
                        bookmark_period
                        and _time.monotonic() - last_write >= bookmark_period
                    ):
                        write_bookmark(last_rv_sent)
                    continue
                if replay_left > 0:
                    replay_left -= 1
                    if replay_left == 0:
                        self._release_watch_seat()
                if ev.type == BOOKMARK:
                    # cache-originated progress notify: forwarded before
                    # the ns/selector filters (it carries no object).
                    # Queue-ordered behind the events it covers, so its
                    # rv is safe to advertise
                    write_bookmark(ev.resource_version)
                    last_rv_sent = max(last_rv_sent, ev.resource_version)
                    continue
                obj = ev.object
                if ns is not None and obj.metadata.namespace != ns:
                    continue
                if pred is not None and not pred(obj):
                    continue
                write_event(ev)
                last_rv_sent = max(last_rv_sent, ev.resource_version)
        except (BrokenPipeError, ConnectionResetError, OSError):
            # decrement on the write-failure path itself: the stream is
            # observably dead the moment a frame write fails
            gauge_close()
        finally:
            try:
                # terminate the chunked body: without the trailer a
                # keep-alive client blocks on the half-finished stream
                # forever instead of seeing EOF and resuming (server
                # shutdown / cacher stop must look like a stream END)
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except OSError:
                pass
            watcher.stop()
            gauge_close()

    def _handle_POST(self):
        if self._maybe_proxy():
            return
        resource, ns, name, _q = self._parse()
        if resource is None:
            return self._status_error(404, "NotFound", "unknown path")
        # any authenticated user may ask "can I?" about themselves — the
        # review endpoint is exempt from the resource gate and authz
        # (apiserver authorizes selfsubjectaccessreviews for system:authenticated)
        if resource != "selfsubjectaccessreviews":
            if not self._resource_served(resource):
                return self._status_error(
                    404, "NotFound", f"no such resource {resource}"
                )
            # subresources authorize under their own resource name
            # (authorization.k8s.io attributes): pods/binding is the verb
            # the SCHEDULER holds — the node authorizer denies it to
            # kubelets even though they may create (mirror) pods
            authz_resource = resource
            if resource == "pods" and name and name.endswith("/binding"):
                authz_resource = "bindings"
            if not self._authorize("create", authz_resource, ns):
                return
        try:
            body = self._read_body()
            if resource == "pods" and name and name.endswith("/exec"):
                # pods/{name}/exec subresource (ExecSync through the pod's
                # kubelet); body: {"command": [...]} — plain-text reply
                cmd = body.get("command") or []
                if (
                    not isinstance(cmd, list)
                    or not cmd
                    or not all(isinstance(c, str) for c in cmd)
                ):
                    return self._status_error(
                        400, "BadRequest", "exec body needs a list of strings"
                    )
                try:
                    out = self.store.pod_exec(
                        ns or "default", name[: -len("/exec")], cmd
                    )
                except NotImplementedError:
                    return self._status_error(
                        501, "NotImplemented", "runtime does not support exec"
                    )
                data = out.encode()
                self.send_response(200)
                self._last_code = 200
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            if resource == "pods" and name and name.endswith("/binding"):
                b = codec.from_dict(Binding, body)
                pod_name = name.rsplit("/", 1)[0]
                b.pod_name = b.pod_name or pod_name
                b.pod_namespace = b.pod_namespace or (ns or "default")
                # leadership fencing over REST: an X-Leadership-Fence
                # header rebuilds the BindFence and the store validates it
                # against the live lease UNDER THE SAME LOCK the bind
                # applies under — a scheduler replica deposed between
                # minting the token and this request gets LeaderFenced
                # (409, distinct reason), never a silently applied late
                # bind. A malformed header is 400: it must never degrade
                # to an unfenced bind.
                from ..client.leaderelection import (
                    FENCE_HEADER,
                    fence_from_header,
                )

                fence = None
                fence_hdr = self.headers.get(FENCE_HEADER)
                if fence_hdr:
                    try:
                        fence = fence_from_header(fence_hdr)
                    except ValueError as fe:
                        return self._status_error(400, "BadRequest", str(fe))
                # trace-context propagation (utils/tracing.py): the
                # scheduler-minted trace id arrives in X-Trace-Context;
                # re-establish it thread-locally so the store's apply
                # (or LeaderFenced rejection) stamps under the SAME id —
                # a bind that crosses REST keeps its identity
                from ..utils.tracing import TRACE_HEADER, bind_context

                trace_hdr = self.headers.get(TRACE_HEADER) or ""
                bind_key = f"{b.pod_namespace}/{b.pod_name}"
                with bind_context({bind_key: trace_hdr} if trace_hdr else {}):
                    errs = self.store.bind_pods([b], fence=fence)
                if errs and errs[0] is not None:
                    # preserve the store's error taxonomy across the wire
                    # (bind_pods returns the typed exception): a vanished
                    # pod is 404 — the scheduler's reconciler branches on
                    # NotFound — and only real bind conflicts (already
                    # bound / uid mismatch) are 409
                    if isinstance(errs[0], NotFound):
                        return self._status_error(
                            404, "NotFound", str(errs[0])
                        )
                    return self._status_error(409, "Conflict", str(errs[0]))
                return self._json(201, {"kind": "Status", "status": "Success"})
            if resource == "pods" and name and name.endswith("/eviction"):
                # PDB-respecting delete (registry/core/pod/rest/eviction.go)
                from ..api.objects import Eviction
                from ..client.apiserver import TooManyRequests

                ev = codec.from_dict(Eviction, body)
                pod_name = name.rsplit("/", 1)[0]
                if ev.pod_name and ev.pod_name != pod_name:
                    return self._status_error(
                        400, "BadRequest", "eviction body names a different pod"
                    )
                try:
                    self.store.evict_pod(ns or "default", pod_name)
                except TooManyRequests as e:
                    # Retry-After rides along (eviction.go returns the
                    # DisruptedPods-style backoff hint): a paced drainer
                    # (descheduler wave, kubectl drain loop) should wait
                    # for the disruption controller's next budget resync
                    # instead of giving up on the first 429
                    return self._status_error(
                        429,
                        "TooManyRequests",
                        str(e),
                        retry_after_s=getattr(e, "retry_after_s", 1.0),
                    )
                return self._json(201, {"kind": "Status", "status": "Success"})
            if resource == "selfsubjectaccessreviews":
                # authz introspection (SelfSubjectAccessReview): evaluate
                # the chain's own authorizer for the requesting user. The
                # AUTHN gate still applies — a caller who would be 401'd
                # everywhere must be 401'd here too, not told "allowed"
                attrs = body.get("spec", {}).get("resourceAttributes", {})
                user, ok = self._authenticate()
                if not ok:
                    return
                allowed = (
                    self.server.authorizer is None
                    or user is None  # insecure port: everything allowed
                    or self.server.authorizer.authorize(
                        user,
                        attrs.get("verb", "get"),
                        attrs.get("resource", ""),
                        attrs.get("namespace") or "*",
                        attrs.get("name", ""),
                    )
                )
                return self._json(
                    201,
                    {
                        "kind": "SelfSubjectAccessReview",
                        "status": {"allowed": allowed},
                    },
                )
            self._cr_write_gate(resource, body)
            obj = codec.decode(resource, body)
            if ns is not None:
                obj.metadata.namespace = ns
            created = self.store.create(resource, obj)
            return self._json(201, codec.encode(created))
        except AlreadyExists as e:
            return self._status_error(409, "AlreadyExists", str(e))
        except LeaderFenced as e:
            # leadership fence rejection: the caller's lease grant was
            # superseded BEFORE anything applied. 409 with a distinct
            # reason so the client maps it back to LeaderFenced (a plain
            # Conflict is retryable per-pod; this one means "you are not
            # the leader anymore" for the whole batch)
            return self._status_error(409, "LeaderFenced", str(e))
        except DegradedWrites as e:
            return self._degraded_error(e)
        except NotPrimary as e:
            # fenced store: permanent for this process (a successor
            # exists) — 503 without Retry-After; clients must re-discover
            # the primary, not hammer this one
            return self._status_error(503, "ServiceUnavailable", str(e))
        except AdmissionDenied as e:
            # quota denial is 403 Forbidden like the reference's admission
            return self._status_error(403, "Forbidden", str(e))
        except NotFound as e:
            # e.g. evicting/binding a pod that vanished — NotFound is a
            # KeyError subclass, so this must precede the 400 handler
            return self._status_error(404, "NotFound", str(e))
        except ValidationError as e:
            return self._status_error(400, "Invalid", str(e))
        except (KeyError, json.JSONDecodeError) as e:
            return self._status_error(400, "BadRequest", str(e))

    def _handle_PUT(self):
        if self._maybe_proxy():
            return
        resource, ns, name, _q = self._parse()
        if resource is None or not name:
            return self._status_error(404, "NotFound", "unknown path")
        if not self._resource_served(resource):
            return self._status_error(404, "NotFound", f"no such resource {resource}")
        if not self._authorize("update", resource, ns, name or ""):
            return
        try:
            body = self._read_body()
            self._cr_write_gate(resource, body)
            obj = codec.decode(resource, body)
            if ns is not None:
                obj.metadata.namespace = ns
            updated = self.store.update(resource, obj)
            return self._respond_obj(200, updated)
        except NotFound as e:
            return self._status_error(404, "NotFound", str(e))
        except Conflict as e:
            return self._status_error(409, "Conflict", str(e))
        except DegradedWrites as e:
            return self._degraded_error(e)
        except NotPrimary as e:
            return self._status_error(503, "ServiceUnavailable", str(e))
        except AdmissionDenied as e:
            return self._status_error(403, "Forbidden", str(e))
        except ValidationError as e:
            return self._status_error(400, "Invalid", str(e))
        except (KeyError, json.JSONDecodeError) as e:
            return self._status_error(400, "BadRequest", str(e))

    def _handle_DELETE(self):
        if self._maybe_proxy():
            return
        resource, ns, name, _q = self._parse()
        if resource is None or not name:
            return self._status_error(404, "NotFound", "unknown path")
        if not self._resource_served(resource):
            return self._status_error(404, "NotFound", f"no such resource {resource}")
        if not self._authorize("delete", resource, ns, name or ""):
            return
        try:
            self.store.delete(resource, ns or "", name)
            return self._json(200, {"kind": "Status", "status": "Success"})
        except NotFound as e:
            return self._status_error(404, "NotFound", str(e))
        except DegradedWrites as e:
            return self._degraded_error(e)
        except NotPrimary as e:
            return self._status_error(503, "ServiceUnavailable", str(e))
        except AdmissionDenied as e:
            return self._status_error(403, "Forbidden", str(e))


def _list_options_predicate(query: dict):
    """?labelSelector= / ?fieldSelector= -> combined object predicate, or
    None when neither is present (apimachinery ListOptions). ValueError
    (→400) on selector syntax errors.

    Watch caveat vs the reference's cacher: an object MODIFIED out of the
    selector is dropped, not synthesized into a DELETED event; informer
    relists reconcile the difference."""
    lsel_s = query.get("labelSelector")
    fsel_s = query.get("fieldSelector")
    if not lsel_s and not fsel_s:
        return None
    from ..api.selectors import FieldSelector, parse_label_selector

    lsel = parse_label_selector(lsel_s) if lsel_s else None
    fsel = FieldSelector.parse(fsel_s) if fsel_s else None

    def pred(obj) -> bool:
        if lsel is not None and not lsel.matches(obj.metadata.labels or {}):
            return False
        if fsel is not None and not fsel.matches(obj):
            return False
        return True

    return pred


def _backend_ssl_context(spec):
    """SSL context for an https APIService backend: verify against the
    spec's base64 caBundle when set (kube-aggregator apiservice cert
    handling); insecureSkipTLSVerify disables verification entirely;
    neither set falls back to system roots."""
    import base64
    import ssl

    if spec.insecure_skip_tls_verify:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx
    if spec.ca_bundle:
        pem = base64.b64decode(spec.ca_bundle).decode()
        return ssl.create_default_context(cadata=pem)
    return ssl.create_default_context()


class APIServerHTTP(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(
        self,
        addr,
        store: APIServer,
        authenticator=None,
        authorizer=None,
        max_in_flight: int = 400,
        priority_and_fairness: bool = True,
        audit=None,  # apiserver.audit.AuditLogger, or None
        watch_cache: bool = True,
        bookmark_period_s: float = 2.0,
        watch_cache_window: int = 0,
        freshness_timeout_s: float = 5.0,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
    ):
        super().__init__(addr, _Handler)
        # TLS on the serving hop: wrap the LISTENING socket with the
        # handshake DEFERRED — accept() hands back an un-handshaken
        # SSLSocket and the handshake happens on the handler thread's
        # first read, so a slow (or hostile) handshaker can never stall
        # the accept loop (the same never-block-the-dispatcher contract
        # the relay workers live under)
        self.tls = bool(tls_cert and tls_key)
        if self.tls:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            self.socket = ctx.wrap_socket(
                self.socket, server_side=True, do_handshake_on_connect=False
            )
        self.store = store
        self.authenticator = authenticator  # None = insecure port semantics
        self.authorizer = authorizer
        self.audit = audit
        self.bookmark_period_s = bookmark_period_s
        # the watch cache (apiserver/cacher.py): every watch stream and
        # paginated/rv=0 list serves from it — ONE store watch per kind
        # regardless of client count
        self.cacher = None
        if watch_cache:
            from .cacher import DEFAULT_WINDOW, Cacher

            self.cacher = Cacher(
                store,
                window=watch_cache_window or DEFAULT_WINDOW,
                bookmark_period_s=bookmark_period_s,
                freshness_timeout_s=freshness_timeout_s,
            )
        self._watch_streams_lock = threading.Lock()
        self._watch_streams: dict = {}
        # WithPriorityAndFairness over the same total budget; falls back to
        # WithMaxInFlightLimit (config.go:662-666) when disabled. 0/None
        # max_in_flight disables both
        self.flow = None
        # APF needs identities to classify; on the insecure port every
        # request would be anonymous and the whole server would collapse
        # into global-default's share — fall back to the plain limiter
        if max_in_flight and priority_and_fairness and authenticator is not None:
            from .flowcontrol import FlowController

            self.flow = FlowController(total_concurrency=max_in_flight)
        self.inflight = (
            threading.BoundedSemaphore(max_in_flight) if max_in_flight else None
        )
        self.stopping = threading.Event()

    def watch_streams_adjust(self, resource: str, delta: int) -> None:
        """Track live watch-stream threads per resource: the gauge is how
        the half-open-connection reaper is observable (a dead client's
        thread exits on its next bookmark write and the gauge drops)."""
        from ..utils.metrics import metrics

        with self._watch_streams_lock:
            n = self._watch_streams.get(resource, 0) + delta
            self._watch_streams[resource] = max(0, n)
            metrics.set_gauge(
                "apiserver_watch_streams", self._watch_streams[resource],
                {"resource": resource},
            )

    def watch_stream_count(self, resource: str) -> int:
        with self._watch_streams_lock:
            return self._watch_streams.get(resource, 0)

    def shutdown(self):
        self.stopping.set()
        if self.cacher is not None:
            self.cacher.stop()
        super().shutdown()


def serve(
    store: Optional[APIServer] = None,
    port: int = 0,
    authenticator=None,
    authorizer=None,
    max_in_flight: int = 400,
    priority_and_fairness: bool = True,
    audit=None,
    watch_cache: bool = True,
    bookmark_period_s: float = 2.0,
    watch_cache_window: int = 0,
    freshness_timeout_s: float = 5.0,
    tls_cert: Optional[str] = None,
    tls_key: Optional[str] = None,
) -> Tuple[APIServerHTTP, int, APIServer]:
    """Start the façade on a background thread; returns (server, port, store).
    max_in_flight=0 disables the in-flight limiter. watch_cache=False
    falls back to per-client store watches (the pre-cacher read path).
    tls_cert+tls_key turn the port into an https listener."""
    store = store or APIServer()
    srv = APIServerHTTP(
        ("0.0.0.0", port),
        store,
        authenticator,
        authorizer,
        max_in_flight=max_in_flight,
        priority_and_fairness=priority_and_fairness,
        audit=audit,
        watch_cache=watch_cache,
        bookmark_period_s=bookmark_period_s,
        watch_cache_window=watch_cache_window,
        freshness_timeout_s=freshness_timeout_s,
        tls_cert=tls_cert,
        tls_key=tls_key,
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1], store
