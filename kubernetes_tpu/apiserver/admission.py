"""Admission breadth: NodeRestriction, AlwaysPullImages, PodSecurityPolicy,
and quota scope selection.

Reference: plugin/pkg/admission/noderestriction/admission.go (node
identities may mutate only their own Node and pods bound to them),
…/alwayspullimages (force PullAlways so a scheduled-together pod can't read
a private image from the node cache), …/security/podsecuritypolicy
(validate pod security posture against cluster policies), and the quota
evaluator's scope matching (pkg/quota/v1/evaluator/core/pods.go
podMatchesScopeFunc).

The requesting identity reaches in-process admission through a contextvar
the REST layer sets after authentication — the moral equivalent of
admission.Attributes.GetUserInfo(). In-process callers (controllers,
tests) have no identity set and are unrestricted, like loopback clients
with cluster-admin.
"""

from __future__ import annotations

import contextvars
from typing import Optional

from ..api import objects as v1
from .auth import AdmissionDenied, AdmissionPlugin

# set by the REST layer per request (None = loopback/in-process client)
request_user: contextvars.ContextVar = contextvars.ContextVar(
    "request_user", default=None
)

NODE_USER_PREFIX = "system:node:"
NODES_GROUP = "system:nodes"


class NodeRestrictionAdmission(AdmissionPlugin):
    """A node identity (user system:node:<name>, group system:nodes) may
    mutate only its OWN Node object and pods BOUND to it (the mirror-pod /
    status-update surface). Everything else is denied — a compromised
    kubelet cannot reach across the cluster."""

    name = "NodeRestriction"

    def validate(self, verb: str, resource: str, obj) -> None:
        user = request_user.get()
        if user is None or NODES_GROUP not in getattr(user, "groups", ()):
            return
        if not user.name.startswith(NODE_USER_PREFIX):
            raise AdmissionDenied(
                f"user in {NODES_GROUP} without a node identity: {user.name}"
            )
        node_name = user.name[len(NODE_USER_PREFIX):]
        if resource == "nodes":
            if obj is not None and obj.metadata.name != node_name:
                raise AdmissionDenied(
                    f"node {node_name!r} cannot modify node "
                    f"{obj.metadata.name!r}"
                )
            return
        if resource == "pods":
            bound = getattr(obj.spec, "node_name", "") if obj is not None else ""
            if bound != node_name:
                raise AdmissionDenied(
                    f"node {node_name!r} can only {verb} pods bound to "
                    f"itself (pod bound to {bound or 'nothing'})"
                )
            return
        if resource == "leases":
            # node heartbeat leases: only its own
            if obj is not None and obj.metadata.name != node_name:
                raise AdmissionDenied(
                    f"node {node_name!r} cannot renew lease "
                    f"{obj.metadata.name!r}"
                )
            return
        raise AdmissionDenied(
            f"node identity may not {verb} {resource} objects"
        )


class AlwaysPullImagesAdmission(AdmissionPlugin):
    """Force imagePullPolicy=Always on every container at create: without
    it, any pod scheduled onto a node can run a private image already
    pulled there without presenting credentials."""

    name = "AlwaysPullImages"

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            c.image_pull_policy = "Always"


class PodSecurityPolicyAdmission(AdmissionPlugin):
    """Validate pod security posture against the cluster's
    PodSecurityPolicy objects: the pod is admitted iff SOME policy allows
    every requested capability (privileged, hostNetwork, run-as-user).
    No policies installed = the gate is open (the plugin disabled state;
    the reference denies, but requires explicit enablement — here
    installing the first policy arms the gate)."""

    name = "PodSecurityPolicy"

    def __init__(self, server):
        self.server = server

    @staticmethod
    def _pod_wants(pod) -> dict:
        privileged = any(
            c.security_context is not None and c.security_context.privileged
            for c in list(pod.spec.containers) + list(pod.spec.init_containers)
        )
        runs_as_root = any(
            c.security_context is not None
            and c.security_context.run_as_user == 0
            for c in list(pod.spec.containers) + list(pod.spec.init_containers)
        )
        return {
            "privileged": privileged,
            "host_network": pod.spec.host_network,
            "runs_as_root": runs_as_root,
        }

    @staticmethod
    def _allows(psp: "v1.PodSecurityPolicy", wants: dict) -> Optional[str]:
        s = psp.spec
        if wants["privileged"] and not s.privileged:
            return "privileged containers are not allowed"
        if wants["host_network"] and not s.host_network:
            return "hostNetwork is not allowed"
        if s.run_as_user_rule == "MustRunAsNonRoot" and wants["runs_as_root"]:
            return "running as root (runAsUser=0) is not allowed"
        return None

    def validate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        try:
            policies, _ = self.server.list("podsecuritypolicies")
        except Exception:
            return
        if not policies:
            return
        wants = self._pod_wants(obj)
        reasons = []
        for psp in sorted(policies, key=lambda p: p.metadata.name):
            why = self._allows(psp, wants)
            if why is None:
                return  # some policy admits the pod
            reasons.append(f"{psp.metadata.name}: {why}")
        raise AdmissionDenied(
            "unable to validate against any pod security policy: "
            + "; ".join(reasons)
        )


def pod_matches_scopes(pod, scopes) -> bool:
    """Quota scope selection (podMatchesScopeFunc): a scoped quota tracks
    and limits only matching pods. BestEffort = no container requests or
    limits at all; Terminating = activeDeadlineSeconds set."""
    for scope in scopes:
        best_effort = not any(
            c.requests or c.limits
            for c in list(pod.spec.containers) + list(pod.spec.init_containers)
        )
        terminating = pod.spec.active_deadline_seconds is not None
        if scope == "BestEffort" and not best_effort:
            return False
        if scope == "NotBestEffort" and best_effort:
            return False
        if scope == "Terminating" and not terminating:
            return False
        if scope == "NotTerminating" and terminating:
            return False
    return True
