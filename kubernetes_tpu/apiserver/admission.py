"""Admission breadth: NodeRestriction, AlwaysPullImages, PodSecurityPolicy,
and quota scope selection.

Reference: plugin/pkg/admission/noderestriction/admission.go (node
identities may mutate only their own Node and pods bound to them),
…/alwayspullimages (force PullAlways so a scheduled-together pod can't read
a private image from the node cache), …/security/podsecuritypolicy
(validate pod security posture against cluster policies), and the quota
evaluator's scope matching (pkg/quota/v1/evaluator/core/pods.go
podMatchesScopeFunc).

The requesting identity reaches in-process admission through a contextvar
the REST layer sets after authentication — the moral equivalent of
admission.Attributes.GetUserInfo(). In-process callers (controllers,
tests) have no identity set and are unrestricted, like loopback clients
with cluster-admin.
"""

from __future__ import annotations

import contextvars
from typing import Optional

from ..api import objects as v1
from .auth import AdmissionDenied, AdmissionPlugin

# set by the REST layer per request (None = loopback/in-process client)
request_user: contextvars.ContextVar = contextvars.ContextVar(
    "request_user", default=None
)

NODE_USER_PREFIX = "system:node:"
NODES_GROUP = "system:nodes"


class NodeRestrictionAdmission(AdmissionPlugin):
    """A node identity (user system:node:<name>, group system:nodes) may
    mutate only its OWN Node object and pods BOUND to it (the mirror-pod /
    status-update surface). Everything else is denied — a compromised
    kubelet cannot reach across the cluster."""

    name = "NodeRestriction"

    def validate(self, verb: str, resource: str, obj) -> None:
        user = request_user.get()
        if user is None or NODES_GROUP not in getattr(user, "groups", ()):
            return
        if not user.name.startswith(NODE_USER_PREFIX):
            raise AdmissionDenied(
                f"user in {NODES_GROUP} without a node identity: {user.name}"
            )
        node_name = user.name[len(NODE_USER_PREFIX):]
        if resource == "nodes":
            if obj is not None and obj.metadata.name != node_name:
                raise AdmissionDenied(
                    f"node {node_name!r} cannot modify node "
                    f"{obj.metadata.name!r}"
                )
            return
        if resource == "pods":
            bound = getattr(obj.spec, "node_name", "") if obj is not None else ""
            if bound != node_name:
                raise AdmissionDenied(
                    f"node {node_name!r} can only {verb} pods bound to "
                    f"itself (pod bound to {bound or 'nothing'})"
                )
            return
        if resource == "leases":
            # node heartbeat leases: only its own
            if obj is not None and obj.metadata.name != node_name:
                raise AdmissionDenied(
                    f"node {node_name!r} cannot renew lease "
                    f"{obj.metadata.name!r}"
                )
            return
        raise AdmissionDenied(
            f"node identity may not {verb} {resource} objects"
        )


class AlwaysPullImagesAdmission(AdmissionPlugin):
    """Force imagePullPolicy=Always on every container at create: without
    it, any pod scheduled onto a node can run a private image already
    pulled there without presenting credentials."""

    name = "AlwaysPullImages"

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            c.image_pull_policy = "Always"


class PodSecurityPolicyAdmission(AdmissionPlugin):
    """Validate pod security posture against the cluster's
    PodSecurityPolicy objects: the pod is admitted iff SOME policy allows
    every requested capability (privileged, hostNetwork, run-as-user).
    No policies installed = the gate is open (the plugin disabled state;
    the reference denies, but requires explicit enablement — here
    installing the first policy arms the gate)."""

    name = "PodSecurityPolicy"

    def __init__(self, server):
        self.server = server

    @staticmethod
    def _pod_wants(pod) -> dict:
        privileged = any(
            c.security_context is not None and c.security_context.privileged
            for c in list(pod.spec.containers) + list(pod.spec.init_containers)
        )
        runs_as_root = any(
            c.security_context is not None
            and c.security_context.run_as_user == 0
            for c in list(pod.spec.containers) + list(pod.spec.init_containers)
        )
        return {
            "privileged": privileged,
            "host_network": pod.spec.host_network,
            "runs_as_root": runs_as_root,
        }

    @staticmethod
    def _allows(psp: "v1.PodSecurityPolicy", wants: dict) -> Optional[str]:
        s = psp.spec
        if wants["privileged"] and not s.privileged:
            return "privileged containers are not allowed"
        if wants["host_network"] and not s.host_network:
            return "hostNetwork is not allowed"
        if s.run_as_user_rule == "MustRunAsNonRoot" and wants["runs_as_root"]:
            return "running as root (runAsUser=0) is not allowed"
        return None

    def validate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        try:
            policies, _ = self.server.list("podsecuritypolicies")
        except Exception:
            return
        if not policies:
            return
        wants = self._pod_wants(obj)
        reasons = []
        for psp in sorted(policies, key=lambda p: p.metadata.name):
            why = self._allows(psp, wants)
            if why is None:
                return  # some policy admits the pod
            reasons.append(f"{psp.metadata.name}: {why}")
        raise AdmissionDenied(
            "unable to validate against any pod security policy: "
            + "; ".join(reasons)
        )


class ExtendedResourceTolerationAdmission(AdmissionPlugin):
    """Pods requesting extended resources get tolerations for taints keyed
    by those resources (plugin/pkg/admission/extendedresourcetoleration):
    the TPU-shaped flow — nodes carrying tpu.dev/chip advertise a matching
    NoSchedule taint so ordinary pods stay off the accelerator pool, and
    chip-requesting pods tolerate it automatically."""

    name = "ExtendedResourceToleration"

    BUILTIN = frozenset({"cpu", "memory", "ephemeral-storage", "pods"})

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        wanted = set()
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            for name in list(c.requests) + list(c.limits):
                if "/" in name and name not in self.BUILTIN:
                    wanted.add(name)
        for res_name in sorted(wanted):
            taint = v1.Taint(res_name, "", v1.TAINT_NO_SCHEDULE)
            # effect/operator-aware: a key-matching toleration with the
            # wrong effect would NOT tolerate the pool's NoSchedule taint
            if any(t.tolerates(taint) for t in obj.spec.tolerations):
                continue
            obj.spec.tolerations.append(
                v1.Toleration(
                    key=res_name, operator="Exists", effect="NoSchedule"
                )
            )


class PodNodeSelectorAdmission(AdmissionPlugin):
    """Namespace-pinned node selectors (plugin/pkg/admission/podnodeselector):
    the namespace's scheduler.alpha.kubernetes.io/node-selector annotation
    merges into every pod created there; conflicts are denied."""

    name = "PodNodeSelector"
    ANNOTATION = "scheduler.alpha.kubernetes.io/node-selector"

    def __init__(self, server):
        self.server = server

    def _ns_selector(self, namespace: str) -> dict:
        try:
            ns = self.server.get("namespaces", "", namespace)
        except Exception:
            return {}
        raw = ns.metadata.annotations.get(self.ANNOTATION, "")
        out = {}
        for part in raw.split(","):
            if "=" in part:
                k, _, val = part.partition("=")
                out[k.strip()] = val.strip()
        return out

    def mutate(self, verb: str, resource: str, obj) -> None:
        # create merges the pin; update re-verifies it (a PUT rewriting the
        # selector must not escape the namespace pin before scheduling)
        if verb not in ("create", "update") or resource != "pods":
            return
        sel = self._ns_selector(obj.metadata.namespace)
        for k, val in sel.items():
            if obj.spec.node_selector.get(k, val) != val:
                raise AdmissionDenied(
                    f"pod node selector {k}={obj.spec.node_selector[k]} "
                    f"conflicts with namespace selector {k}={val}"
                )
            obj.spec.node_selector[k] = val


class PodTolerationRestrictionAdmission(AdmissionPlugin):
    """Namespace toleration whitelists (plugin/pkg/admission/
    podtolerationrestriction): a pod may only carry tolerations the
    namespace's whitelist annotation allows (JSON list of Toleration
    objects, reference admission.go:59 NSWLTolerations; no annotation =
    everything allowed).

    Chain position follows AllOrderedPlugins (plugins.go:83): AFTER
    DefaultTolerationSeconds — whose injected not-ready/unreachable
    tolerations are therefore whitelist-checked, exactly like the
    reference's merged-set verification (VerifyAgainstWhitelist over
    pod.Spec.Tolerations post-merge) — and BEFORE
    ExtendedResourceToleration, whose additions escape the check. On
    update, only NEWLY ADDED toleration keys are checked (the stored pod
    legitimately carries chain-injected keys from create)."""

    name = "PodTolerationRestriction"
    # the reference's NSWLTolerations annotation key (admission.go:59); the
    # value is a JSON list of full Toleration objects
    WHITELIST = "scheduler.alpha.kubernetes.io/tolerationsWhitelist"

    def __init__(self, server):
        self.server = server

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb not in ("create", "update") or resource != "pods":
            return
        import json as _json

        try:
            ns = self.server.get("namespaces", "", obj.metadata.namespace)
        except Exception:
            return
        raw = ns.metadata.annotations.get(self.WHITELIST, "")
        if not raw:
            return
        try:
            # reference wire format: a list of Toleration objects; the
            # key is the whitelist axis this build enforces
            allowed = {e.get("key", "") for e in _json.loads(raw)}
        except (ValueError, AttributeError):
            return  # malformed whitelist: fail open like a missing one
        exempt: set = set()
        if verb == "update":
            try:
                cur = self.server.get(
                    "pods", obj.metadata.namespace, obj.metadata.name
                )
                exempt = {t.key for t in cur.spec.tolerations}
            except Exception:
                pass
        for t in obj.spec.tolerations:
            if t.key not in allowed and t.key not in exempt:
                raise AdmissionDenied(
                    f"toleration {t.key!r} is not whitelisted in namespace "
                    f"{obj.metadata.namespace}"
                )


class PVCResizeAdmission(AdmissionPlugin):
    """PVC expansion gate (plugin/pkg/admission/storage/
    persistentvolumeclaimresize): size may only GROW, and only when the
    claim's StorageClass allows expansion."""

    name = "PersistentVolumeClaimResize"

    def __init__(self, server):
        self.server = server

    def validate(self, verb: str, resource: str, obj) -> None:
        if verb != "update" or resource != "persistentvolumeclaims":
            return
        from ..api.resources import parse_quantity

        try:
            cur = self.server.get(
                "persistentvolumeclaims",
                obj.metadata.namespace,
                obj.metadata.name,
            )
        except Exception:
            return
        old_size = parse_quantity(cur.spec.resources.get("storage", 0))
        new_size = parse_quantity(obj.spec.resources.get("storage", 0))
        if new_size == old_size:
            return
        if new_size < old_size:
            raise AdmissionDenied("persistent volume claims may not shrink")
        # the BOUND class decides (the incoming object could swap in an
        # expandable class in the same update to dodge the gate; nothing
        # else enforces storage-class immutability here)
        sc_name = cur.spec.storage_class_name
        if not sc_name:
            raise AdmissionDenied(
                "only claims with an expandable StorageClass may be resized"
            )
        try:
            sc = self.server.get("storageclasses", "", sc_name)
        except Exception:
            raise AdmissionDenied(f"storage class {sc_name!r} not found")
        if not sc.allow_volume_expansion:
            raise AdmissionDenied(
                f"storage class {sc_name!r} does not allow volume expansion"
            )


class RuntimeClassAdmission(AdmissionPlugin):
    """Merge a pod's named RuntimeClass into its spec at create
    (plugin/pkg/admission/runtimeclass/admission.go): the class's overhead
    becomes spec.overhead (a user-supplied CONFLICTING overhead is denied),
    and the class's scheduling nodeSelector/tolerations merge like
    PodNodeSelector does — selector conflicts are denied, tolerations
    append."""

    name = "RuntimeClass"

    def __init__(self, server):
        self.server = server

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "pods":
            return
        rc_name = obj.spec.runtime_class_name
        if not rc_name:
            return
        try:
            # cluster-scoped: the store canonicalizes the namespace to ""
            rc = self.server.get("runtimeclasses", "", rc_name)
        except Exception:
            raise AdmissionDenied(
                f"pod rejected: RuntimeClass {rc_name!r} not found"
            )
        if rc.overhead:
            if obj.spec.overhead and dict(obj.spec.overhead) != dict(rc.overhead):
                raise AdmissionDenied(
                    "pod overhead does not match RuntimeClass "
                    f"{rc_name!r} overhead"
                )
            obj.spec.overhead = dict(rc.overhead)
        sched = rc.scheduling
        if sched is not None:
            for k, val in sched.node_selector.items():
                if obj.spec.node_selector.get(k, val) != val:
                    raise AdmissionDenied(
                        f"pod node selector {k}={obj.spec.node_selector[k]} "
                        f"conflicts with RuntimeClass selector {k}={val}"
                    )
                obj.spec.node_selector[k] = val
            existing = {
                (t.key, t.operator, t.value, t.effect)
                for t in obj.spec.tolerations
            }
            for t in sched.tolerations:
                if (t.key, t.operator, t.value, t.effect) not in existing:
                    obj.spec.tolerations.append(t)


class TaintNodesByConditionAdmission(AdmissionPlugin):
    """New nodes are tainted not-ready at create
    (plugin/pkg/admission/nodetaint/admission.go): the node lifecycle
    controller lifts the taint once the node reports Ready, closing the
    window where pods land on a node whose kubelet has not yet synced."""

    name = "TaintNodesByCondition"
    TAINT_KEY = "node.kubernetes.io/not-ready"

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "nodes":
            return
        # a registration that already reports Ready=True has no
        # kubelet-not-yet-synced window to close (this build's kubelet
        # registers with live status in one write; the reference's
        # two-step register-then-sync is where the window exists)
        if any(
            c.type == v1.NODE_READY and c.status == "True"
            for c in obj.status.conditions
        ):
            return
        if any(t.key == self.TAINT_KEY for t in obj.spec.taints):
            return
        obj.spec.taints.append(
            v1.Taint(self.TAINT_KEY, "", v1.TAINT_NO_SCHEDULE)
        )


class StorageObjectInUseProtectionAdmission(AdmissionPlugin):
    """PVCs/PVs get their protection finalizer at create
    (plugin/pkg/admission/storage/storageobjectinuseprotection): deletion
    then parks until the protection controller confirms no pod uses the
    object (controller/podgc.py PVC/PVProtectionController strips it)."""

    name = "StorageObjectInUseProtection"
    PVC_FINALIZER = "kubernetes.io/pvc-protection"
    PV_FINALIZER = "kubernetes.io/pv-protection"

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create":
            return
        if resource == "persistentvolumeclaims":
            if self.PVC_FINALIZER not in obj.metadata.finalizers:
                obj.metadata.finalizers.append(self.PVC_FINALIZER)
        elif resource == "persistentvolumes":
            if self.PV_FINALIZER not in obj.metadata.finalizers:
                obj.metadata.finalizers.append(self.PV_FINALIZER)


class CertificateSubjectRestrictionAdmission(AdmissionPlugin):
    """CSRs for the kube-apiserver-client signer claiming system:masters
    are denied (plugin/pkg/admission/certificates/subjectrestriction):
    auto-approval flows must never be able to mint a cluster-admin
    credential."""

    name = "CertificateSubjectRestriction"
    SIGNER = "kubernetes.io/kube-apiserver-client"
    BLOCKED_GROUP = "system:masters"

    def validate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "certificatesigningrequests":
            return
        if (
            obj.spec.signer_name == self.SIGNER
            and self.BLOCKED_GROUP in obj.spec.groups
        ):
            raise AdmissionDenied(
                f"use of signer {self.SIGNER} is not allowed for group "
                f"{self.BLOCKED_GROUP}"
            )


def pod_matches_scopes(pod, scopes) -> bool:
    """Quota scope selection (podMatchesScopeFunc): a scoped quota tracks
    and limits only matching pods. BestEffort = no container requests or
    limits at all; Terminating = activeDeadlineSeconds set."""
    for scope in scopes:
        best_effort = not any(
            c.requests or c.limits
            for c in list(pod.spec.containers) + list(pod.spec.init_containers)
        )
        terminating = pod.spec.active_deadline_seconds is not None
        if scope == "BestEffort" and not best_effort:
            return False
        if scope == "NotBestEffort" and best_effort:
            return False
        if scope == "Terminating" and not terminating:
            return False
        if scope == "NotTerminating" and terminating:
            return False
    return True


class NamespaceAutoProvisionAdmission(AdmissionPlugin):
    """Create the namespace on first use (plugin/pkg/admission/namespace/
    autoprovision/admission.go): a namespaced create whose namespace does
    not exist provisions it instead of failing. Default-off in the
    reference's recommended set, like here."""

    name = "NamespaceAutoProvision"

    def __init__(self, server):
        self._server = server

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource in ("namespaces", "events"):
            return
        from ..api.serialization import CLUSTER_SCOPED

        if resource in CLUSTER_SCOPED:
            return
        ns = getattr(obj.metadata, "namespace", "")
        if not ns:
            return
        from ..client.apiserver import AlreadyExists, NotFound

        try:
            self._server.get("namespaces", "", ns)
        except NotFound:
            try:
                self._server.create(
                    "namespaces",
                    v1.Namespace(metadata=v1.ObjectMeta(name=ns, namespace="")),
                )
            except AlreadyExists:
                pass


class NamespaceExistsAdmission(AdmissionPlugin):
    """Reject namespaced creates into a namespace that does not exist
    (plugin/pkg/admission/namespace/exists/admission.go)."""

    name = "NamespaceExists"

    def __init__(self, server):
        self._server = server

    def validate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource in ("namespaces", "events"):
            return
        from ..api.serialization import CLUSTER_SCOPED

        if resource in CLUSTER_SCOPED:
            return
        ns = getattr(obj.metadata, "namespace", "")
        if not ns:
            return
        from ..client.apiserver import NotFound

        try:
            self._server.get("namespaces", "", ns)
        except NotFound:
            raise AdmissionDenied(f"namespace {ns!r} does not exist")


class SecurityContextDenyAdmission(AdmissionPlugin):
    """Deny pods that customize the security-sensitive SecurityContext
    fields (plugin/pkg/admission/securitycontext/scdeny/admission.go —
    the pre-PSP hard gate). Default-off, for clusters without PSP."""

    name = "SecurityContextDeny"

    def validate(self, verb: str, resource: str, obj) -> None:
        if resource != "pods" or verb not in ("create", "update"):
            return
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            sc = c.security_context
            if sc is None:
                continue
            if sc.privileged or sc.run_as_user is not None:
                raise AdmissionDenied(
                    "SecurityContextDeny: securityContext.privileged and "
                    "runAsUser are forbidden"
                )


class LimitPodHardAntiAffinityTopologyAdmission(AdmissionPlugin):
    """Deny required pod anti-affinity with a topology key other than
    kubernetes.io/hostname (plugin/pkg/admission/antiaffinity): a
    zone-wide REQUIRED anti-affinity term lets one tenant fence whole
    failure domains from everyone else."""

    name = "LimitPodHardAntiAffinityTopology"
    HOSTNAME = "kubernetes.io/hostname"

    def validate(self, verb: str, resource: str, obj) -> None:
        if resource != "pods" or verb not in ("create", "update"):
            return
        aff = obj.spec.affinity
        if aff is None or aff.pod_anti_affinity is None:
            return
        for term in aff.pod_anti_affinity.required:
            if term.topology_key != self.HOSTNAME:
                raise AdmissionDenied(
                    "affinity.podAntiAffinity.requiredDuringScheduling... "
                    f"topologyKey must be {self.HOSTNAME} "
                    f"(got {term.topology_key!r})"
                )


class EventRateLimitAdmission(AdmissionPlugin):
    """Server-scope token bucket over event writes (plugin/pkg/admission/
    eventratelimit): an event storm (crash-looping workload, hot failure
    path) must not starve the API server for every other client. Over
    budget => deny (the recorder treats event writes as best-effort and
    drops). Default-off, like the reference."""

    name = "EventRateLimit"

    def __init__(self, qps: float = 50.0, burst: int = 100):
        import threading
        import time as _time

        self._qps = float(qps)
        self._burst = float(burst)
        self._tokens = float(burst)
        self._t_last = _time.monotonic()
        self._mu = threading.Lock()

    def validate(self, verb: str, resource: str, obj) -> None:
        if resource != "events" or verb not in ("create", "update"):
            return
        import time as _time

        with self._mu:
            now = _time.monotonic()
            self._tokens = min(
                self._burst, self._tokens + (now - self._t_last) * self._qps
            )
            self._t_last = now
            if self._tokens < 1.0:
                raise AdmissionDenied(
                    "EventRateLimit: server event budget exhausted"
                )
            self._tokens -= 1.0


class OwnerReferencesPermissionEnforcementAdmission(AdmissionPlugin):
    """Setting ownerReferences[].blockOwnerDeletion makes the GC hold the
    OWNER's deletion until this dependent is gone — so it requires
    delete (finalizer-grade) permission on that owner (plugin/pkg/
    admission/gc/gc_admission.go). Gates the DELTA like the reference:
    only refs NEWLY gaining the bit are checked, so an unrelated update
    (label patch) by a user without owner-delete permission still lands
    on an already-protected object. In-process callers (no request user)
    are unrestricted, like loopback cluster-admin."""

    name = "OwnerReferencesPermissionEnforcement"

    def __init__(self, authorizer, server=None):
        self._authz = authorizer
        self._server = server

    def _blocking(self, obj) -> dict:
        return {
            (r.kind, r.name): r
            for r in (getattr(obj.metadata, "owner_references", None) or [])
            if getattr(r, "block_owner_deletion", False)
        }

    def validate(self, verb: str, resource: str, obj) -> None:
        if verb not in ("create", "update"):
            return
        user = request_user.get()
        if user is None:
            return
        new_blocking = self._blocking(obj)
        if not new_blocking:
            return
        if verb == "update" and self._server is not None:
            from ..client.apiserver import NotFound

            try:
                old = self._server.get(
                    resource, obj.metadata.namespace, obj.metadata.name
                )
                for key in self._blocking(old):
                    new_blocking.pop(key, None)  # already protected
            except NotFound:
                pass
        from ..api.serialization import KIND_TO_RESOURCE

        for (kind, name), ref in new_blocking.items():
            owner_res = KIND_TO_RESOURCE.get(kind, kind.lower() + "s")
            if not self._authz.authorize(
                user, "delete", owner_res, obj.metadata.namespace, name
            ):
                raise AdmissionDenied(
                    f"cannot set blockOwnerDeletion on {kind} "
                    f"{name!r}: user {user.name!r} may not delete it"
                )


class DefaultIngressClassAdmission(AdmissionPlugin):
    """Stamp the cluster-default IngressClass onto classless Ingresses at
    create (plugin/pkg/admission/defaultingressclass — the 1.18
    networking analogue of DefaultStorageClass). Multiple defaults =>
    deny, like the reference."""

    name = "DefaultIngressClass"
    DEFAULT_ANN = "ingressclass.kubernetes.io/is-default-class"

    def __init__(self, server):
        self._server = server

    def mutate(self, verb: str, resource: str, obj) -> None:
        if verb != "create" or resource != "ingresses":
            return
        if obj.spec.ingress_class_name is not None:
            return
        defaults = [
            ic
            for ic in self._server.list("ingressclasses")[0]
            if ic.metadata.annotations.get(self.DEFAULT_ANN) == "true"
        ]
        if not defaults:
            return
        if len(defaults) > 1:
            raise AdmissionDenied(
                "multiple default IngressClasses marked: "
                + ", ".join(sorted(ic.metadata.name for ic in defaults))
            )
        obj.spec.ingress_class_name = defaults[0].metadata.name


class CertificateApprovalAdmission(AdmissionPlugin):
    """Approving a CSR requires 'approve' permission on the signer
    (plugin/pkg/admission/certificates/approval): RBAC gates WHO may
    bless requests for WHICH signerName. Gates the DELTA like the
    reference (only updates that CHANGE the approval conditions) — a
    signer identity writing status.certificate on an already-approved
    CSR must not need 'approve' — and ALSO gates create: a CSR born
    with an Approved condition would otherwise bypass the gate entirely
    and mint a live credential via the CSR token index."""

    name = "CertificateApproval"

    def __init__(self, authorizer, server=None):
        self._authz = authorizer
        self._server = server

    @staticmethod
    def _approval_state(obj) -> tuple:
        return tuple(
            sorted(
                (c.type, c.status)
                for c in obj.status.conditions
                if c.type in ("Approved", "Denied")
            )
        )

    def validate(self, verb: str, resource: str, obj) -> None:
        if resource != "certificatesigningrequests" or verb not in (
            "create", "update",
        ):
            return
        user = request_user.get()
        if user is None:
            return
        new_state = self._approval_state(obj)
        if not new_state:
            return
        if verb == "update" and self._server is not None:
            from ..client.apiserver import NotFound

            try:
                old = self._server.get(resource, "", obj.metadata.name)
                if self._approval_state(old) == new_state:
                    return  # approval unchanged: not an approval write
            except NotFound:
                pass
        if not self._authz.authorize(
            user, "approve", "signers", "", obj.spec.signer_name
        ):
            raise AdmissionDenied(
                f"user {user.name!r} may not approve requests for signer "
                f"{obj.spec.signer_name!r}"
            )


class CertificateSigningAdmission(AdmissionPlugin):
    """Issuing the certificate (writing status.certificate) requires
    'sign' permission on the signer (plugin/pkg/admission/certificates/
    signing). Delta-gated like approval, and create-gated for the same
    reason: a CSR created WITH a certificate would otherwise inject a
    live credential without anyone holding 'sign'."""

    name = "CertificateSigning"

    def __init__(self, authorizer, server=None):
        self._authz = authorizer
        self._server = server

    def validate(self, verb: str, resource: str, obj) -> None:
        if resource != "certificatesigningrequests" or verb not in (
            "create", "update",
        ):
            return
        user = request_user.get()
        if user is None:
            return
        if not obj.status.certificate:
            return
        if verb == "update" and self._server is not None:
            from ..client.apiserver import NotFound

            try:
                old = self._server.get(resource, "", obj.metadata.name)
                if old.status.certificate == obj.status.certificate:
                    return  # certificate unchanged: not a signing write
            except NotFound:
                pass
        if not self._authz.authorize(
            user, "sign", "signers", "", obj.spec.signer_name
        ):
            raise AdmissionDenied(
                f"user {user.name!r} may not sign requests for signer "
                f"{obj.spec.signer_name!r}"
            )
