"""Dynamic admission webhooks.

Reference: staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook — the
apiserver POSTs an AdmissionReview to every matching webhook from the
Mutating/ValidatingWebhookConfiguration objects; mutating responses may
carry a JSONPatch over the object's wire form; a webhook that cannot be
reached fails open or closed per its failurePolicy. This build speaks the
same AdmissionReview shape over plain HTTP (service references are not
modeled; client_config carries a URL).

Wire shapes:
  request:  {"kind": "AdmissionReview", "request": {"uid", "resource",
             "operation" (CREATE/UPDATE/DELETE), "object": {...}}}
  response: {"response": {"allowed": bool, "status": {"message": str},
             "patchType": "JSONPatch", "patch": base64(json list)}}
"""

from __future__ import annotations

import base64
import json
import logging
import urllib.error
import urllib.request
import uuid
from typing import Any, List, Optional

from ..api import serialization
from .auth import AdmissionDenied, AdmissionPlugin

logger = logging.getLogger("kubernetes_tpu.apiserver.webhook")


def apply_json_patch(doc: Any, patch: List[dict]) -> Any:
    """Minimal RFC 6902: add / replace / remove over dicts and lists
    (the reference accepts exactly JSONPatch from mutating webhooks)."""
    for op in patch:
        path = [p.replace("~1", "/").replace("~0", "~") for p in op["path"].lstrip("/").split("/")]
        parent = doc
        for seg in path[:-1]:
            parent = parent[int(seg) if isinstance(parent, list) else seg]
        leaf = path[-1]
        kind = op["op"]
        if isinstance(parent, list):
            idx = len(parent) if leaf == "-" else int(leaf)
            if kind == "add":
                parent.insert(idx, op["value"])
            elif kind == "replace":
                parent[idx] = op["value"]
            elif kind == "remove":
                del parent[idx]
            else:
                raise ValueError(f"unsupported JSONPatch op {kind!r}")
        else:
            if kind == "add" or kind == "replace":
                parent[leaf] = op["value"]
            elif kind == "remove":
                parent.pop(leaf, None)
            else:
                raise ValueError(f"unsupported JSONPatch op {kind!r}")
    return doc


def _matches(hook, resource: str, verb: str) -> bool:
    op = {"create": "CREATE", "update": "UPDATE", "delete": "DELETE"}.get(
        verb, verb.upper()
    )
    for rule in hook.rules or []:
        ops_ok = "*" in rule.operations or op in rule.operations
        res_ok = "*" in rule.resources or resource in rule.resources
        if ops_ok and res_ok:
            return True
    return not hook.rules  # no rules = match everything (defaulted "*")


class WebhookUnavailable(Exception):
    """Transport failure OR unusable response — both are 'the webhook did
    not answer' for failurePolicy purposes."""


def _call(hook, resource: str, verb: str, obj) -> dict:
    review = {
        "kind": "AdmissionReview",
        "request": {
            "uid": str(uuid.uuid4()),
            "resource": resource,
            "operation": verb.upper(),
            "object": serialization.encode(obj) if obj is not None else None,
        },
    }
    req = urllib.request.Request(
        hook.client_config.url,
        data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=hook.timeout_seconds) as r:
            body = r.read()
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise WebhookUnavailable(str(e)) from None
    try:
        resp = json.loads(body or b"{}").get("response", {})
        if not isinstance(resp, dict):
            raise ValueError(f"response is {type(resp).__name__}, not object")
        return resp
    except (ValueError, AttributeError) as e:
        # HTML error page, truncated body, wrong shape: same treatment as
        # unreachable — failurePolicy decides
        raise WebhookUnavailable(f"malformed AdmissionReview response: {e}") from None


class _WebhookAdmission(AdmissionPlugin):
    """Shared dispatch; subclasses pick the configuration resource and
    whether patches apply."""

    config_resource = ""
    mutating = False

    def __init__(self, server):
        self.server = server

    # BOTH configuration kinds are exempt from BOTH plugins: if each kind
    # were only exempt from its own plugin, two broken failurePolicy=Fail
    # configs could veto each other's deletion and lock the cluster out of
    # every write forever (upstream exempts the admissionregistration
    # group for the same reason)
    EXEMPT_RESOURCES = frozenset(
        {"mutatingwebhookconfigurations", "validatingwebhookconfigurations"}
    )

    def _dispatch(self, verb: str, resource: str, obj) -> None:
        if resource in self.EXEMPT_RESOURCES:
            return
        try:
            configs, _ = self.server.list(self.config_resource)
        except Exception:
            return
        for cfg in configs:
            for hook in cfg.webhooks:
                if not _matches(hook, resource, verb):
                    continue
                try:
                    resp = _call(hook, resource, verb, obj)
                except WebhookUnavailable as e:
                    if hook.failure_policy == "Ignore":
                        logger.warning(
                            "webhook %s unavailable (ignored): %s", hook.name, e
                        )
                        continue
                    raise AdmissionDenied(
                        f"webhook {hook.name!r} unavailable and failurePolicy"
                        f"=Fail: {e}"
                    ) from None
                if not resp.get("allowed", False):
                    msg = (resp.get("status") or {}).get("message", "denied")
                    raise AdmissionDenied(
                        f"admission webhook {hook.name!r} denied the request: {msg}"
                    )
                patch_b64 = resp.get("patch")
                if self.mutating and patch_b64 and obj is not None:
                    try:
                        patch = json.loads(base64.b64decode(patch_b64))
                        doc = apply_json_patch(
                            serialization.encode(obj), patch
                        )
                        new_obj = serialization.decode(resource, doc)
                    except Exception as e:
                        raise AdmissionDenied(
                            f"webhook {hook.name!r} returned an unusable "
                            f"patch: {e}"
                        ) from None
                    # immutable-metadata guard (the reference re-validates
                    # object meta after mutation): a patch renaming the
                    # object would silently change its store identity (the
                    # key is derived AFTER admission), and a patched
                    # resourceVersion would subvert the conflict check
                    old_m, new_m = obj.metadata, new_obj.metadata
                    for f in ("name", "namespace", "uid", "resource_version"):
                        if getattr(new_m, f) != getattr(old_m, f):
                            raise AdmissionDenied(
                                f"webhook {hook.name!r} patch mutates "
                                f"immutable metadata.{f}"
                            )
                    # status is not admittable content either: keep ours
                    if hasattr(obj, "status"):
                        new_obj.status = obj.status
                    # graft the mutated state onto the live object the
                    # admission chain carries forward
                    obj.__dict__.update(new_obj.__dict__)


class MutatingWebhookAdmission(_WebhookAdmission):
    name = "MutatingAdmissionWebhook"
    config_resource = "mutatingwebhookconfigurations"
    mutating = True

    def mutate(self, verb: str, resource: str, obj) -> None:
        self._dispatch(verb, resource, obj)


class ValidatingWebhookAdmission(_WebhookAdmission):
    name = "ValidatingAdmissionWebhook"
    config_resource = "validatingwebhookconfigurations"
    mutating = False

    def validate(self, verb: str, resource: str, obj) -> None:
        self._dispatch(verb, resource, obj)
