"""REST client: the APIServer interface over HTTP.

The typed-clientset role of client-go (staging/src/k8s.io/client-go
kubernetes.Interface): every component that takes an `APIServer` (scheduler,
informers, controllers, kubectl) can take a RESTClient instead and run
against a remote API process. Watch uses the newline-delimited JSON stream
and feeds a local Watcher, exactly how Reflector consumes watch responses
(client-go/tools/cache/reflector.go:210).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, List, Optional, Tuple

from ..api import serialization as codec
from ..client.apiserver import (
    AlreadyExists,
    Conflict,
    Expired,
    LeaderFenced,
    NotFound,
    NotPrimary,
)
from ..client.leaderelection import FENCE_HEADER, fence_header_value
from ..runtime.consensus import DegradedWrites, QuorumLost
from ..runtime.watch import Event, Watcher
from ..utils.tracing import TRACE_HEADER, trace_for_binding


class RESTClient:
    """degraded_retries / degraded_retry_cap_s: a fast-fail 503 from a
    degraded read-only store (reason "Degraded": the write gate refused
    BEFORE applying anything, runtime/consensus.py) is transparently
    retried — the client honors the Retry-After header (capped) for up
    to degraded_retries attempts before surfacing DegradedWrites. A
    "WriteQuorumLost" 503 (the write applied locally but missed quorum:
    outcome unknown) surfaces as QuorumLost without replay, and a 503
    with no Retry-After (fenced ex-primary) surfaces as NotPrimary."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        degraded_retries: int = 3,
        degraded_retry_cap_s: float = 2.0,
    ):
        self.base = base_url.rstrip("/")
        self.timeout = timeout
        self.degraded_retries = degraded_retries
        self.degraded_retry_cap_s = degraded_retry_cap_s
        self._headers: dict = {}

    # -- plumbing ------------------------------------------------------------

    def _url(self, resource: str, namespace: str, name: str = "") -> str:
        # empty namespace = cluster-scoped path (the store keys by the
        # object's own namespace either way)
        if namespace:
            path = f"/api/v1/namespaces/{namespace}/{resource}"
        else:
            path = f"/api/v1/{resource}"
        if name:
            path += f"/{name}"
        return self.base + path

    def get_text(self, resource: str, namespace: str, name: str) -> str:
        """Plain-text GET of a subresource (pods/{name}/log): same URL
        scheme, headers, timeout, and HTTP error mapping as the JSON
        path (get_raw is the JSON variant for aggregated API paths)."""
        req = urllib.request.Request(
            self._url(resource, namespace, name), headers=self._headers
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read().decode()
        except urllib.error.HTTPError as e:
            msg = e.read().decode() or str(e)
            if e.code == 404:
                raise NotFound(msg) from None
            raise RuntimeError(msg) from None

    def post_text(self, resource: str, namespace: str, name: str, body: dict) -> str:
        """Plain-text POST to a subresource (pods/{name}/exec): same URL
        scheme, headers, timeout, and error mapping as the JSON path."""
        req = urllib.request.Request(
            self._url(resource, namespace, name),
            data=json.dumps(body).encode(),
            method="POST",
            headers={"Content-Type": "application/json", **self._headers},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read().decode()
        except urllib.error.HTTPError as e:
            payload = {}
            try:
                payload = json.loads(e.read().decode() or "{}")
            except Exception:
                pass
            msg = payload.get("message", str(e))
            if e.code == 404:
                raise NotFound(msg) from None
            raise RuntimeError(msg) from None

    def _request(
        self,
        method: str,
        url: str,
        body: Optional[dict] = None,
        headers: Optional[dict] = None,
    ) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        attempt = 0
        while True:
            req = urllib.request.Request(
                url,
                data=data,
                method=method,
                headers={
                    "Content-Type": "application/json",
                    **self._headers,
                    **(headers or {}),
                },
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return json.loads(resp.read().decode() or "{}")
            except urllib.error.HTTPError as e:
                payload = {}
                try:
                    payload = json.loads(e.read().decode() or "{}")
                except Exception:
                    pass
                msg = payload.get("message", str(e))
                if e.code == 404:
                    raise NotFound(msg) from None
                if e.code == 409:
                    reason = payload.get("reason", "")
                    if reason == "AlreadyExists":
                        raise AlreadyExists(msg) from None
                    if reason == "LeaderFenced":
                        # leadership fence rejection: the caller's lease
                        # grant was superseded — non-retryable (the caller
                        # is not the leader anymore), nothing was applied
                        raise LeaderFenced(msg) from None
                    raise Conflict(msg) from None
                if e.code == 503:
                    # three distinct 503 contracts (rest.py):
                    #   "Degraded"        gate refused before applying:
                    #                     replaying is safe — honor
                    #                     Retry-After (capped) and retry;
                    #                     the store re-opens the moment
                    #                     followers catch the commit up
                    #   "WriteQuorumLost" THIS request applied locally but
                    #                     missed quorum: outcome unknown —
                    #                     a blind replay would 409 against
                    #                     its own first attempt; surface it
                    #   no Retry-After    fenced primary (permanent for
                    #                     that process): never hammer it —
                    #                     callers must re-discover the
                    #                     leader
                    reason = payload.get("reason", "")
                    retry_after = e.headers.get("Retry-After")
                    if retry_after is None:
                        raise NotPrimary(msg) from None
                    if reason == "WriteQuorumLost":
                        raise QuorumLost(msg) from None
                    if attempt < self.degraded_retries:
                        attempt += 1
                        try:
                            delay = float(retry_after)
                        except ValueError:
                            delay = 0.5
                        time.sleep(min(delay, self.degraded_retry_cap_s))
                        continue
                    raise DegradedWrites(msg) from None
                raise

    def get_raw(self, path: str) -> dict:
        """GET an arbitrary API path (aggregated APIs like metrics.k8s.io)."""
        return self._request("GET", self.base + path)

    # -- the APIServer interface ---------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        out = self._request(
            "POST",
            self._url(kind, obj.metadata.namespace),
            codec.encode(obj),
        )
        return codec.decode(kind, out)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        out = self._request("GET", self._url(kind, namespace, name))
        return codec.decode(kind, out)

    def update(self, kind: str, obj: Any, check_version: bool = True) -> Any:
        out = self._request(
            "PUT",
            self._url(kind, obj.metadata.namespace, obj.metadata.name),
            codec.encode(obj),
        )
        return codec.decode(kind, out)

    def guaranteed_update(
        self, kind: str, namespace: str, name: str, mutate: Callable[[Any], Any]
    ) -> Any:
        while True:
            cur = self.get(kind, namespace, name)
            new = mutate(cur)
            if new is None:
                return cur
            try:
                return self.update(kind, new)
            except Conflict:
                continue

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        return self._request("DELETE", self._url(kind, namespace, name))

    def list(self, kind: str, namespace: Optional[str] = None) -> Tuple[List[Any], int]:
        url = self._url(kind, namespace or "")
        out = self._request("GET", url)
        rv = int(out.get("metadata", {}).get("resourceVersion", 0))
        items = [codec.decode(kind, item) for item in out.get("items", [])]
        if namespace is not None:
            items = [o for o in items if o.metadata.namespace == namespace]
        return items, rv

    def watch(self, kind: str, from_version: int = 0) -> Watcher:
        w = Watcher()
        url = self._url(kind, "") + f"?watch=1&resourceVersion={from_version}"
        # open SYNCHRONOUSLY so a 410 Gone ("resourceVersion too old")
        # surfaces to the caller as Expired — informers re-list on it; a
        # silent pump-thread death would hand them a gapped stream. Other
        # connection errors keep the old contract (a stopped watcher, not
        # an exception), and the connect itself is bounded by the client
        # timeout; the STREAM then clears the socket timeout (an idle but
        # healthy watch must not be killed by a read timeout).
        req = urllib.request.Request(url, headers=dict(self._headers))
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            if e.code == 410:
                raise Expired(e.read().decode() or "resourceVersion too old") from None
            w.stop()
            return w
        except (urllib.error.URLError, OSError):
            w.stop()
            return w
        try:
            resp.fp.raw._sock.settimeout(None)  # stream: no read timeout
        except AttributeError:
            pass  # CPython internals moved: 30s idle kills the stream,
            # and the consumer's relist path recovers

        def pump():
            from ..runtime.watch import BOOKMARK

            try:
                with resp:
                    for line in resp:
                        if w.stopped:
                            break
                        line = line.strip()
                        if not line:
                            continue
                        msg = json.loads(line)
                        if msg["type"] == BOOKMARK:
                            # rv-only progress notify from the watch cache
                            # (idle heartbeat / window keep-alive): carry
                            # the rv through; informers advance their
                            # resume position on it, other consumers skip
                            # unknown event types
                            rv = int(
                                (msg.get("object") or {})
                                .get("metadata", {})
                                .get("resourceVersion", 0)
                            )
                            from .cacher import bookmark_object

                            w.push(Event(BOOKMARK, bookmark_object(kind, rv), rv))
                            continue
                        obj = codec.decode(kind, msg["object"])
                        w.push(
                            Event(
                                msg["type"],
                                obj,
                                obj.metadata.resource_version,
                            )
                        )
            except Exception:
                pass
            finally:
                w.stop()

        threading.Thread(target=pump, daemon=True).start()
        return w

    @staticmethod
    def _fence_headers(fence) -> Optional[dict]:
        return (
            {FENCE_HEADER: fence_header_value(fence)}
            if fence is not None
            else None
        )

    @staticmethod
    def _bind_headers(base: Optional[dict], binding) -> Optional[dict]:
        """Fence headers plus trace-context propagation: the pod's trace
        id (minted at queue admission in THIS process) rides the
        X-Trace-Context header so the store process stamps its apply —
        or its LeaderFenced rejection — under the same identity."""
        tid = trace_for_binding(binding)
        if not tid:
            return base
        return {**(base or {}), TRACE_HEADER: tid}

    @staticmethod
    def _classify_bind_transport(e: Exception) -> DegradedWrites:
        """Map a transport-level failure of a /binding POST onto the bind
        outcome taxonomy. A refused connect means the request never
        reached the server — retryable, same contract as a degraded-store
        refusal (nothing applied, safe to replay verbatim). ANYTHING else
        (timeout, reset, EOF-without-response, half-delivered body) means
        the request MAY have been processed with its response lost: the
        one honest classification is QuorumLost — the caller must read
        the pod back before any retry, never blindly replay (a netchaos
        blackhole is exactly this shape: write applied, ack dropped)."""
        cause = getattr(e, "reason", e)  # URLError wraps the socket error
        if isinstance(cause, ConnectionRefusedError):
            return DegradedWrites(f"api server unreachable: {cause}")
        return QuorumLost(f"bind outcome unknown (transport failure: {e})")

    def bind_pod(self, binding, fence=None) -> None:
        """Single-pod binding subresource (DefaultBinder's surface; the
        bulk bind_pods below shares the wire path). Raises on failure so
        the bind plugin's error handling fires like the in-process store.
        fence: optional BindFence, attached as the X-Leadership-Fence
        header; the server rejects with LeaderFenced when superseded."""
        try:
            self._request(
                "POST",
                self.base
                + f"/api/v1/namespaces/{binding.pod_namespace}/pods/"
                + f"{binding.pod_name}/binding",
                codec.encode(binding),
                headers=self._bind_headers(self._fence_headers(fence), binding),
            )
        except (
            LeaderFenced,
            DegradedWrites,
            NotFound,
            Conflict,
            urllib.error.HTTPError,
        ):
            raise
        except OSError as e:
            raise self._classify_bind_transport(e) from e

    def bind_pods(self, bindings, fence=None) -> list:
        """Per-binding error list (None = bound). Retryable degraded-store
        refusals come back as the EXCEPTION OBJECT (DegradedWrites /
        QuorumLost), not a string — the scheduler's ride-through layer
        parks those placements instead of failing them. After the first
        degraded refusal the remaining bindings are not attempted (each
        would burn its own client-side retry budget against a store that
        just said "read-only"); they get a fresh DegradedWrites — none of
        them was applied, so replaying them later is safe. Transport
        failures classify through _classify_bind_transport: refused
        connect = retryable DegradedWrites, anything after the connect =
        QuorumLost (outcome unknown, read back before retrying).

        fence: the leadership fencing token (BindFence), attached to every
        binding POST as the X-Leadership-Fence header and validated by the
        server against the live lease under the bind lock. A LeaderFenced
        rejection RAISES (mirroring the in-process store's whole-batch
        reject): the remaining bindings are not attempted — the caller is
        not the leader anymore. Bindings that already landed in this batch
        were applied while the grant was still valid and stay applied
        exactly once; the new leader's adoption pass reads them back."""
        errors = []
        degraded: Optional[DegradedWrites] = None
        fence_headers = self._fence_headers(fence)  # one token per batch
        for b in bindings:
            if degraded is not None:
                errors.append(
                    DegradedWrites(f"not attempted: {degraded}")
                )
                continue
            try:
                self._request(
                    "POST",
                    self.base
                    + f"/api/v1/namespaces/{b.pod_namespace}/pods/"
                    + f"{b.pod_name}/binding",
                    codec.encode(b),
                    headers=self._bind_headers(fence_headers, b),
                )
                errors.append(None)
            except LeaderFenced:
                # deposed mid-batch: nothing further may apply. Raise like
                # the in-process store's atomic whole-batch reject; the
                # scheduler's _on_fenced_binds drops every placement (the
                # already-landed prefix is re-adopted from informer state)
                raise
            except QuorumLost as e:
                # THIS binding applied remotely but missed quorum: its
                # outcome is unknown — surface the exception itself so the
                # caller reads the pod back before any retry
                errors.append(e)
                degraded = e
            except DegradedWrites as e:
                errors.append(e)
                degraded = e
            except (NotFound, Conflict) as e:
                # typed like the in-process store's error list, so the
                # scheduler's reconciler branches identically over REST
                errors.append(e)
            except urllib.error.HTTPError as e:
                # a non-2xx the taxonomy doesn't know (500, 403, ...):
                # the server DID answer — a known refusal, not unknown
                errors.append(str(e))
            except OSError as e:
                # transport failure (partition, reset, blackholed ack):
                # classify, then stop attempting the rest of the batch —
                # the network just proved undeliverable and each further
                # attempt would burn its own timeout
                err = self._classify_bind_transport(e)
                errors.append(err)
                degraded = err
            except Exception as e:
                errors.append(str(e))
        return errors


class AuthRESTClient(RESTClient):
    """RESTClient sending a bearer token (kubeconfig user credentials)."""

    def __init__(self, base_url: str, token: str, timeout: float = 30.0):
        super().__init__(base_url, timeout=timeout)
        self._headers["Authorization"] = f"Bearer {token}"
