"""REST client: the APIServer interface over HTTP.

The typed-clientset role of client-go (staging/src/k8s.io/client-go
kubernetes.Interface): every component that takes an `APIServer` (scheduler,
informers, controllers, kubectl) can take a RESTClient instead and run
against a remote API process. Watch uses the newline-delimited JSON stream
(or the length-prefixed binary watch codec when the server speaks it —
apiserver/watchcodec.py) and feeds a local Watcher, exactly how Reflector
consumes watch responses (client-go/tools/cache/reflector.go:210).

Transport: a bounded per-host pool of persistent HTTP/1.1 connections
(client-go's http.Transport keep-alive role). PERFORMANCE.md round-11
measured accept+connect dominating the REST bind cost when every request
opened a fresh TCP connection; `_request`, watch streams, and bind POSTs
all draw from the same pool now. A pooled socket the server closed while
idle is detected at acquire time (pending FIN/EOF) and discarded; the
narrow race where the close lands mid-request reopens ONCE for
idempotent GETs only — a reused connection that dies anywhere in a
bind POST (send or response phase; see the _RETRYABLE_METHODS note for
why a send-phase death is NOT proof of non-delivery) classifies as
QuorumLost through `_classify_bind_transport`: outcome unknown, read
back before any retry, never a blind replay.
"""

from __future__ import annotations

import http.client
import io
import json
import select
import socket
import threading
import time
import urllib.error
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from ..api import serialization as codec
from ..client.apiserver import (
    AlreadyExists,
    Conflict,
    Expired,
    LeaderFenced,
    NotFound,
    NotPrimary,
    TooManyRequests,
)
from ..client.leaderelection import FENCE_HEADER, fence_header_value
from ..runtime.consensus import (
    DegradedWrites,
    DiskFailed,
    DiskPressure,
    QuorumLost,
)
from ..runtime.watch import BOOKMARK, Event, Watcher
from ..utils.metrics import metrics
from ..utils.tracing import TRACE_HEADER, trace_for_binding

# connection-pool observability (SIGUSR2 "serving / REST client" section;
# the serving A/B reads opened vs reused to prove the pool is actually on
# the hot path): opened counts real HTTPConnection creations, reused
# counts requests served on a pooled socket, pool_size is idle sockets
COUNTER_CONN_OPENED = "restclient_connections_opened_total"
COUNTER_CONN_REUSED = "restclient_connections_reused_total"
GAUGE_POOL_SIZE = "restclient_pool_size"
# watch-pump resumes: the pump transparently reconnects a died stream at
# its last delivered rv (labels: reason = error|eof|truncated) — through
# a balancer this is what lets a watcher ride a frontend death with zero
# informer-visible relists (the replacement frontend's cache replays)
COUNTER_WATCH_RECONNECTS = "restclient_watch_reconnects_total"  # {reason}
# HTTP/1.1 pipelining (idempotent GETs only): requests sent back-to-back
# on one pooled connection, responses drained in order; requeues count
# requests pushed back after a mid-pipeline transport error (labels:
# first_in_flight = the one request that classified as retryable,
# unattempted = requests behind it that were never answered)
COUNTER_PIPELINED = "restclient_pipelined_requests_total"
COUNTER_PIPELINE_REQUEUES = "restclient_pipeline_requeues_total"  # {reason}

# replay safety: methods whose transparent one-shot retry after a reused
# connection died cannot double-apply. Deliberately NOT send-phase-gated
# for writes: an EPIPE mid-send proves an RST arrived between our two
# writes, not that the peer ignored the bytes it already had — a proxy
# (or server) killing the connection BECAUSE of this request looks
# identical to an idle close racing it. Idle-closed pooled sockets are
# instead caught at acquire time (pending-EOF check), which is where the
# no-double-send guarantee for binds actually lives.
_RETRYABLE_METHODS = ("GET", "HEAD")

_WATCH_RESUME_ATTEMPTS = 4


class _NoDelayHTTPConnection(http.client.HTTPConnection):
    """HTTPConnection with Nagle disabled. http.client writes the header
    block and the body as two separate sends; with Nagle on, the second
    small write stalls behind the peer's delayed ACK (~40 ms) — measured
    as the DOMINANT cost of a pooled bind POST on loopback. TCP_NODELAY
    turns a bind round trip from a delayed-ACK artifact into an actual
    network round trip."""

    def connect(self):
        super().connect()
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass


class _NoDelayHTTPSConnection(http.client.HTTPSConnection):
    """TLS variant: the Nagle/delayed-ACK stall applies identically under
    TLS (the record layer rides the same two-write pattern)."""

    def connect(self):
        super().connect()
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass


class _NoCloseReader:
    """A file proxy whose close() is a no-op: HTTPResponse closes its fp
    once a response is fully read, but pipelined responses SHARE one
    buffered reader (a per-response makefile could prefetch the next
    response's bytes and lose them) — the window owns the close."""

    __slots__ = ("_fp",)

    def __init__(self, fp):
        self._fp = fp

    def close(self):
        pass

    def flush(self):
        pass

    def __getattr__(self, name):
        return getattr(self._fp, name)


def _tls_client_context(tls_ca: Optional[str]):
    """Client-side TLS context: verify against the given CA bundle, or —
    the fleet-internal default, where the relay/frontend certs are
    self-signed test material — encrypt without verification (the bench
    measures handshake+record crypto cost either way)."""
    import ssl

    if tls_ca:
        return ssl.create_default_context(cafile=tls_ca)
    ctx = ssl._create_unverified_context()
    return ctx


def _new_connection(
    scheme: str, host: str, port: int, timeout: float,
    tls_ctx=None, tls_ca: Optional[str] = None,
) -> http.client.HTTPConnection:
    if scheme == "https":
        return _NoDelayHTTPSConnection(
            host, port, timeout=timeout,
            context=tls_ctx or _tls_client_context(tls_ca),
        )
    return _NoDelayHTTPConnection(host, port, timeout=timeout)


class HTTPConnectionPool:
    """Bounded per-host idle pool of persistent http.client connections.

    acquire() pops an idle connection for the host (discarding stale ones
    the server closed while they sat idle — a readable socket with a
    pending EOF), else hands out a fresh one; release() returns a healthy
    keep-alive connection; discard() closes one that died or was consumed
    by a stream. Thread-safe; the pool never blocks a caller waiting for
    a slot — the bound is on IDLE sockets kept, not on concurrency."""

    def __init__(
        self,
        max_idle_per_host: int = 8,
        timeout: float = 30.0,
        tls_ca: Optional[str] = None,
    ):
        self.max_idle_per_host = max_idle_per_host
        self.timeout = timeout
        self._lock = threading.Lock()
        # keyed by (scheme, host, port): an https socket is never handed
        # to a plaintext request and vice versa
        self._idle: Dict[
            Tuple[str, str, int], List[http.client.HTTPConnection]
        ] = {}
        self._idle_count = 0
        self._tls_ca = tls_ca
        self._tls_ctx = None  # built lazily on the first https acquire

    @staticmethod
    def _stale(conn: http.client.HTTPConnection) -> bool:
        """An idle keep-alive socket must have NOTHING to say. Readable
        means the server closed it (pending FIN) or broke protocol
        (unsolicited bytes) — either way it cannot carry a request."""
        sock = conn.sock
        if sock is None:
            return True
        try:
            readable, _, errored = select.select([sock], [], [sock], 0)
        except (OSError, ValueError):
            return True
        return bool(readable or errored)

    def acquire(
        self, host: str, port: int, scheme: str = "http"
    ) -> Tuple[http.client.HTTPConnection, bool]:
        """(connection, reused): reused=True means it already carried at
        least one request on this socket (retry policy branches on it)."""
        key = (scheme, host, port)
        while True:
            with self._lock:
                idle = self._idle.get(key)
                conn = idle.pop() if idle else None
                if conn is not None:
                    self._idle_count -= 1
                    metrics.set_gauge(GAUGE_POOL_SIZE, self._idle_count)
            if conn is None:
                break
            if self._stale(conn):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            metrics.inc(COUNTER_CONN_REUSED)
            return conn, True
        if scheme == "https" and self._tls_ctx is None:
            self._tls_ctx = _tls_client_context(self._tls_ca)
        conn = _new_connection(
            scheme, host, port, self.timeout, tls_ctx=self._tls_ctx
        )
        metrics.inc(COUNTER_CONN_OPENED)
        return conn, False

    def release(self, host: str, port: int, conn, scheme: str = "http") -> None:
        with self._lock:
            idle = self._idle.setdefault((scheme, host, port), [])
            if len(idle) >= self.max_idle_per_host:
                pass  # over the idle bound: close below instead
            else:
                idle.append(conn)
                self._idle_count += 1
                metrics.set_gauge(GAUGE_POOL_SIZE, self._idle_count)
                return
        try:
            conn.close()
        except OSError:
            pass

    def discard(self, conn) -> None:
        try:
            conn.close()
        except OSError:
            pass

    def close(self) -> None:
        with self._lock:
            conns = [c for idle in self._idle.values() for c in idle]
            self._idle.clear()
            self._idle_count = 0
            metrics.set_gauge(GAUGE_POOL_SIZE, 0)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def size(self) -> int:
        with self._lock:
            return self._idle_count


class RESTClient:
    """degraded_retries / degraded_retry_cap_s: a fast-fail 503 from a
    degraded read-only store (reason "Degraded": the write gate refused
    BEFORE applying anything, runtime/consensus.py) is transparently
    retried — the client honors the Retry-After header (capped) for up
    to degraded_retries attempts before surfacing DegradedWrites. A
    "WriteQuorumLost" 503 (the write applied locally but missed quorum:
    outcome unknown) surfaces as QuorumLost without replay, and a 503
    with no Retry-After (fenced ex-primary) surfaces as NotPrimary.

    pool_connections: idle keep-alive sockets kept per host (0 disables
    the pool entirely — every request opens and closes its own
    connection, the pre-pool behavior the serving A/B baselines)."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        degraded_retries: int = 3,
        degraded_retry_cap_s: float = 2.0,
        pool_connections: int = 8,
        tls_ca: Optional[str] = None,
    ):
        self.base = base_url.rstrip("/")
        self.timeout = timeout
        self.degraded_retries = degraded_retries
        self.degraded_retry_cap_s = degraded_retry_cap_s
        self.tls_ca = tls_ca
        self._headers: dict = {}
        self.pool: Optional[HTTPConnectionPool] = (
            HTTPConnectionPool(pool_connections, timeout=timeout, tls_ca=tls_ca)
            if pool_connections
            else None
        )

    # -- transport -----------------------------------------------------------

    def _url(self, resource: str, namespace: str, name: str = "") -> str:
        # empty namespace = cluster-scoped path (the store keys by the
        # object's own namespace either way)
        if namespace:
            path = f"/api/v1/namespaces/{namespace}/{resource}"
        else:
            path = f"/api/v1/{resource}"
        if name:
            path += f"/{name}"
        return self.base + path

    def _acquire(self, host: str, port: int, scheme: str = "http"):
        if self.pool is not None:
            return self.pool.acquire(host, port, scheme)
        conn = _new_connection(
            scheme, host, port, self.timeout, tls_ca=self.tls_ca
        )
        metrics.inc(COUNTER_CONN_OPENED)
        return conn, False

    def _park(self, host: str, port: int, conn, resp,
              scheme: str = "http") -> None:
        """Return a connection after a fully-read response: back to the
        pool when the response allows reuse, closed otherwise."""
        if self.pool is None or resp.will_close:
            try:
                conn.close()
            except OSError:
                pass
            return
        self.pool.release(host, port, conn, scheme)

    def _discard(self, conn) -> None:
        if self.pool is not None:
            self.pool.discard(conn)
        else:
            try:
                conn.close()
            except OSError:
                pass

    def _http(
        self,
        method: str,
        url: str,
        data: Optional[bytes] = None,
        headers: Optional[dict] = None,
        stream: bool = False,
    ):
        """One HTTP exchange over a pooled connection.

        Non-stream: (status, reason, headers, body) with the connection
        returned to the pool. stream=True: (response, conn, host, port)
        with the UNREAD response and connection owned by the caller (a
        watch stream holds its socket for its lifetime and discards it).

        Stale-reuse retry contract: a REUSED connection that dies under
        a GET/HEAD reopens once transparently; under a write it raises —
        the request may have been applied with the ack lost (for a bind
        POST that is exactly the QuorumLost shape: the caller's
        read-back reconciler resolves it, never a blind replay).
        Fresh-connection failures never retry here."""
        u = urlsplit(url)
        scheme = u.scheme or "http"
        host = u.hostname or "127.0.0.1"
        port = u.port or (443 if scheme == "https" else 80)
        path = u.path + (f"?{u.query}" if u.query else "")
        hdrs = dict(headers or {})
        if self.pool is None:
            hdrs.setdefault("Connection", "close")
        retried = False
        while True:
            conn, reused = self._acquire(host, port, scheme)
            try:
                conn.request(method, path, body=data, headers=hdrs)
                resp = conn.getresponse()
            except (ConnectionResetError, BrokenPipeError,
                    http.client.BadStatusLine) as e:
                # RemoteDisconnected subclasses both BadStatusLine and
                # ConnectionResetError: the server closed without a
                # response — the stale-pooled-socket signature
                self._discard(conn)
                if reused and not retried and method in _RETRYABLE_METHODS:
                    retried = True
                    continue
                if isinstance(e, http.client.BadStatusLine) and not isinstance(
                    e, http.client.RemoteDisconnected
                ):
                    raise OSError(f"malformed response: {e}") from e
                raise
            except (OSError, http.client.HTTPException) as e:
                self._discard(conn)
                if isinstance(e, OSError):
                    raise
                raise OSError(str(e)) from e
            if stream:
                return resp, conn, host, port
            try:
                body = resp.read()
            except OSError:
                self._discard(conn)
                raise
            self._park(host, port, conn, resp, scheme)
            return resp.status, resp.reason, resp.headers, body

    def _request_raw(
        self,
        method: str,
        url: str,
        body: Optional[dict] = None,
        headers: Optional[dict] = None,
    ) -> bytes:
        """Shared request plumbing: pooled transport, degraded-503 retry,
        and the full HTTP error taxonomy. get_text/post_text ride this
        too — a degraded store no longer fast-fails log/exec
        subresources with an unmapped error."""
        data = json.dumps(body).encode() if body is not None else None
        attempt = 0
        while True:
            status, reason, hdrs, raw = self._http(
                method,
                url,
                data,
                {
                    "Content-Type": "application/json",
                    **self._headers,
                    **(headers or {}),
                },
            )
            if 200 <= status < 300:
                return raw
            payload = {}
            try:
                payload = json.loads(raw.decode() or "{}")
            except (ValueError, UnicodeDecodeError):
                pass
            msg = payload.get("message", f"HTTP Error {status}: {reason}")
            if status == 404:
                raise NotFound(msg)
            if status == 409:
                err_reason = payload.get("reason", "")
                if err_reason == "AlreadyExists":
                    raise AlreadyExists(msg)
                if err_reason == "LeaderFenced":
                    # leadership fence rejection: the caller's lease
                    # grant was superseded — non-retryable (the caller
                    # is not the leader anymore), nothing was applied
                    raise LeaderFenced(msg)
                raise Conflict(msg)
            if status == 503:
                # three distinct 503 contracts (rest.py):
                #   "Degraded"        gate refused before applying:
                #                     replaying is safe — honor
                #                     Retry-After (capped) and retry;
                #                     the store re-opens the moment
                #                     followers catch the commit up
                #   "WriteQuorumLost" THIS request applied locally but
                #                     missed quorum: outcome unknown —
                #                     a blind replay would 409 against
                #                     its own first attempt; surface it
                #   "DiskFailed"      the replica's WAL sink is
                #                     fail-stopped: the gate refused
                #                     before applying, so replaying is
                #                     safe — bounded retries ride out a
                #                     leader failover to a disk-healthy
                #                     replica
                #   "DiskPressure"    WAL volume low on space: refused
                #                     before applying; retry while
                #                     compaction/reclaim frees space
                #   no Retry-After    fenced primary (permanent for
                #                     that process): never hammer it —
                #                     callers must re-discover the
                #                     leader
                err_reason = payload.get("reason", "")
                retry_after = hdrs.get("Retry-After")
                if retry_after is None:
                    raise NotPrimary(msg)
                if err_reason == "WriteQuorumLost":
                    raise QuorumLost(msg)
                if attempt < self.degraded_retries:
                    attempt += 1
                    try:
                        delay = float(retry_after)
                    except ValueError:
                        delay = 0.5
                    time.sleep(min(delay, self.degraded_retry_cap_s))
                    continue
                if err_reason == "DiskFailed":
                    raise DiskFailed(msg)
                if err_reason == "DiskPressure":
                    raise DiskPressure(msg)
                raise DegradedWrites(msg)
            raise urllib.error.HTTPError(url, status, msg, hdrs, io.BytesIO(raw))

    def _request(
        self,
        method: str,
        url: str,
        body: Optional[dict] = None,
        headers: Optional[dict] = None,
    ) -> dict:
        return json.loads(self._request_raw(method, url, body, headers) or b"{}")

    # -- HTTP/1.1 pipelining (idempotent GETs only) --------------------------

    def _http_error_for(self, url, status, reason, hdrs, raw) -> Exception:
        """The _request_raw error taxonomy as a one-shot classifier (no
        degraded-503 sleep/retry loop: the pipeline path surfaces the
        typed error and lets the caller decide)."""
        payload = {}
        try:
            payload = json.loads(raw.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            pass
        msg = payload.get("message", f"HTTP Error {status}: {reason}")
        err_reason = payload.get("reason", "")
        if status == 404:
            return NotFound(msg)
        if status == 409:
            if err_reason == "AlreadyExists":
                return AlreadyExists(msg)
            if err_reason == "LeaderFenced":
                return LeaderFenced(msg)
            return Conflict(msg)
        if status == 410:
            return Expired(msg)
        if status == 503:
            if hdrs.get("Retry-After") is None:
                return NotPrimary(msg)
            if err_reason == "WriteQuorumLost":
                return QuorumLost(msg)
            if err_reason == "DiskFailed":
                return DiskFailed(msg)
            if err_reason == "DiskPressure":
                return DiskPressure(msg)
            return DegradedWrites(msg)
        return urllib.error.HTTPError(url, status, msg, hdrs, io.BytesIO(raw))

    def pipelined_get_raw(
        self,
        urls: List[str],
        headers: Optional[dict] = None,
        depth: int = 8,
    ) -> List[bytes]:
        """K idempotent GETs pipelined on one pooled connection.

        Requests go out back-to-back in windows of ``depth`` and the
        responses drain IN ORDER off the same socket — one connection,
        one round trip of latency for the whole window instead of K.

        Mid-pipeline transport error contract: only the FIRST in-flight
        request (sent, unanswered, no response bytes consumed for it)
        may classify as retryable — it gets the same one-shot
        reused-connection retry a plain GET gets; every request behind
        it was never attempted by the server as far as we can prove, so
        those requeue unattempted WITHOUT consuming retry budget. Bind
        POSTs never ride this path (`_classify_bind_transport` keeps
        writes strictly one-at-a-time).

        Responses within one window share a single buffered reader:
        a per-response ``makefile`` could prefetch bytes belonging to
        the NEXT response and lose them with the file object.
        """
        results: List[Optional[bytes]] = [None] * len(urls)
        pending = deque(enumerate(urls))
        retried: set = set()
        base_hdrs = {**self._headers, **(headers or {})}
        while pending:
            window = []
            while pending and len(window) < depth:
                window.append(pending.popleft())
            u = urlsplit(window[0][1])
            scheme = u.scheme or "http"
            host = u.hostname or "127.0.0.1"
            port = u.port or (443 if scheme == "https" else 80)
            conn, reused = self._acquire(host, port, scheme)
            completed = 0
            fp = None
            try:
                if conn.sock is None:
                    conn.connect()
                sock = conn.sock
                out = []
                for _idx, url in window:
                    pu = urlsplit(url)
                    path = pu.path + (f"?{pu.query}" if pu.query else "")
                    lines = [
                        f"GET {path} HTTP/1.1",
                        f"Host: {host}:{port}",
                        "Accept-Encoding: identity",
                    ]
                    lines += [f"{k}: {v}" for k, v in base_hdrs.items()]
                    out.append(
                        ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
                    )
                sock.sendall(b"".join(out))
                metrics.inc(COUNTER_PIPELINED, by=len(window))
                fp = sock.makefile("rb")
                shared = _NoCloseReader(fp)
                last_resp = None
                early_close = False
                for j, (idx, url) in enumerate(window):
                    resp = http.client.HTTPResponse(sock, method="GET")
                    resp.fp.close()
                    resp.fp = shared  # shared reader: see docstring
                    resp.begin()
                    body = resp.read()
                    if not (200 <= resp.status < 300):
                        raise self._http_error_for(
                            url, resp.status, resp.reason, resp.headers, body
                        )
                    results[idx] = body
                    completed = j + 1
                    last_resp = resp
                    if resp.will_close and j + 1 < len(window):
                        # the server is closing after this response: the
                        # unanswered tail requeues unattempted
                        tail = window[j + 1:]
                        for item in reversed(tail):
                            pending.appendleft(item)
                        metrics.inc(
                            COUNTER_PIPELINE_REQUEUES,
                            {"reason": "unattempted"}, by=len(tail),
                        )
                        early_close = True
                        break
                if early_close or last_resp is None or last_resp.will_close:
                    self._discard(conn)
                else:
                    self._park(host, port, conn, last_resp, scheme)
            except (OSError, http.client.HTTPException) as e:
                self._discard(conn)
                in_flight = window[completed:]
                if not in_flight:
                    raise
                first, rest = in_flight[0], in_flight[1:]
                for item in reversed(rest):
                    pending.appendleft(item)
                if rest:
                    metrics.inc(
                        COUNTER_PIPELINE_REQUEUES,
                        {"reason": "unattempted"}, by=len(rest),
                    )
                # only the first in-flight request classifies as
                # retryable — and only with the plain GET's one-shot
                # reused-connection policy
                if reused and first[0] not in retried:
                    retried.add(first[0])
                    pending.appendleft(first)
                    metrics.inc(
                        COUNTER_PIPELINE_REQUEUES,
                        {"reason": "first_in_flight"},
                    )
                    continue
                if isinstance(e, http.client.HTTPException) and not isinstance(
                    e, OSError
                ):
                    raise OSError(str(e)) from e
                raise
            finally:
                if fp is not None:
                    try:
                        fp.close()
                    except OSError:
                        pass
        return results  # type: ignore[return-value]

    def get_many(
        self, kind: str, namespace: str, names: List[str], depth: int = 8
    ) -> List[Any]:
        """Pipelined typed point-gets: K objects in ~one round trip."""
        urls = [self._url(kind, namespace, n) for n in names]
        return [
            codec.decode(kind, json.loads(raw or b"{}"))
            for raw in self.pipelined_get_raw(urls, depth=depth)
        ]

    def get_text(self, resource: str, namespace: str, name: str) -> str:
        """Plain-text GET of a subresource (pods/{name}/log): shared
        plumbing with the JSON path — same pool, same degraded-503
        retry, same typed error taxonomy (get_raw is the JSON variant
        for aggregated API paths)."""
        return self._request_raw(
            "GET", self._url(resource, namespace, name)
        ).decode()

    def post_text(self, resource: str, namespace: str, name: str, body: dict) -> str:
        """Plain-text POST to a subresource (pods/{name}/exec): same
        shared plumbing as get_text."""
        return self._request_raw(
            "POST", self._url(resource, namespace, name), body
        ).decode()

    def get_raw(self, path: str) -> dict:
        """GET an arbitrary API path (aggregated APIs like metrics.k8s.io)."""
        return self._request("GET", self.base + path)

    def backup_state(self) -> dict:
        """Online consistent backup image from the live server
        (/debug/backup — the `ktpu-backup save --url` path)."""
        return self.get_raw("/debug/backup")

    def close(self) -> None:
        """Drop the idle connection pool (tests / process teardown)."""
        if self.pool is not None:
            self.pool.close()

    # -- the APIServer interface ---------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        out = self._request(
            "POST",
            self._url(kind, obj.metadata.namespace),
            codec.encode(obj),
        )
        return codec.decode(kind, out)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        out = self._request("GET", self._url(kind, namespace, name))
        return codec.decode(kind, out)

    def update(self, kind: str, obj: Any, check_version: bool = True) -> Any:
        out = self._request(
            "PUT",
            self._url(kind, obj.metadata.namespace, obj.metadata.name),
            codec.encode(obj),
        )
        return codec.decode(kind, out)

    def guaranteed_update(
        self, kind: str, namespace: str, name: str, mutate: Callable[[Any], Any]
    ) -> Any:
        while True:
            cur = self.get(kind, namespace, name)
            new = mutate(cur)
            if new is None:
                return cur
            try:
                return self.update(kind, new)
            except Conflict:
                continue

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        return self._request("DELETE", self._url(kind, namespace, name))

    def list(self, kind: str, namespace: Optional[str] = None) -> Tuple[List[Any], int]:
        url = self._url(kind, namespace or "")
        out = self._request("GET", url)
        rv = int(out.get("metadata", {}).get("resourceVersion", 0))
        items = [codec.decode(kind, item) for item in out.get("items", [])]
        if namespace is not None:
            items = [o for o in items if o.metadata.namespace == namespace]
        return items, rv

    def kind_resource_version(self, kind: str) -> int:
        """rv of the newest event OF THIS KIND at the server (the
        freshness target for consistent cache-served lists — see
        APIServer.kind_resource_version). Served by a dedicated cheap
        query (?kindResourceVersion=1, no object payload); a frontend
        chain forwards it upstream to the primary."""
        out = self._request(
            "GET", self._url(kind, "") + "?kindResourceVersion=1"
        )
        return int(out.get("kindResourceVersion", 0) or 0)

    def pod_logs(
        self, namespace: str, name: str, tail_lines: Optional[int] = None
    ) -> str:
        """pods/{name}/log over REST (the store surface rest.py serves a
        frontend from)."""
        url = self._url("pods", namespace, f"{name}/log")
        if tail_lines is not None:
            url += f"?tailLines={tail_lines}"
        return self._request_raw("GET", url).decode()

    def pod_exec(self, namespace: str, name: str, command) -> str:
        """pods/{name}/exec over REST (frontend store surface)."""
        return self.post_text(
            "pods", namespace, f"{name}/exec", {"command": list(command)}
        )

    def evict_pod(
        self, namespace: str, name: str, retries_429: int = 2
    ) -> None:
        """pods/{name}/eviction over REST; a PDB/ratelimit refusal (429)
        maps back to TooManyRequests like the in-process store.

        429s carrying a Retry-After header are honored (previously the
        first refusal gave up outright): up to ``retries_429`` paced
        retries sleep out the server's hint — a disruption-controller
        budget resync away from succeeding — each capped at
        degraded_retry_cap_s like the 503 path. A refusal that survives
        the retries (or carries no hint) raises TooManyRequests with the
        hint attached as ``retry_after_s``, so a paced drainer (the
        descheduler's wave loop) can schedule its next attempt instead
        of hammering."""
        attempt = 0
        while True:
            try:
                self._request(
                    "POST",
                    self._url("pods", namespace, f"{name}/eviction"),
                    {"podName": name, "podNamespace": namespace},
                )
                return
            except urllib.error.HTTPError as e:
                if e.code != 429:
                    raise
                raw_hint = (e.headers or {}).get("Retry-After")
                delay = None
                if raw_hint is not None:
                    try:
                        delay = float(raw_hint)
                    except ValueError:
                        delay = 1.0
                if delay is not None and attempt < retries_429:
                    attempt += 1
                    time.sleep(min(delay, self.degraded_retry_cap_s))
                    continue
                err = TooManyRequests(str(e))
                err.retry_after_s = delay
                raise err from None

    # -- watch ---------------------------------------------------------------

    def _open_watch(self, kind: str, from_version: int):
        """One watch stream connect. Returns (resp, conn) with the codec
        decided by the RESPONSE Content-Type: the request offers the
        binary watch codec via Accept, an old server ignores it and
        answers JSON lines — negotiation degrades to the universal wire.
        Raises Expired on 410 (resume position outside the window) and
        OSError on transport/HTTP-level failure."""
        from .watchcodec import WATCH_CONTENT_TYPE

        url = self._url(kind, "") + f"?watch=1&resourceVersion={from_version}"
        resp, conn, _host, _port = self._http(
            "GET",
            url,
            None,
            {**self._headers, "Accept": WATCH_CONTENT_TYPE},
            stream=True,
        )
        if resp.status != 200:
            try:
                body = resp.read().decode()
            except OSError:
                body = ""
            self._discard(conn)
            if resp.status == 410:
                raise Expired(body or "resourceVersion too old")
            raise OSError(f"watch connect failed: HTTP {resp.status} {body}")
        # the STREAM clears the socket timeout: an idle but healthy watch
        # must not be killed by a read timeout (the connect itself was
        # bounded by the client timeout)
        sock = conn.sock
        if sock is not None:
            sock.settimeout(None)
        return resp, conn

    def _pump_stream(self, kind: str, resp, w: Watcher, last_rv: int) -> Tuple[int, str]:
        """Drain one watch stream into the Watcher until it ends.
        Returns (last delivered rv, end reason for the reconnect
        counter). Decodes binary frames when the server negotiated the
        compact codec, newline-JSON otherwise."""
        from . import watchcodec
        from .cacher import bookmark_object

        ctype = resp.headers.get("Content-Type") or ""
        try:
            if watchcodec.WATCH_CONTENT_TYPE in ctype:
                while not w.stopped:
                    frame = watchcodec.read_frame(resp)
                    if frame is None:
                        return last_rv, "eof"
                    ev_type, rv, obj = frame
                    if ev_type == BOOKMARK:
                        w.push(Event(BOOKMARK, bookmark_object(kind, rv), rv))
                    else:
                        if isinstance(obj, dict):
                            obj = codec.decode(kind, obj)  # 'J' fallback frame
                        w.push(Event(ev_type, obj, rv))
                    last_rv = max(last_rv, rv)
                return last_rv, "stopped"
            for line in resp:
                if w.stopped:
                    return last_rv, "stopped"
                line = line.strip()
                if not line:
                    continue
                msg = json.loads(line)
                if msg["type"] == BOOKMARK:
                    # rv-only progress notify from the watch cache
                    # (idle heartbeat / window keep-alive): carry
                    # the rv through; informers advance their
                    # resume position on it, other consumers skip
                    # unknown event types
                    rv = int(
                        (msg.get("object") or {})
                        .get("metadata", {})
                        .get("resourceVersion", 0)
                    )
                    w.push(Event(BOOKMARK, bookmark_object(kind, rv), rv))
                    last_rv = max(last_rv, rv)
                    continue
                obj = codec.decode(kind, msg["object"])
                rv = obj.metadata.resource_version
                w.push(Event(msg["type"], obj, rv))
                last_rv = max(last_rv, rv)
            return last_rv, "eof"
        except ValueError:
            return last_rv, "truncated"
        except Exception:
            return last_rv, "error"

    def watch(self, kind: str, from_version: int = 0) -> Watcher:
        w = Watcher()
        # open SYNCHRONOUSLY so a 410 Gone ("resourceVersion too old")
        # surfaces to the caller as Expired — informers re-list on it; a
        # silent pump-thread death would hand them a gapped stream. Other
        # connection errors keep the old contract (a stopped watcher, not
        # an exception).
        try:
            resp, conn = self._open_watch(kind, from_version)
        except Expired:
            raise
        except OSError:
            w.stop()
            return w

        def pump(resp, conn):
            last_rv = from_version
            stalled = 0  # consecutive resumes that delivered nothing new
            while True:
                rv_before = last_rv
                try:
                    last_rv, reason = self._pump_stream(kind, resp, w, last_rv)
                finally:
                    self._discard(conn)  # a stream's socket is never reused
                if w.stopped or reason == "stopped":
                    break
                # a resume is only "transparent" while it makes progress:
                # a poison event pinned at a fixed rv (decode raises, rv
                # never advances) would otherwise reconnect successfully
                # at full speed forever — _open_watch succeeding means the
                # connect backoff below never engages. Bound consecutive
                # zero-progress resumes and back off between them; hitting
                # the bound stops the watcher, handing the consumer its
                # relist path (same contract as falling out of the window).
                if last_rv > rv_before:
                    stalled = 0
                else:
                    stalled += 1
                    if stalled >= _WATCH_RESUME_ATTEMPTS:
                        w.stop()
                        return
                    time.sleep(min(0.05 * (2 ** (stalled - 1)), 1.0))
                    if w.stopped:
                        return
                # transparent resume at the last delivered rv: through a
                # balancer this lands on ANY healthy frontend, whose
                # watch cache replays the gap from its event window —
                # the consumer sees one continuous stream, no relist
                metrics.inc(COUNTER_WATCH_RECONNECTS, {"reason": reason})
                backoff = 0.05
                for _attempt in range(_WATCH_RESUME_ATTEMPTS):
                    try:
                        resp, conn = self._open_watch(kind, last_rv)
                        break
                    except Expired:
                        # fell out of the window mid-death: stopping the
                        # watcher hands the consumer its relist path
                        w.stop()
                        return
                    except OSError:
                        if w.stopped:
                            return
                        time.sleep(backoff)
                        backoff = min(backoff * 2, 1.0)
                else:
                    w.stop()
                    return

            w.stop()

        threading.Thread(
            target=pump, args=(resp, conn), daemon=True, name=f"watch-{kind}"
        ).start()
        return w

    # -- binds ---------------------------------------------------------------

    @staticmethod
    def _fence_headers(fence) -> Optional[dict]:
        return (
            {FENCE_HEADER: fence_header_value(fence)}
            if fence is not None
            else None
        )

    @staticmethod
    def _bind_headers(base: Optional[dict], binding) -> Optional[dict]:
        """Fence headers plus trace-context propagation: the pod's trace
        id (minted at queue admission in THIS process) rides the
        X-Trace-Context header so the store process stamps its apply —
        or its LeaderFenced rejection — under the same identity."""
        tid = trace_for_binding(binding)
        if not tid:
            return base
        return {**(base or {}), TRACE_HEADER: tid}

    @staticmethod
    def _classify_bind_transport(e: Exception) -> DegradedWrites:
        """Map a transport-level failure of a /binding POST onto the bind
        outcome taxonomy. A refused connect means the request never
        reached the server — retryable, same contract as a degraded-store
        refusal (nothing applied, safe to replay verbatim). ANYTHING else
        (timeout, reset, EOF-without-response, half-delivered body) means
        the request MAY have been processed with its response lost: the
        one honest classification is QuorumLost — the caller must read
        the pod back before any retry, never blindly replay (a netchaos
        blackhole is exactly this shape: write applied, ack dropped).
        The pool's stale-reuse reopen never reaches here for binds at
        all: _http's transparent one-shot retry covers idempotent GETs
        only (_RETRYABLE_METHODS) — every reused-connection death on a
        bind POST, send-phase included, lands in this classifier."""
        cause = getattr(e, "reason", e)  # URLError wraps the socket error
        if isinstance(cause, ConnectionRefusedError):
            return DegradedWrites(f"api server unreachable: {cause}")
        return QuorumLost(f"bind outcome unknown (transport failure: {e})")

    def bind_pod(self, binding, fence=None) -> None:
        """Single-pod binding subresource (DefaultBinder's surface; the
        bulk bind_pods below shares the wire path). Raises on failure so
        the bind plugin's error handling fires like the in-process store.
        fence: optional BindFence, attached as the X-Leadership-Fence
        header; the server rejects with LeaderFenced when superseded."""
        try:
            self._request(
                "POST",
                self.base
                + f"/api/v1/namespaces/{binding.pod_namespace}/pods/"
                + f"{binding.pod_name}/binding",
                codec.encode(binding),
                headers=self._bind_headers(self._fence_headers(fence), binding),
            )
        except (
            LeaderFenced,
            DegradedWrites,
            NotFound,
            Conflict,
            urllib.error.HTTPError,
        ):
            raise
        except OSError as e:
            raise self._classify_bind_transport(e) from e

    def bind_pods(self, bindings, fence=None) -> list:
        """Per-binding error list (None = bound). Retryable degraded-store
        refusals come back as the EXCEPTION OBJECT (DegradedWrites /
        QuorumLost), not a string — the scheduler's ride-through layer
        parks those placements instead of failing them. After the first
        degraded refusal the remaining bindings are not attempted (each
        would burn its own client-side retry budget against a store that
        just said "read-only"); they get a fresh DegradedWrites — none of
        them was applied, so replaying them later is safe. Transport
        failures classify through _classify_bind_transport: refused
        connect = retryable DegradedWrites, anything after the connect =
        QuorumLost (outcome unknown, read back before retrying).

        fence: the leadership fencing token (BindFence), attached to every
        binding POST as the X-Leadership-Fence header and validated by the
        server against the live lease under the bind lock. A LeaderFenced
        rejection RAISES (mirroring the in-process store's whole-batch
        reject): the remaining bindings are not attempted — the caller is
        not the leader anymore. Bindings that already landed in this batch
        were applied while the grant was still valid and stay applied
        exactly once; the new leader's adoption pass reads them back."""
        errors = []
        degraded: Optional[DegradedWrites] = None
        fence_headers = self._fence_headers(fence)  # one token per batch
        for b in bindings:
            if degraded is not None:
                errors.append(
                    DegradedWrites(f"not attempted: {degraded}")
                )
                continue
            try:
                self._request(
                    "POST",
                    self.base
                    + f"/api/v1/namespaces/{b.pod_namespace}/pods/"
                    + f"{b.pod_name}/binding",
                    codec.encode(b),
                    headers=self._bind_headers(fence_headers, b),
                )
                errors.append(None)
            except LeaderFenced:
                # deposed mid-batch: nothing further may apply. Raise like
                # the in-process store's atomic whole-batch reject; the
                # scheduler's _on_fenced_binds drops every placement (the
                # already-landed prefix is re-adopted from informer state)
                raise
            except QuorumLost as e:
                # THIS binding applied remotely but missed quorum: its
                # outcome is unknown — surface the exception itself so the
                # caller reads the pod back before any retry
                errors.append(e)
                degraded = e
            except DegradedWrites as e:
                errors.append(e)
                degraded = e
            except (NotFound, Conflict) as e:
                # typed like the in-process store's error list, so the
                # scheduler's reconciler branches identically over REST
                errors.append(e)
            except urllib.error.HTTPError as e:
                # a non-2xx the taxonomy doesn't know (500, 403, ...):
                # the server DID answer — a known refusal, not unknown
                errors.append(str(e))
            except OSError as e:
                # transport failure (partition, reset, blackholed ack):
                # classify, then stop attempting the rest of the batch —
                # the network just proved undeliverable and each further
                # attempt would burn its own timeout
                err = self._classify_bind_transport(e)
                errors.append(err)
                degraded = err
            except Exception as e:
                errors.append(str(e))
        return errors


def serving_health_lines() -> List[str]:
    """REST-client transport state for the SIGUSR2 dump: pool occupancy,
    opened-vs-reused connection counts, and watch-pump resume counters —
    whether the serving tier's keep-alive path is actually hot is
    diagnosable from one signal."""
    lines: List[str] = []
    for snap in (
        metrics.snapshot_gauges("restclient_"),
        metrics.snapshot_counters("restclient_"),
    ):
        for name, labels, value in snap:
            lines.append(metrics.format_series_line(name, labels, value))
    return lines


class AuthRESTClient(RESTClient):
    """RESTClient sending a bearer token (kubeconfig user credentials)."""

    def __init__(self, base_url: str, token: str, timeout: float = 30.0):
        super().__init__(base_url, timeout=timeout)
        self._headers["Authorization"] = f"Bearer {token}"
