"""Horizontally scaled serving tier: stateless frontends + follower reads.

Reference shape: the upstream serving tier is a composed chain of
stateless apiservers over a shared storage/watch layer (PAPER.md layer 4,
``CreateServerChain`` / aggregator composition, ``storage/cacher``) —
scale-out happens by adding frontends, not by fattening one server. Here:

  * **Stateless frontend** (:func:`serve_frontend`): a full REST façade
    (apiserver/rest.py) whose "store" is a pooled :class:`RESTClient`
    pointed at the primary. The frontend owns its OWN ``Cacher``: every
    watch stream and rv=0/paginated list it serves costs the primary ONE
    upstream watch per kind, writes delegate upstream verbatim (the
    leadership-fence header included — the /binding route re-attaches
    it), and consistent lists wait on the PRIMARY's per-kind rv through
    the chained ``kindResourceVersion`` probe. Frontends hold no durable
    state: kill one and its clients resume on a sibling through the
    balancer, replaying from that sibling's watch-cache window.
  * **Follower reads** (:class:`FollowerReadStore`,
    :func:`serve_follower_frontend`): a consensus follower already holds
    the durable log — attach a watch cache to it and list/watch traffic
    never touches the primary at all. The store adapter exposes the
    replica's state through the standard store read surface, with one
    hard rule: watch events are released only once the COMMIT INDEX
    covers them (etcd fires watch events post-commit for the same
    reason), so the per-kind cache rv *is* the committed rv and the
    PR-6 ``wait_until_fresh`` seam generalizes verbatim into "wait until
    my commit index ≥ the rv the client demands". Writes, point gets,
    and lease operations delegate to the primary — a lease served from a
    lagging replica could hand two electors the same grant.

The balancer in front of the fleet is
``kubernetes_tpu.testing.netchaos.LoadBalancerProxy`` — the netchaos
proxy machinery run in reverse (one listener, N upstreams).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..client.apiserver import Expired, NotPrimary
from ..runtime.watch import ADDED, DELETED, MODIFIED, Event, Watcher
from ..testing.lockgraph import named_lock
from ..utils.metrics import metrics

# events a follower read store buffers per kind for watch(from_version)
# replay across the list->watch gap (the cacher's own window does the
# long-haul replay; this ring only bridges cache resyncs)
FOLLOWER_HISTORY = 4096

GAUGE_FOLLOWER_COMMIT_LAG = "follower_read_commit_lag"
COUNTER_FOLLOWER_EVENTS = "follower_read_events_total"  # {kind}

_VERB_TO_EVENT = {"create": ADDED, "delete": DELETED}


class _MinRvWatcher(Watcher):
    """Store-side watcher with a resume floor: events at or below the
    caller's from_version are already in its seed list."""

    def __init__(self, min_rv: int):
        super().__init__()
        self.min_rv = min_rv

    def push_event(self, ev: Event) -> None:
        if ev.resource_version > self.min_rv:
            self.push(ev)


class FollowerReadStore:
    """The store read surface over a replication Follower, commit-gated.

    Read path (served locally, no primary touch):
      * ``list(kind)``: the replica's applied objects, labeled with the
        COMMITTED rv (state may run slightly ahead of the label — the
        uncommitted tail arrives later as events; a reader is never told
        a write is consistent before a quorum holds it).
      * ``watch(kind, from_version)``: applied records are parked until
        the learned commit index covers them, then fan out in rv order.

    Everything else — writes, point gets (electors read leases through
    get), subresources — delegates to the primary client: only the
    fan-out-heavy surface moves to the follower.
    """

    def __init__(self, follower, primary, commit_gated: bool = True):
        self._follower = follower
        self._primary = primary
        # legacy replication (no consensus) never advances a commit
        # index; an ungated adapter treats applied as committed
        self._commit_gated = commit_gated
        self._lock = named_lock("follower.read")
        self._commit = follower.commit_index if commit_gated else 0
        # applied-but-uncommitted events, strict rv order
        self._pending: deque = deque()
        # per-kind committed-event history for watch replay + the rv of
        # the newest event evicted from each ring (410 boundary)
        self._history: Dict[str, deque] = {}
        self._evicted_rv: Dict[str, int] = {}
        self._watchers: Dict[str, List[_MinRvWatcher]] = {}
        self._committed_rv = self._commit
        follower.register_observer(self)

    # -- follower observer side ----------------------------------------------

    def on_records(self, recs: List[Tuple[int, str, str, Any]]) -> None:
        with self._lock:
            for rv, verb, kind, obj in recs:
                if obj is None:
                    continue
                ev = Event(_VERB_TO_EVENT.get(verb, MODIFIED), obj, rv)
                self._pending.append((kind, ev))
            self._flush_locked()

    def on_commit(self, commit: int) -> None:
        with self._lock:
            if commit > self._commit:
                self._commit = commit
            self._flush_locked()

    def on_snapshot(self) -> None:
        """Full state transfer: the incremental event view is invalid.
        Terminate every watcher (their consumer — the kind cache —
        resyncs from list) and reset the rings, mirroring the cacher's
        own terminateAllWatchers discipline."""
        with self._lock:
            self._pending.clear()
            for kind, ring in self._history.items():
                if ring:
                    self._evicted_rv[kind] = max(
                        self._evicted_rv.get(kind, 0),
                        ring[-1].resource_version,
                    )
                ring.clear()
            watchers = [w for ws in self._watchers.values() for w in ws]
            self._watchers.clear()
            self._committed_rv = max(self._committed_rv, self._follower.rv)
        for w in watchers:
            w.stop()

    def _flush_locked(self) -> None:
        """Release pending events the commit index now covers (or all of
        them when ungated). Caller holds the lock."""
        import time as _time

        gate = self._commit if self._commit_gated else float("inf")
        while self._pending and self._pending[0][1].resource_version <= gate:
            kind, ev = self._pending.popleft()
            ev.ts = _time.monotonic()
            self._committed_rv = max(self._committed_rv, ev.resource_version)
            ring = self._history.setdefault(
                kind, deque(maxlen=FOLLOWER_HISTORY)
            )
            if len(ring) == FOLLOWER_HISTORY and ring:
                self._evicted_rv[kind] = ring[0].resource_version
            ring.append(ev)
            metrics.inc(COUNTER_FOLLOWER_EVENTS, {"kind": kind})
            ws = self._watchers.get(kind)
            if ws:
                dead = []
                for w in ws:
                    if w.stopped:
                        dead.append(w)
                    else:
                        w.push_event(ev)
                for w in dead:
                    ws.remove(w)
        if self._commit_gated:
            metrics.set_gauge(
                GAUGE_FOLLOWER_COMMIT_LAG,
                max(self._follower.rv - self._commit, 0),
            )

    # -- store read surface ---------------------------------------------------

    def list(
        self, kind: str, namespace: Optional[str] = None
    ) -> Tuple[List[Any], int]:
        objs, rv = self._follower.list_kind(kind)
        if namespace is not None:
            objs = [o for o in objs if o.metadata.namespace == namespace]
        with self._lock:
            # label with the committed rv: the applied tail beyond it is
            # IN the objects (harmlessly fresh) but is never advertised
            # as consistent until a quorum holds it; watchers seeded from
            # this list receive the tail as events once it commits
            rv = min(rv, self._commit) if self._commit_gated else rv
        return objs, rv

    def watch(self, kind: str, from_version: int = 0) -> Watcher:
        with self._lock:
            evicted = self._evicted_rv.get(kind, 0)
            if from_version and from_version < evicted:
                raise Expired(
                    f"{kind} resourceVersion {from_version} is too old for "
                    f"the follower read ring (events up to rv {evicted} "
                    "were evicted)"
                )
            w = _MinRvWatcher(from_version)
            for ev in self._history.get(kind, ()):
                w.push_event(ev)
            self._watchers.setdefault(kind, []).append(w)
            return w

    def get(self, kind: str, namespace: str, name: str) -> Any:
        # point reads stay PRIMARY reads: the elector's lease get must
        # never observe a lagging replica (two grants from one lease),
        # and single-object reads are not the fan-out cost this tier
        # exists to absorb
        return self._primary.get(kind, namespace, name)

    def kind_resource_version(self, kind: str) -> int:
        """The PRIMARY's per-kind rv: what a consistent list through this
        follower must wait for — the freshness demand is defined by the
        leader's log, the wait is served by our commit index."""
        return self._primary.kind_resource_version(kind)

    def committed_rv(self) -> int:
        with self._lock:
            return self._committed_rv

    def wait_commit(self, rv: int, timeout: float = 5.0) -> bool:
        return self._follower.wait_commit(rv, timeout=timeout)

    def __getattr__(self, name: str):
        # write path / subresources / typed helpers -> the primary
        return getattr(self._primary, name)


# kind caches a frontend warms at startup: a COLD cache's replay floor
# starts at its seed rv, so the first client resuming onto a
# just-started (or never-before-asked) frontend would 410 into a relist
# even though no event was ever missed. Warm caches make "kill a
# frontend, resume on a sibling" replay from the window instead.
FRONTEND_WARM_KINDS = ("pods", "nodes")


def serve_frontend(
    primary_url: str,
    port: int = 0,
    timeout: float = 30.0,
    pool_connections: int = 8,
    warm_kinds: Tuple[str, ...] = FRONTEND_WARM_KINDS,
    relay_workers: int = 0,
    relay_port: int = 0,
    relay_kinds: Tuple[str, ...] = ("pods",),
    relay_hollow_clients: int = 0,
    **serve_kwargs,
):
    """One stateless REST frontend over a remote primary. Returns
    (server, port, client) — the full rest.py façade with its own watch
    cache, every upstream byte on pooled persistent connections.

    relay_workers > 0 attaches the watch-relay tier
    (kubernetes_tpu/relay/): this frontend's cacher publishes each
    relay_kinds frame once into shared memory and N SO_REUSEPORT worker
    processes own the watch-client fan-out on ``relay_port``. The
    handle hangs off ``srv.relay``; tls_cert/tls_key in serve_kwargs
    flow to both the REST port and the relay workers, so the whole
    serving hop is TLS or none of it is."""
    from .client import RESTClient
    from .rest import serve

    client = RESTClient(
        primary_url, timeout=timeout, pool_connections=pool_connections
    )
    srv, bound, _store = serve(store=client, port=port, **serve_kwargs)
    if srv.cacher is not None:
        for kind in warm_kinds:
            srv.cacher.cache_for(kind)
    srv.relay = None
    if relay_workers:
        if srv.cacher is None:
            raise ValueError("the watch relay requires the watch cache")
        from ..relay import start_relay

        tls_cert = serve_kwargs.get("tls_cert")
        tls_key = serve_kwargs.get("tls_key")
        scheme = "https" if tls_cert and tls_key else "http"
        srv.relay = start_relay(
            srv.cacher,
            f"{scheme}://127.0.0.1:{bound}",
            kinds=relay_kinds,
            n_workers=relay_workers,
            port=relay_port,
            tls_cert=tls_cert,
            tls_key=tls_key,
            hollow_clients=relay_hollow_clients,
        )
    return srv, bound, client


def serve_follower_frontend(
    follower,
    primary_url: str,
    port: int = 0,
    timeout: float = 30.0,
    commit_gated: bool = True,
    warm_kinds: Tuple[str, ...] = FRONTEND_WARM_KINDS,
    **serve_kwargs,
):
    """A follower-read REST frontend: list/watch from the replica's
    commit-gated state, writes and point gets delegated to the primary.
    Returns (server, port, read_store)."""
    from .client import RESTClient
    from .rest import serve

    primary = RESTClient(primary_url, timeout=timeout)
    store = FollowerReadStore(follower, primary, commit_gated=commit_gated)
    srv, bound, _ = serve(store=store, port=port, **serve_kwargs)
    if srv.cacher is not None:
        for kind in warm_kinds:
            srv.cacher.cache_for(kind)
    return srv, bound, store


def frontend_health_lines() -> List[str]:
    """Follower-read lag/fan-out counters for the SIGUSR2 dump."""
    lines: List[str] = []
    for snap in (
        metrics.snapshot_gauges("follower_read_"),
        metrics.snapshot_counters("follower_read_"),
    ):
        for name, labels, value in snap:
            lines.append(metrics.format_series_line(name, labels, value))
    return lines


# the balancer needs no state here, but NotPrimary is what a frontend
# surfaces when its primary link is gone mid-write; re-exported so fleet
# tooling imports one module
__all__ = [
    "FollowerReadStore",
    "serve_frontend",
    "serve_follower_frontend",
    "frontend_health_lines",
    "NotPrimary",
]
