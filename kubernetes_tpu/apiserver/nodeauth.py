"""Node authorizer (graph-lite).

Reference: plugin/pkg/auth/authorizer/node/node_authorizer.go:1 — a
dedicated authorizer for kubelet identities (user ``system:node:<name>``,
group ``system:nodes``) that scopes every request to the node's own
objects via a graph of node → pods → secrets/configmaps/PVCs edges. This
build keeps the decision table but derives the graph edges on demand from
the store (clusters here are orders of magnitude smaller than the
reference's 5k-node graph-index target; a per-request pod scan is cheap
and always current).

Decision table for node users (everything else: deny):
  * nodes / node leases: read any, write only its OWN
  * pods: read any; create allowed (mirror pods — NodeRestriction
    admission validates the binding); update/patch/delete only pods BOUND
    to this node; ``bindings`` never (binding is the scheduler's verb)
  * secrets / configmaps / persistentvolumeclaims: get only when some pod
    bound to this node references the object (the graph edge)
  * events, certificatesigningrequests: create (status reporting, cert
    renewal)
  * services / endpoints / endpointslices: read (the proxier dataplane)

Non-node users are delegated to the wrapped authorizer (RBAC): the union
semantics of the reference's authorizer chain, with the node decision
authoritative for node users so a broad RBAC group grant can never hand a
kubelet another node's pods.
"""

from __future__ import annotations

from typing import Optional

from .admission import NODE_USER_PREFIX, NODES_GROUP

_READ_VERBS = frozenset({"get", "list", "watch"})
_READ_OK = frozenset(
    {"nodes", "pods", "services", "endpoints", "endpointslices", "csinodes",
     "runtimeclasses"}
)
_GRAPH_KINDS = frozenset({"secrets", "configmaps", "persistentvolumeclaims"})


class NodeAwareAuthorizer:
    """Wraps an RBAC authorizer; node-identity requests get the node
    decision table above (authoritative), everything else delegates."""

    def __init__(self, rbac, server):
        self.rbac = rbac
        self.server = server

    # -- graph edges ---------------------------------------------------------

    def _node_pods(self, node_name: str, namespace: Optional[str]):
        try:
            pods, _ = self.server.list("pods")
        except Exception:
            return []
        return [
            p
            for p in pods
            if p.spec.node_name == node_name
            and (not namespace or p.metadata.namespace == namespace)
        ]

    def _pod_references(self, pod, resource: str, name: str) -> bool:
        # Volume's union members are plain NAME strings (api/objects.py
        # Volume: persistent_volume_claim / config_map / secret)
        for v in pod.spec.volumes:
            if resource == "persistentvolumeclaims":
                if v.persistent_volume_claim == name:
                    return True
            elif resource == "secrets" and v.secret == name:
                return True
            elif resource == "configmaps" and v.config_map == name:
                return True
        return False

    def _graph_allows(
        self, node_name: str, resource: str, namespace: str, name: str
    ) -> bool:
        if not name:
            return False  # no list/watch over graph kinds (reference denies)
        return any(
            self._pod_references(p, resource, name)
            for p in self._node_pods(node_name, namespace)
        )

    def _pod_bound_to(self, node_name: str, namespace: str, name: str) -> bool:
        try:
            pod = self.server.get("pods", namespace or "default", name)
        except Exception:
            # unknown pod: fail CLOSED for writes (the reference denies
            # when the graph has no edge)
            return False
        return pod.spec.node_name == node_name

    # -- decision ------------------------------------------------------------

    def _authorize_node(
        self, node_name: str, verb: str, resource: str, namespace: str, name: str
    ) -> bool:
        if resource in _GRAPH_KINDS:
            return verb == "get" and self._graph_allows(
                node_name, resource, namespace, name
            )
        if resource == "certificatesigningrequests":
            # before the generic read branch: credential renewal needs
            # create + get (poll for the signed credential)
            return verb in ("create", "get")
        if verb in _READ_VERBS:
            return resource in _READ_OK or resource == "leases"
        if resource == "nodes":
            return verb in ("create", "update", "patch") and (
                not name or name == node_name
            )
        if resource == "leases":
            return verb in ("create", "update", "patch") and (
                not name or name == node_name
            )
        if resource == "pods":
            if verb == "create":
                return True  # mirror pods; NodeRestriction checks the body
            if verb in ("update", "patch", "delete"):
                return self._pod_bound_to(node_name, namespace, name)
            return False
        if resource == "bindings":
            return False  # binding is the scheduler's verb, never a kubelet's
        if resource == "events":
            return verb == "create"
        return False

    def authorize(self, user, verb, resource, namespace, name="") -> bool:
        if (
            user is not None
            and NODES_GROUP in getattr(user, "groups", ())
            and user.name.startswith(NODE_USER_PREFIX)
        ):
            node_name = user.name[len(NODE_USER_PREFIX):]
            return self._authorize_node(
                node_name, verb, resource, namespace or "", name
            )
        if self.rbac is None:
            return True
        return self.rbac.authorize(user, verb, resource, namespace, name)

    # delegate the RBAC-management surface so callers can keep using
    # authz.bind(...) unchanged
    def bind(self, subject, rule) -> None:
        self.rbac.bind(subject, rule)
