"""Length-prefixed compact binary codec for watch streams.

The newline-delimited JSON watch wire (rest.py `_serve_watch`) pays a
full ``codec.encode(obj)`` + ``json.dumps`` per DELIVERY: with 10k
informers on one kind, one store event becomes 10k independent JSON
encodes — pure overhead, measured as the dominant fan-out cost in the
PR-6 readpath bench. This codec replaces the per-delivery encode with a
per-EVENT frame: the object payload is the existing protobuf-wire
envelope (api/protocodec.py, ~3x denser than JSON), the frame is
immutable bytes, and the frame is memoized ON the Event object — the
same Event instance sits in every CacheWatcher queue of a kind's
fan-out, so N streams ship the SAME bytes and the encode happens once.

Negotiation (rest.py / apiserver/client.py): the client offers
``Accept: application/vnd.kubernetes-tpu.watchstream``; a server that
speaks it answers with that Content-Type and binary frames; an old
server ignores the unknown Accept and answers JSON lines — the client
branches on the RESPONSE Content-Type, so JSON remains the default and
the universal wire fallback (mixed fleets mid-upgrade just work).

Frame layout (all integers big-endian):

    frame    := type(1) length(4) payload(length)
    type 'A' | 'M' | 'D'  object event; payload = protocodec envelope
    type 'B'              bookmark; payload = rv as 8-byte unsigned
    type 'J'              JSON fallback event (custom resources — the
                          protocodec cannot encode Unstructured, same
                          restriction as the reference); payload is the
                          JSON line the legacy wire would have carried

Import-light (stdlib + api codecs): the balancer and chaos children
decode frames without touching jax.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Optional, Tuple

from ..api import protocodec
from ..api import serialization as codec
from ..runtime.watch import ADDED, BOOKMARK, DELETED, MODIFIED

# offered by clients in Accept, answered by speakers in Content-Type
WATCH_CONTENT_TYPE = "application/vnd.kubernetes-tpu.watchstream"

_HEADER = struct.Struct(">cI")
_RV = struct.Struct(">Q")

_TYPE_TO_CODE = {ADDED: b"A", MODIFIED: b"M", DELETED: b"D"}
_CODE_TO_TYPE = {b"A": ADDED, b"M": MODIFIED, b"D": DELETED}

# Event attribute carrying the memoized frame: the cacher fan-out hands
# ONE Event instance to every client queue, so the first stream to
# serialize it pays the encode and the rest ship the same bytes. The
# race (two streams encoding concurrently) is benign — both produce
# identical immutable frames and either may win the attribute store.
_MEMO_ATTR = "_watch_binframe"


def _frame(code: bytes, payload: bytes) -> bytes:
    return _HEADER.pack(code, len(payload)) + payload


def bookmark_frame(rv: int) -> bytes:
    """Bookmarks are per-stream (the idle heartbeat advertises each
    stream's own last-written rv) — never memoized, always cheap."""
    return _frame(b"B", _RV.pack(rv))


def event_frame(ev: Any) -> bytes:
    """The event's wire frame, memoized on the Event object itself."""
    memo: Optional[bytes] = getattr(ev, _MEMO_ATTR, None)
    if memo is not None:
        return memo
    obj = ev.object
    from ..api import objects as v1api

    code = _TYPE_TO_CODE.get(ev.type)
    if code is not None and not isinstance(obj, v1api.Unstructured):
        frame = _frame(code, protocodec.encode_obj(obj))
    else:
        # custom resources (and any future event type) ride the JSON
        # fallback frame: the codec stays total over the object model
        frame = _frame(
            b"J",
            json.dumps({"type": ev.type, "object": codec.encode(obj)}).encode(),
        )
    try:
        setattr(ev, _MEMO_ATTR, frame)
    except AttributeError:
        pass  # slotted/foreign event object: serve unmemoized
    return frame


def read_frame(fp) -> Optional[Tuple[str, int, Any]]:
    """Decode one frame from a file-like stream (the client pump side).

    Returns (event_type, rv, object) — object is None for bookmarks (rv
    carries the payload), a DECODED typed object for binary frames, and
    a JSON-ready dict for 'J' fallback frames (the caller resolves the
    kind, exactly like the legacy JSON line pump). Returns None on a
    clean EOF at a frame boundary; a truncated frame raises ValueError
    (the stream died mid-frame — a resume, not an EOF).
    """
    head = fp.read(_HEADER.size)
    if not head:
        return None
    if len(head) < _HEADER.size:
        raise ValueError("truncated watch frame header")
    code, length = _HEADER.unpack(head)
    payload = fp.read(length)
    if len(payload) < length:
        raise ValueError("truncated watch frame payload")
    if code == b"B":
        return BOOKMARK, _RV.unpack(payload)[0], None
    if code == b"J":
        msg = json.loads(payload)
        obj = msg.get("object") or {}
        rv = int((obj.get("metadata") or {}).get("resourceVersion", 0) or 0)
        return msg.get("type", ""), rv, obj
    ev_type = _CODE_TO_TYPE.get(code)
    if ev_type is None:
        raise ValueError(f"unknown watch frame type {code!r}")
    obj = protocodec.decode_obj(payload)
    return ev_type, int(obj.metadata.resource_version or 0), obj
