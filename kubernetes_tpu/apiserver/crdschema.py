"""CRD versions + OpenAPI-v3 structural-schema validation.

The apiextensions-apiserver's per-version serving and validation role
(reference staging/src/k8s.io/apiextensions-apiserver/pkg/apiserver/
validation/validation.go and customresource_handler.go): a CRD may
declare multiple versions, each served or not, exactly one marked
`storage`; custom-resource writes are validated against the request
version's schema and persisted at the storage version ("None"
conversion strategy — only the apiVersion field is rewritten, which is
all this single-internal-version build needs).

CRD spec.versions accepts both shorthand strings ("v1" — served,
first entry is storage) and objects
{name, served, storage, schema: {openAPIV3Schema: {...}}}, mirroring
the reference's v1beta1 `version` shorthand vs v1 `versions` list.

The schema validator covers the structural subset the reference
enforces most: type, properties, required, items, enum, minimum/
maximum, minLength/maxLength, minItems/maxItems, pattern,
additionalProperties (false or schema). `x-kubernetes-*` extensions
are accepted and ignored.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from ..api.validation import ValidationError
from ..client.apiserver import NotFound

_TYPE_CHECKS = {
    "object": lambda v: isinstance(v, dict),
    "array": lambda v: isinstance(v, list),
    "string": lambda v: isinstance(v, str),
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "number": lambda v: isinstance(v, (int, float)) and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
}


def normalize_versions(crd) -> List[Dict[str, Any]]:
    """spec.versions (strings or dicts) -> [{name, served, storage,
    schema}]. Exactly one storage version: explicit flags win; with pure
    shorthand the FIRST entry is storage (deterministic, documented)."""
    out: List[Dict[str, Any]] = []
    raw = list(getattr(crd.spec, "versions", None) or [])
    for entry in raw:
        if isinstance(entry, str):
            out.append(
                {"name": entry, "served": True, "storage": False, "schema": None}
            )
        elif isinstance(entry, dict):
            schema = (entry.get("schema") or {}).get("openAPIV3Schema") or None
            out.append(
                {
                    "name": entry.get("name", ""),
                    "served": bool(entry.get("served", True)),
                    "storage": bool(entry.get("storage", False)),
                    "schema": schema,
                }
            )
    if out and not any(v["storage"] for v in out):
        out[0]["storage"] = True
    return out


def version_entry(crd, version: str) -> Optional[Dict[str, Any]]:
    for v in normalize_versions(crd):
        if v["name"] == version:
            return v
    return None


def storage_api_version(crd) -> str:
    vs = normalize_versions(crd)
    name = next((v["name"] for v in vs if v["storage"]), "v1")
    group = crd.spec.group
    return f"{group}/{name}" if group else name


def validate_schema(value: Any, schema: Dict[str, Any], path: str = "") -> List[str]:
    """Value vs OpenAPI-v3 subset; returns human-readable violations."""
    errs: List[str] = []
    where = path or "<root>"
    t = schema.get("type")
    if t:
        check = _TYPE_CHECKS.get(t)
        if check is None:
            errs.append(f"{where}: unknown schema type {t!r}")
            return errs
        if not check(value):
            errs.append(
                f"{where}: expected {t}, got {type(value).__name__}"
            )
            return errs
    if "enum" in schema and value not in schema["enum"]:
        errs.append(f"{where}: {value!r} not in enum {schema['enum']}")
    if isinstance(value, str):
        if "minLength" in schema and len(value) < schema["minLength"]:
            errs.append(f"{where}: shorter than minLength {schema['minLength']}")
        if "maxLength" in schema and len(value) > schema["maxLength"]:
            errs.append(f"{where}: longer than maxLength {schema['maxLength']}")
        if "pattern" in schema and not re.search(schema["pattern"], value):
            errs.append(f"{where}: does not match pattern {schema['pattern']!r}")
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        if "minimum" in schema and value < schema["minimum"]:
            errs.append(f"{where}: {value} < minimum {schema['minimum']}")
        if "maximum" in schema and value > schema["maximum"]:
            errs.append(f"{where}: {value} > maximum {schema['maximum']}")
    if isinstance(value, list):
        if "minItems" in schema and len(value) < schema["minItems"]:
            errs.append(f"{where}: fewer than minItems {schema['minItems']}")
        if "maxItems" in schema and len(value) > schema["maxItems"]:
            errs.append(f"{where}: more than maxItems {schema['maxItems']}")
        items = schema.get("items")
        if items:
            for idx, item in enumerate(value):
                errs.extend(validate_schema(item, items, f"{path}[{idx}]"))
    if isinstance(value, dict):
        props = schema.get("properties") or {}
        for k, sub in props.items():
            if k in value:
                errs.extend(
                    validate_schema(value[k], sub, f"{path}.{k}" if path else k)
                )
        for k in schema.get("required", []):
            if k not in value:
                errs.append(f"{where}: missing required property {k!r}")
        addl = schema.get("additionalProperties", True)
        if addl is False:
            for k in value:
                if k not in props:
                    errs.append(f"{where}: unknown property {k!r}")
        elif isinstance(addl, dict):
            for k, v in value.items():
                if k not in props:
                    errs.extend(
                        validate_schema(v, addl, f"{path}.{k}" if path else k)
                    )
    return errs


def find_crd(store, resource: str, group: Optional[str]):
    """The established CRD claiming (group, plural), or None."""
    try:
        crds, _ = store.list("customresourcedefinitions")
    except Exception:
        return None
    for c in crds:
        if c.spec.names.plural != resource:
            continue
        if group is None or c.spec.group == group:
            return c
    return None


def check_cr_write(crd, version: Optional[str], body: dict) -> str:
    """Gate one custom-resource write: the request version must be
    served, and the non-metadata content must satisfy that version's
    schema. Returns the storage apiVersion to persist at. Raises
    ValidationError (HTTP 400) on violation, NotFound (404) for an
    unserved/unknown version."""
    ver = version
    if ver is None:
        # core-path writes (/api/v1/<plural>) carry the version in the
        # body's apiVersion, if any
        av = body.get("apiVersion", "")
        ver = av.rsplit("/", 1)[-1] if av else None
    if ver is not None:
        entry = version_entry(crd, ver)
        if entry is None or not entry["served"]:
            raise NotFound(
                f"version {ver!r} of {crd.spec.names.plural} is not served"
            )
        schema = entry["schema"]
    else:
        # versionless shorthand write: validate against the storage schema
        vs = normalize_versions(crd)
        entry = next((v for v in vs if v["storage"]), None)
        schema = entry["schema"] if entry else None
    if schema:
        content = {
            k: v
            for k, v in body.items()
            if k not in ("metadata", "kind", "apiVersion")
        }
        errs = validate_schema(content, schema)
        if errs:
            raise ValidationError(
                f"{crd.spec.names.kind or crd.spec.names.plural} invalid: "
                + "; ".join(errs[:8])
            )
    return storage_api_version(crd)
