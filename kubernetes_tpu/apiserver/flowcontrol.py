"""API Priority and Fairness (APF), simplified.

Reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol — requests are
classified by FlowSchemas into PriorityLevels; each level owns a share of
the server's concurrency budget so a flood at one level (a misbehaving
workload) cannot starve another (leader-election renewals, node
heartbeats). This build keeps the classification + per-level isolated
concurrency + bounded queuing, and simplifies the shuffle-sharded fair
queues within a level to a FIFO wait on the level's semaphore (documented
divergence: per-flow fairness INSIDE one level is approximate; isolation
BETWEEN levels is exact).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.apiserver.flowcontrol")


GAUGE_SEATS_IN_USE = "apiserver_flowcontrol_seats_in_use"  # {priority_level}
GAUGE_SEATS_TOTAL = "apiserver_flowcontrol_seats_total"    # {priority_level}


@dataclass
class PriorityLevel:
    """One isolated concurrency pool (flowcontrol.PriorityLevelConfiguration:
    assured concurrency shares). Seat occupancy is published as gauges so
    a read storm's pressure — and the isolation protecting heartbeats and
    binds from it — is visible in /metrics and the SIGUSR2 dump."""

    name: str
    shares: int = 20
    exempt: bool = False
    _sem: Optional[threading.Semaphore] = field(default=None, repr=False)
    _seats: int = field(default=0, repr=False)
    _in_use: int = field(default=0, repr=False)
    _mu: Optional[threading.Lock] = field(default=None, repr=False)

    def setup(self, total_concurrency: int, total_shares: int) -> None:
        self._mu = threading.Lock()
        if self.exempt:
            self._sem = None
            return
        n = max(1, round(total_concurrency * self.shares / max(1, total_shares)))
        self._seats = n
        self._sem = threading.BoundedSemaphore(n)
        metrics.set_gauge(GAUGE_SEATS_TOTAL, n, {"priority_level": self.name})
        metrics.set_gauge(GAUGE_SEATS_IN_USE, 0, {"priority_level": self.name})

    def _occupy(self, delta: int) -> None:
        if self._mu is None:
            return
        with self._mu:
            self._in_use += delta
            metrics.set_gauge(
                GAUGE_SEATS_IN_USE, self._in_use, {"priority_level": self.name}
            )


@dataclass
class FlowSchema:
    """Maps requests to a priority level (flowcontrol.FlowSchema). The
    matcher sees (user, resource, verb); user may be None (anonymous)."""

    name: str
    priority_level: str
    match: Callable = lambda user, resource, verb: True


def _is_system_user(user) -> bool:
    return user is not None and (
        user.name.startswith("system:kube-")
        or user.name.startswith("system:node")
        or "system:nodes" in user.groups
    )


def default_levels() -> List[PriorityLevel]:
    # bootstrap levels (apiserver/pkg/apis/flowcontrol/bootstrap): shares
    # proportioned like the reference's defaults, plus a dedicated pool
    # for watch INITIALIZATION (list-from-cache replay + window replay):
    # 10k cold informers connecting at once contend for watch-init seats
    # against each other, never against the system pool serving kubelet
    # heartbeats and scheduler binds
    return [
        PriorityLevel("exempt", exempt=True),
        PriorityLevel("system", shares=30),
        PriorityLevel("leader-election", shares=10),
        PriorityLevel("watch-init", shares=10),
        PriorityLevel("workload-high", shares=40),
        PriorityLevel("global-default", shares=20),
    ]


def default_schemas() -> List[FlowSchema]:
    # watch-init sits AFTER the system schemas: a system component's watch
    # re-establishment rides its protected pool, while workload informers'
    # watch inits — the storm-shaped traffic — are penned into watch-init
    return [
        FlowSchema(
            "exempt",
            "exempt",
            lambda u, r, v: u is not None and "system:masters" in u.groups,
        ),
        FlowSchema(
            "system-leader-election",
            "leader-election",
            lambda u, r, v: r == "leases" and _is_system_user(u),
        ),
        FlowSchema("system-nodes", "system", lambda u, r, v: _is_system_user(u)),
        FlowSchema("watch-init", "watch-init", lambda u, r, v: v == "watch"),
        FlowSchema(
            "service-accounts",
            "workload-high",
            lambda u, r, v: u is not None
            and u.name.startswith("system:serviceaccount:"),
        ),
        FlowSchema("global-default", "global-default", lambda u, r, v: True),
    ]


class RequestRejected(Exception):
    def __init__(self, level: str):
        super().__init__(
            f"too many requests at priority level {level!r}; retry later"
        )
        self.level = level


class FlowController:
    """Classify + admit. Usage:
        lv = fc.begin(user, resource, verb)   # may raise RequestRejected
        try: ... finally: fc.end(lv)
    """

    def __init__(
        self,
        total_concurrency: int = 400,
        queue_wait_s: float = 0.05,
        levels: Optional[Sequence[PriorityLevel]] = None,
        schemas: Optional[Sequence[FlowSchema]] = None,
    ):
        self.levels = {l.name: l for l in (levels or default_levels())}
        self.schemas = list(schemas or default_schemas())
        self._warned_schemas: set = set()
        self.queue_wait_s = queue_wait_s
        total_shares = sum(l.shares for l in self.levels.values() if not l.exempt)
        for l in self.levels.values():
            l.setup(total_concurrency, total_shares)

    def classify(self, user, resource: str, verb: str) -> PriorityLevel:
        for s in self.schemas:
            if s.match(user, resource, verb):
                lv = self.levels.get(s.priority_level)
                if lv is not None:
                    return lv
                if s.name not in self._warned_schemas:
                    # once per schema: this fires on EVERY matching request
                    self._warned_schemas.add(s.name)
                    logger.warning(
                        "FlowSchema %s references unknown priority level %s",
                        s.name,
                        s.priority_level,
                    )
        # fail-CLOSED fallback: global-default (or any throttled level),
        # never the dict's first entry — with default_levels() that is
        # 'exempt', which would silently unlimit misconfigured traffic
        lv = self.levels.get("global-default")
        if lv is not None:
            return lv
        non_exempt = [l for l in self.levels.values() if not l.exempt]
        return non_exempt[0] if non_exempt else next(iter(self.levels.values()))

    def begin(self, user, resource: str, verb: str) -> PriorityLevel:
        lv = self.classify(user, resource, verb)
        if lv.exempt or lv._sem is None:
            return lv
        # bounded queuing: a short FIFO wait absorbs bursts (the queued
        # request IS the reference's queued request; the wait bound is its
        # queue-length limit), then reject
        if not lv._sem.acquire(timeout=self.queue_wait_s):
            metrics.inc(
                "apiserver_flowcontrol_rejected_requests_total",
                {"priority_level": lv.name},
            )
            raise RequestRejected(lv.name)
        metrics.inc(
            "apiserver_flowcontrol_dispatched_requests_total",
            {"priority_level": lv.name},
        )
        lv._occupy(+1)
        return lv

    def end(self, level: PriorityLevel) -> None:
        if not level.exempt and level._sem is not None:
            level._sem.release()
            level._occupy(-1)
