"""Deterministic network + process fault injection for chaos tests.

The control-plane chaos suites inject faults at the store boundary
(ChaosStore) and the data-plane suites at the device boundary
(device_faults.py); this module is the NETWORK sibling — a toxiproxy-
style in-process TCP proxy that sits between REST clients and the API
server and injects the failure modes only a real deployment sees:

  * **latency / jitter / bandwidth caps** (``set_latency`` /
    ``set_bandwidth``): per-chunk shaping on both directions — the slow-
    network soak regime where every request still succeeds, eventually;
  * **mid-request connection resets** (``reset_next_requests``): the
    client's request head is read, then the connection is RST before
    anything reaches the server — the request was provably NOT applied;
  * **response blackholes** (``blackhole_next_responses``): the request
    is forwarded and processed upstream, the RESPONSE is discarded and
    the client leg reset — the write APPLIED, the ack was lost. The
    unknown-outcome case the PR-3 read-back reconciler exists for;
  * **full partitions** (``partition``): ``refuse`` closes the listener
    (fast ECONNREFUSED — the request never left the client) and severs
    live flows with RST; ``drop`` silently stops forwarding so both
    sides hang until their own timeouts, like real packet loss;
  * **half-open streams** (``half_open_upstream``): the server-facing
    leg is severed with RST while the client leg stays open and silent —
    from the server's side the client vanished without FIN, exactly the
    half-open TCP shape the watch bookmark heartbeat must reap.

Everything is armed-counter indexed (consumed in connection-accept
order) with an optional request-head ``match`` — never random. A chaos
scenario is a statement, not a dice roll. Deterministic jitter comes
from a fixed LCG sequence.

Process-chaos helpers (``sigstop``/``sigcont``/``sigkill``) ride along:
a SIGSTOP'd scheduler is the canonical zombie ex-leader — frozen through
its lease expiry, resumed with a stale fence.

Import-light on purpose (stdlib + utils.metrics, NO jax): the proxy runs
in tier-1 tests and in child processes that never touch a device.
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import struct
import threading
import time
from collections import deque
from typing import List, Optional

from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.testing.netchaos")

# TCP connections accepted by the proxy (one REST request each for the
# keep-alive-free urllib client; watch streams hold one for their life)
COUNTER_CONNECTIONS = "netchaos_connections_total"
# injected faults by kind: reset, blackhole, partition_refuse,
# partition_drop, partition_parked, half_open
COUNTER_FAULTS = "netchaos_injected_faults_total"  # {kind}
# bytes relayed per direction (up = client->server, down = server->client)
COUNTER_BYTES = "netchaos_bytes_total"  # {direction}

_CHUNK = 65536


def _rst_close(sock: Optional[socket.socket]) -> None:
    """Sever a connection abruptly. SO_LINGER(1,0) + close sends RST when
    this thread owns the socket; the shutdown in between is load-bearing
    for the CROSS-THREAD case — a close() while another thread is blocked
    in recv() on the same fd leaves the kernel socket alive (the in-flight
    syscall holds a file reference) and the peer would never see the
    connection die at all. shutdown() tears the connection down at the
    kernel level immediately (the peer sees FIN/RST and any blocked recv
    wakes), close then releases the fd."""
    if sock is None:
        return
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _quiet_close(sock: Optional[socket.socket]) -> None:
    if sock is None:
        return
    try:
        sock.close()
    except OSError:
        pass


def _close_listener(lst: Optional[socket.socket]) -> None:
    """Close a LISTENING socket so the port actually refuses. A plain
    close() while another thread is blocked in accept() leaves the
    kernel socket alive (the in-flight syscall holds a file reference)
    and the port keeps completing handshakes into the backlog; shutdown
    first wakes the blocked accept and drops the listen queue."""
    if lst is None:
        return
    try:
        lst.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    _quiet_close(lst)


class _ArmedFault:
    """One armed fault: consumed by the first matching connection."""

    def __init__(self, kind: str, match: Optional[bytes]):
        self.kind = kind
        self.match = match

    def matches(self, head: bytes) -> bool:
        return self.match is None or self.match in head


class _Pair:
    """One client<->upstream connection pair."""

    def __init__(self, client: socket.socket, upstream: socket.socket,
                 blackhole_down: bool):
        self.client = client
        self.upstream = upstream
        self.blackhole_down = blackhole_down
        self.stale = False  # severed by partition/half-open: pumps bail
        self._pumps_left = 2
        self._lock = threading.Lock()

    def pump_done(self) -> None:
        with self._lock:
            self._pumps_left -= 1
            last = self._pumps_left == 0
        if last:
            _quiet_close(self.client)
            _quiet_close(self.upstream)


class NetChaosProxy:
    """Deterministic TCP proxy between a REST client and the API server.

    Usage::

        proxy = NetChaosProxy("127.0.0.1", api_port)
        proxy.start()
        client = RESTClient(f"http://127.0.0.1:{proxy.port}")
        proxy.blackhole_next_responses(1, match=b"/binding")
        ...
        proxy.stop()
    """

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        listen_host: str = "127.0.0.1",
    ):
        self.upstream_host = upstream_host
        self.upstream_port = upstream_port
        self.listen_host = listen_host
        self.port: int = 0
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._pairs: List[_Pair] = []
        self._parked: List[socket.socket] = []  # partition(drop) arrivals
        self._half_open_clients: List[socket.socket] = []
        self._faults: deque = deque()  # armed _ArmedFaults, FIFO
        self._latency_s = 0.0
        self._jitter_s = 0.0
        self._bandwidth_bps: Optional[float] = None
        self._partitioned: Optional[str] = None  # None | "refuse" | "drop"
        self._jitter_state = 0x9E3779B9  # fixed LCG seed: deterministic
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "NetChaosProxy":
        self._bind_listener()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="netchaos-accept"
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            lst, self._listener = self._listener, None
            pairs = list(self._pairs)
            parked = list(self._parked)
            half = list(self._half_open_clients)
            self._pairs.clear()
            self._parked.clear()
            self._half_open_clients.clear()
        _close_listener(lst)
        for p in pairs:
            _rst_close(p.client)
            _rst_close(p.upstream)
        for s in parked + half:
            _rst_close(s)

    def _bind_listener(self) -> None:
        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind((self.listen_host, self.port))
        lst.listen(128)
        self.port = lst.getsockname()[1]
        self._listener = lst

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            lst = self._listener
            if lst is None:  # partition(refuse): port answers ECONNREFUSED
                time.sleep(0.02)
                continue
            try:
                client, _ = lst.accept()
            except OSError:
                continue  # listener closed under us (partition/stop)
            threading.Thread(
                target=self._serve_conn,
                args=(client,),
                daemon=True,
                name="netchaos-conn",
            ).start()

    # -- fault knobs ---------------------------------------------------------

    def set_latency(self, latency_s: float, jitter_s: float = 0.0) -> None:
        """Per-chunk one-way latency (+deterministic jitter in
        [-jitter_s, +jitter_s] from a fixed LCG sequence)."""
        with self._lock:
            self._latency_s = latency_s
            self._jitter_s = jitter_s

    def set_bandwidth(self, bytes_per_s: Optional[float]) -> None:
        """Cap relay throughput (None = unlimited)."""
        with self._lock:
            self._bandwidth_bps = bytes_per_s

    def reset_next_requests(
        self, n: int = 1, match: Optional[bytes] = None
    ) -> None:
        """RST the next n (matching) connections after reading the
        request head and BEFORE forwarding anything: the request provably
        never reached the server."""
        with self._lock:
            for _ in range(n):
                self._faults.append(_ArmedFault("reset", match))

    def blackhole_next_responses(
        self, n: int = 1, match: Optional[bytes] = None
    ) -> None:
        """Forward the next n (matching) requests upstream, DISCARD the
        responses, and RST the client leg: write applied, ack lost — the
        unknown-outcome case."""
        with self._lock:
            for _ in range(n):
                self._faults.append(_ArmedFault("blackhole", match))

    def clear_faults(self) -> int:
        """Disarm every armed (unconsumed) reset/blackhole fault; returns
        how many were still pending. The heal knob for bind-path-only
        fault storms (arm N resets, let the reconciler spin, clear)."""
        with self._lock:
            n = len(self._faults)
            self._faults.clear()
            return n

    def partition(self, mode: str = "refuse") -> None:
        """Sever the proxy path. ``refuse``: the listener closes (new
        connects get ECONNREFUSED instantly — the request never left the
        client) and live flows are RST. ``drop``: the listener keeps
        accepting but nothing is ever answered and live flows silently
        stop forwarding — both sides hang until their own timeouts, like
        real packet loss. heal() restores the path on the SAME port."""
        if mode not in ("refuse", "drop"):
            raise ValueError(f"unknown partition mode {mode!r}")
        metrics.inc(COUNTER_FAULTS, {"kind": f"partition_{mode}"})
        with self._lock:
            self._partitioned = mode
            pairs = list(self._pairs)
            lst = None
            if mode == "refuse":
                lst, self._listener = self._listener, None
            for p in pairs:
                p.stale = True
        _close_listener(lst)
        if mode == "refuse":
            for p in pairs:
                _rst_close(p.client)
                _rst_close(p.upstream)

    def heal(self) -> None:
        """Restore the path. Connections that spanned the partition get
        RST (a real heal finds the peer's TCP state gone); parked
        connections are released the same way so blocked clients error
        out now instead of at their timeout."""
        with self._lock:
            mode, self._partitioned = self._partitioned, None
            stale = [p for p in self._pairs if p.stale]
            self._pairs = [p for p in self._pairs if not p.stale]
            parked, self._parked = self._parked, []
            need_listener = self._listener is None and not self._stop.is_set()
        for p in stale:
            _rst_close(p.client)
            _rst_close(p.upstream)
        for s in parked:
            _rst_close(s)
        if need_listener:
            self._bind_listener()
        if mode:
            logger.info("netchaos: healed %s partition on :%d", mode, self.port)

    def half_open_upstream(self) -> int:
        """Sever every live upstream leg with RST while keeping the
        client legs open and silent: the server sees a vanished peer
        (next write fails — what the bookmark heartbeat reaper detects),
        the client sees a half-open stream. Returns severed count."""
        with self._lock:
            pairs = [p for p in self._pairs if not p.stale]
            for p in pairs:
                p.stale = True
                self._half_open_clients.append(p.client)
            self._pairs = [p for p in self._pairs if p not in pairs]
        for p in pairs:
            _rst_close(p.upstream)
            metrics.inc(COUNTER_FAULTS, {"kind": "half_open"})
        return len(pairs)

    def kill_connections(self) -> int:
        """RST both legs of every live pair (crash-shaped disconnect)."""
        with self._lock:
            pairs, self._pairs = self._pairs, []
        for p in pairs:
            p.stale = True
            _rst_close(p.client)
            _rst_close(p.upstream)
        return len(pairs)

    def live_connections(self) -> int:
        with self._lock:
            return len([p for p in self._pairs if not p.stale])

    # -- internals -----------------------------------------------------------

    def _take_fault(self, head: bytes) -> Optional[str]:
        with self._lock:
            for f in list(self._faults):
                if f.matches(head):
                    self._faults.remove(f)
                    return f.kind
        return None

    def _next_jitter(self) -> float:
        """Deterministic jitter in [-jitter_s, +jitter_s]: a fixed 32-bit
        LCG, never wall-clock or random — reruns shape identically."""
        with self._lock:
            self._jitter_state = (
                self._jitter_state * 1664525 + 1013904223
            ) & 0xFFFFFFFF
            unit = self._jitter_state / 0xFFFFFFFF  # [0, 1]
            return (unit * 2.0 - 1.0) * self._jitter_s

    def _shape(self, n_bytes: int) -> None:
        with self._lock:
            latency = self._latency_s
            bw = self._bandwidth_bps
        delay = 0.0
        if latency > 0.0:
            delay += max(0.0, latency + self._next_jitter())
        if bw:
            delay += n_bytes / bw
        if delay > 0.0:
            time.sleep(delay)

    def _serve_conn(self, client: socket.socket) -> None:
        metrics.inc(COUNTER_CONNECTIONS)
        if self._partitioned == "drop":
            # packet-loss partition: the connect succeeded (SYN queue)
            # but nothing is ever answered — park until heal/stop
            metrics.inc(COUNTER_FAULTS, {"kind": "partition_parked"})
            with self._lock:
                self._parked.append(client)
            return
        try:
            head = client.recv(_CHUNK)
        except OSError:
            _quiet_close(client)
            return
        if not head:
            _quiet_close(client)
            return
        fault = self._take_fault(head)
        if fault == "reset":
            # mid-request reset: nothing was forwarded — the server never
            # saw this request
            metrics.inc(COUNTER_FAULTS, {"kind": "reset"})
            _rst_close(client)
            return
        try:
            upstream = socket.create_connection(
                (self.upstream_host, self.upstream_port), timeout=5.0
            )
        except OSError:
            _rst_close(client)
            return
        if fault == "blackhole":
            metrics.inc(COUNTER_FAULTS, {"kind": "blackhole"})
        pair = _Pair(client, upstream, blackhole_down=(fault == "blackhole"))
        with self._lock:
            if self._stop.is_set():
                pass  # closed below by the pumps exiting immediately
            self._pairs.append(pair)
        metrics.inc(COUNTER_BYTES, {"direction": "up"}, by=float(len(head)))
        self._shape(len(head))
        try:
            upstream.sendall(head)
        except OSError:
            pair.stale = True
        threading.Thread(
            target=self._pump, args=(pair, "up"), daemon=True,
            name="netchaos-up",
        ).start()
        threading.Thread(
            target=self._pump, args=(pair, "down"), daemon=True,
            name="netchaos-down",
        ).start()

    @staticmethod
    def _looks_like_request_head(chunk: bytes) -> bool:
        return chunk.split(b" ", 1)[0] in (
            b"GET", b"POST", b"PUT", b"DELETE", b"HEAD", b"PATCH",
        )

    def _pump(self, pair: _Pair, direction: str) -> None:
        src = pair.client if direction == "up" else pair.upstream
        dst = pair.upstream if direction == "up" else pair.client
        try:
            while not self._stop.is_set() and not pair.stale:
                try:
                    chunk = src.recv(_CHUNK)
                except OSError:
                    break
                if not chunk:
                    break
                if self._partitioned or pair.stale:
                    # partition landed while we were blocked in recv:
                    # silently drop the data — both sides now hang
                    return  # pair closed by heal()/stop()
                if (
                    direction == "up"
                    and self._faults
                    and self._looks_like_request_head(chunk)
                ):
                    # keep-alive clients carry MANY requests per
                    # connection: armed faults must match each request
                    # head, not just the connection's first (accept-time)
                    # one. A body-continuation chunk never starts with a
                    # method verb and is skipped.
                    fault = self._take_fault(chunk)
                    if fault == "reset":
                        # dropped BEFORE forwarding: the server never saw
                        # this request (the client, on its reused socket,
                        # cannot know that — honest classification there
                        # is unknown-outcome)
                        metrics.inc(COUNTER_FAULTS, {"kind": "reset"})
                        self._terminate_pair(pair)
                        return
                    if fault == "blackhole":
                        metrics.inc(COUNTER_FAULTS, {"kind": "blackhole"})
                        pair.blackhole_down = True
                metrics.inc(
                    COUNTER_BYTES, {"direction": direction},
                    by=float(len(chunk)),
                )
                self._shape(len(chunk))
                if direction == "down" and pair.blackhole_down:
                    # response discarded: write applied, ack lost. The
                    # pair dies NOW — a keep-alive upstream never EOFs on
                    # its own, and the client must see a dead connection,
                    # not a stall
                    self._terminate_pair(pair)
                    return
                try:
                    dst.sendall(chunk)
                except OSError:
                    break
        finally:
            if pair.stale or self._partitioned:
                return  # sockets are owned by heal()/stop() now
            if direction == "down" and pair.blackhole_down:
                # response fully swallowed: the ack is LOST, not late —
                # the client must see a dead connection, not a clean EOF
                # it could mistake for a framed empty response
                _rst_close(pair.client)
            else:
                try:
                    dst.shutdown(socket.SHUT_WR)  # relay the EOF
                except OSError:
                    pass
            pair.pump_done()
            with self._lock:
                if pair in self._pairs and pair._pumps_left == 0:
                    self._pairs.remove(pair)

    def _terminate_pair(self, pair: _Pair) -> None:
        """RST both legs of a pair from inside a pump (injected fault).
        Marking the pair stale FIRST makes both pumps' cleanup paths
        stand down (stale = sockets owned elsewhere — here): the sibling
        wakes on its dead socket and simply exits."""
        pair.stale = True
        _rst_close(pair.client)
        _rst_close(pair.upstream)
        with self._lock:
            if pair in self._pairs:
                self._pairs.remove(pair)


# -- load balancer: the proxy machinery run in reverse -----------------------

# client connections relayed per backend, and backends skipped because a
# connect failed (the backend is cooling down / dead)
COUNTER_BALANCER_CONNS = "netchaos_balancer_connections_total"  # {backend}
COUNTER_BALANCER_SKIPS = "netchaos_balancer_backend_skips_total"  # {backend}


class LoadBalancerProxy:
    """One listener, N upstream backends: the serving-tier balancer.

    The same accept/pump machinery as :class:`NetChaosProxy`, inverted —
    instead of one upstream with injected faults, each accepted client
    connection is relayed verbatim to a backend chosen by policy:

      * ``round_robin``: rotate through the backend list;
      * ``least_conn`` (default): the backend with the fewest live
        relayed connections — watch streams hold their connection for
        life, so connection count is the honest load signal for a mixed
        request/stream fleet.

    A backend whose connect fails is put on a cooldown
    (``retry_cooldown_s``) and the next candidate is tried in the same
    accept — a killed frontend drains out of rotation within one failed
    connect, and its in-flight streams RST so clients resume (the
    RESTClient watch pump reconnects through the balancer and lands on a
    healthy sibling, whose watch cache replays the gap).

    Deliberately a dumb L4 relay: HTTP keep-alive, chunked watch
    streams, and the binary watch codec all pass through untouched.
    """

    def __init__(
        self,
        backends: List[Tuple[str, int]],
        listen_host: str = "127.0.0.1",
        policy: str = "least_conn",
        retry_cooldown_s: float = 1.0,
        connect_timeout_s: float = 2.0,
    ):
        if policy not in ("round_robin", "least_conn"):
            raise ValueError(f"unknown balance policy {policy!r}")
        self.backends = [tuple(b) for b in backends]
        self.listen_host = listen_host
        self.policy = policy
        self.retry_cooldown_s = retry_cooldown_s
        self.connect_timeout_s = connect_timeout_s
        self.port: int = 0
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._pairs: List[tuple] = []  # (backend, _Pair)
        self._cooldown: dict = {}  # backend -> monotonic deadline
        self._rr = 0

    def start(self) -> "LoadBalancerProxy":
        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind((self.listen_host, self.port))
        lst.listen(512)
        self.port = lst.getsockname()[1]
        self._listener = lst
        threading.Thread(
            target=self._accept_loop, daemon=True, name="lb-accept"
        ).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            lst, self._listener = self._listener, None
            pairs = [p for _b, p in self._pairs]
            self._pairs.clear()
        _close_listener(lst)
        for p in pairs:
            _rst_close(p.client)
            _rst_close(p.upstream)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            lst = self._listener
            if lst is None:
                return
            try:
                client, _ = lst.accept()
            except OSError:
                continue
            threading.Thread(
                target=self._serve_conn, args=(client,), daemon=True,
                name="lb-conn",
            ).start()

    def _candidates(self) -> List[Tuple[str, int]]:
        """Backends in try-order for one accept, cooled-down ones last
        (still tried: with every backend cooling, a liveness probe beats
        refusing service)."""
        now = time.monotonic()
        with self._lock:
            live = [
                (b, sum(1 for bb, p in self._pairs if bb == b))
                for b in self.backends
            ]
            if self.policy == "round_robin":
                self._rr += 1
                n = len(self.backends)
                order = [live[(self._rr + i) % n][0] for i in range(n)]
            else:
                order = [b for b, _cnt in sorted(live, key=lambda x: x[1])]
            cooling = {
                b for b, dl in self._cooldown.items() if dl > now
            }
        return [b for b in order if b not in cooling] + [
            b for b in order if b in cooling
        ]

    def _serve_conn(self, client: socket.socket) -> None:
        for backend in self._candidates():
            try:
                upstream = socket.create_connection(
                    backend, timeout=self.connect_timeout_s
                )
            except OSError:
                metrics.inc(
                    COUNTER_BALANCER_SKIPS,
                    {"backend": f"{backend[0]}:{backend[1]}"},
                )
                with self._lock:
                    self._cooldown[backend] = (
                        time.monotonic() + self.retry_cooldown_s
                    )
                continue
            upstream.settimeout(None)
            metrics.inc(
                COUNTER_BALANCER_CONNS,
                {"backend": f"{backend[0]}:{backend[1]}"},
            )
            with self._lock:
                self._cooldown.pop(backend, None)
            pair = _Pair(client, upstream, blackhole_down=False)
            with self._lock:
                self._pairs.append((backend, pair))
            threading.Thread(
                target=self._pump, args=(pair, "up"), daemon=True,
                name="lb-up",
            ).start()
            threading.Thread(
                target=self._pump, args=(pair, "down"), daemon=True,
                name="lb-down",
            ).start()
            return
        _rst_close(client)  # every backend down: fail fast, not hang

    def _pump(self, pair: _Pair, direction: str) -> None:
        src = pair.client if direction == "up" else pair.upstream
        dst = pair.upstream if direction == "up" else pair.client
        try:
            while not self._stop.is_set():
                try:
                    chunk = src.recv(_CHUNK)
                except OSError:
                    break
                if not chunk:
                    break
                try:
                    dst.sendall(chunk)
                except OSError:
                    break
        finally:
            try:
                dst.shutdown(socket.SHUT_WR)  # relay the EOF
            except OSError:
                pass
            pair.pump_done()
            with self._lock:
                self._pairs = [
                    (b, p)
                    for b, p in self._pairs
                    if not (p is pair and p._pumps_left == 0)
                ]

    def live_connections(self) -> int:
        with self._lock:
            return len(self._pairs)

    def connections_per_backend(self) -> dict:
        with self._lock:
            out: dict = {}
            for b, _p in self._pairs:
                out[b] = out.get(b, 0) + 1
            return out


# -- process chaos -----------------------------------------------------------


def _pid_of(proc) -> int:
    return proc if isinstance(proc, int) else proc.pid


def sigstop(proc) -> None:
    """Freeze a process (SIGSTOP): the zombie-maker. A stopped scheduler
    keeps its lease token but cannot renew; resumed after the standby
    promoted, its late binds carry a stale fence."""
    os.kill(_pid_of(proc), signal.SIGSTOP)


def sigcont(proc) -> None:
    """Resume a SIGSTOP'd process: the zombie walks."""
    os.kill(_pid_of(proc), signal.SIGCONT)


def sigkill(proc) -> None:
    """Hard-kill (SIGKILL): no release, no cleanup — the crash shape."""
    os.kill(_pid_of(proc), signal.SIGKILL)
