"""Plugin-registration DSL + framework builder for tests.

Reference: pkg/scheduler/testing/framework_helpers.go:25,37 — tests declare
exactly the plugins they exercise via RegisterPluginFunc entries and get a
runnable Framework, instead of hand-assembling registry + plugin set.

    fw = new_framework(
        register_queue_sort("PrioritySort"),
        register_filter("NodeResourcesFit"),
        register_score("NodeResourcesLeastAllocated", weight=2),
        register_plugin("Custom", lambda ctx: MyPlugin(), filter=True),
        context={"snapshot_getter": lambda: snap},
    )
"""

from __future__ import annotations

from typing import Callable, Optional

from ..scheduler.framework.registry import PluginSet, Registry, default_registry
from ..scheduler.framework.runtime import Framework

RegisterFunc = Callable[[Registry, PluginSet], None]


def register_plugin(
    name: str,
    factory: Optional[Callable] = None,
    *,
    queue_sort: bool = False,
    pre_filter: bool = False,
    filter: bool = False,  # noqa: A002 — mirrors the extension point name
    pre_score: bool = False,
    score: bool = False,
    weight: float = 1.0,
    reserve: bool = False,
    permit: bool = False,
    pre_bind: bool = False,
    bind: bool = False,
    post_bind: bool = False,
    unreserve: bool = False,
) -> RegisterFunc:
    """General entry: optionally override the factory, and enable the named
    extension points for the plugin."""

    def apply(reg: Registry, ps: PluginSet) -> None:
        if factory is not None:
            reg[name] = factory
        if queue_sort:
            ps.queue_sort = [name]
        if pre_filter:
            ps.pre_filter.append(name)
        if filter:
            ps.filter.append(name)
        if pre_score:
            ps.pre_score.append(name)
        if score:
            ps.score.append((name, weight))
        if reserve:
            ps.reserve.append(name)
        if permit:
            ps.permit.append(name)
        if pre_bind:
            ps.pre_bind.append(name)
        if bind:
            ps.bind = [name]
        if post_bind:
            ps.post_bind.append(name)
        if unreserve:
            ps.unreserve.append(name)

    return apply


def register_queue_sort(name: str, factory=None) -> RegisterFunc:
    return register_plugin(name, factory, queue_sort=True)


def register_pre_filter(name: str, factory=None) -> RegisterFunc:
    return register_plugin(name, factory, pre_filter=True)


def register_filter(name: str, factory=None) -> RegisterFunc:
    return register_plugin(name, factory, filter=True)


def register_score(name: str, factory=None, weight: float = 1.0) -> RegisterFunc:
    return register_plugin(name, factory, score=True, weight=weight)


def register_bind(name: str, factory=None) -> RegisterFunc:
    return register_plugin(name, factory, bind=True)


def new_framework(*registrations: RegisterFunc, context: Optional[dict] = None) -> Framework:
    """Framework with ONLY the registered plugins enabled (st.NewFramework)."""
    reg = default_registry()
    ps = PluginSet(
        queue_sort=["PrioritySort"],
        filter=[],
        bind=["DefaultBinder"],
    )
    for r in registrations:
        r(reg, ps)
    return Framework(registry=reg, plugin_set=ps, context=context or {})
