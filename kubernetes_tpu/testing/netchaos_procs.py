"""Child-process entries for the multi-process network-chaos suite.

`tests/test_chaos_net.py` runs the control plane as REAL OS processes —
API server, leader scheduler, standby scheduler — talking REST through a
NetChaosProxy, so partitions, SIGSTOP zombies, and lost responses hit
actual sockets and actual process boundaries (nothing in-process chaos
can fake). This module is what those children execute:

    python -m kubernetes_tpu.testing.netchaos_procs apiserver \
        --port P --ledger /path/ledger.jsonl
    python -m kubernetes_tpu.testing.netchaos_procs scheduler \
        --server http://127.0.0.1:PROXY --identity a --debug-port D \
        [--zombie-hold] [--lease-duration 1.5 ...]

The API-server child wraps its store in a **LedgerStore**: every bind
application and acknowledgment appends a JSONL record, and every
fence rejection is recorded with the rejected identity — the cross-
process equivalent of ChaosStore's in-memory ledger, so the test can
prove "every pod bound exactly once, every zombie bind fenced" from one
file regardless of which process did what.

The scheduler child wires a replica the way cmd/scheduler.py does
(standby first, the election winner promotes with the fence) and adds a
debug HTTP port: GET /status (role, counters) and POST /bind (drive one
binding through the replica's OWN fence-attaching seam — how the test
makes a resumed zombie attempt a late REST bind deterministically).
``--zombie-hold`` keeps the scheduling loops running after the elector
loses leadership: the deliberately misbehaving replica the fence exists
to stop (a well-behaved one shuts down, and then there is nothing left
to fence).
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger("kubernetes_tpu.testing.netchaos_procs")


# -- apiserver child ---------------------------------------------------------


def _ledger_store(ledger_path: str):
    """APIServer subclass appending bind outcomes to a JSONL ledger."""
    from ..client.apiserver import APIServer, LeaderFenced

    lock = threading.Lock()
    fh = open(ledger_path, "a", encoding="utf-8")

    from ..utils.tracing import trace_for_binding

    class LedgerStore(APIServer):
        def _ledger(self, record: dict) -> None:
            with lock:
                fh.write(json.dumps(record, separators=(",", ":")) + "\n")
                fh.flush()

        def bind_pods(self, bindings, fence=None):
            # trace context (re-established by the REST route from the
            # X-Trace-Context header) resolves per binding: the ledger
            # proves a scheduler-minted trace id survived the wire
            traces = {id(b): trace_for_binding(b) for b in bindings}
            try:
                errors = super().bind_pods(bindings, fence=fence)
            except LeaderFenced:
                self._ledger(
                    {
                        "event": "fenced",
                        "identity": getattr(fence, "identity", None),
                        "transitions": getattr(fence, "transitions", None),
                        "uids": [b.pod_uid for b in bindings],
                        "traces": [traces[id(b)] for b in bindings],
                    }
                )
                raise
            for b, err in zip(bindings, errors):
                if err is None:
                    # the in-process store applies and acks atomically;
                    # both records keep the ledger shape aligned with
                    # ChaosStore (applied_binds / acked_binds)
                    self._ledger(
                        {
                            "event": "applied",
                            "uid": b.pod_uid,
                            "name": f"{b.pod_namespace}/{b.pod_name}",
                            "node": b.target_node,
                            "trace": traces[id(b)],
                        }
                    )
                    self._ledger(
                        {
                            "event": "acked",
                            "uid": b.pod_uid,
                            "name": f"{b.pod_namespace}/{b.pod_name}",
                            "node": b.target_node,
                            "trace": traces[id(b)],
                        }
                    )
            return errors

    return LedgerStore()


def run_apiserver(
    port: int, ledger: str, repl_port: int = 0, cluster_size: int = 0
) -> None:
    from ..apiserver.rest import serve

    store = _ledger_store(ledger)
    repl_bound = 0
    if repl_port or cluster_size:
        # the serving-tier fleet: followers tail this listener and serve
        # commit-gated reads (apiserver/frontend.FollowerReadStore)
        from ..runtime.replication import ReplicationListener

        listener = ReplicationListener(
            port=repl_port, cluster_size=cluster_size or None
        )
        listener.attach(store)
        repl_bound = listener.address[1]
    srv, bound_port, _ = serve(store=store, port=port, bookmark_period_s=0.5)
    print(f"READY apiserver {bound_port} {repl_bound}", flush=True)
    threading.Event().wait()


# -- serving-tier children (frontend / follower) ------------------------------


class _BenchStatsHandler(BaseHTTPRequestHandler):
    """Tiny stats endpoint for the serving bench: the hollow-watcher
    drain pool's delivery latencies + counts, as JSON."""

    server_version = "serving-bench-stats"

    def log_message(self, *args):
        pass

    def do_GET(self):
        stats = self.server.stats_fn()
        body = json.dumps(stats).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _hollow_watcher_pool(cacher, kind: str, n_watchers: int, n_sampled: int = 64):
    """Attach n hollow watchers to this frontend's OWN cache fan-out
    (the kubemark discipline: real queues, a shared drain pool instead
    of n threads) and return a stats closure for /bench-stats."""
    import time as _time

    from ..runtime.watch import BOOKMARK

    watchers = [cacher.watch(kind) for _ in range(n_watchers)]
    sampled = watchers[: min(n_sampled, n_watchers)]
    latencies: list = []
    drained = [0]
    lock = threading.Lock()

    def drain_loop(ws):
        while True:
            idle = True
            for w in ws:
                ev = w.get(timeout=0)
                while ev is not None:
                    idle = False
                    if ev.type != BOOKMARK and ev.ts:
                        with lock:
                            latencies.append(_time.monotonic() - ev.ts)
                            drained[0] += 1
                    ev = w.get(timeout=0)
            if idle:
                _time.sleep(0.001)

    drainers = 4
    chunk = max(1, len(sampled) // drainers)
    for i in range(0, len(sampled), chunk):
        threading.Thread(
            target=drain_loop, args=(sampled[i : i + chunk],), daemon=True
        ).start()

    def stats():
        from ..utils.metrics import metrics

        with lock:
            lat = sorted(latencies)
        events = metrics.counter(
            "watch_cache_events_total", {"kind": kind}
        )
        return {
            "watchers": n_watchers,
            "sampled": len(sampled),
            "drained": drained[0],
            "cache_events": events,
            "delivery_p50_ms": lat[int(0.5 * len(lat))] * 1e3 if lat else 0.0,
            "delivery_p99_ms": (
                lat[min(int(0.99 * len(lat)), len(lat) - 1)] * 1e3
                if lat
                else 0.0
            ),
        }

    return stats


def _serve_stats(stats_fn) -> int:
    dbg = ThreadingHTTPServer(("127.0.0.1", 0), _BenchStatsHandler)
    dbg.daemon_threads = True
    dbg.stats_fn = stats_fn
    threading.Thread(target=dbg.serve_forever, daemon=True).start()
    return dbg.server_address[1]


def _relay_fleet_stats(relay, kind: str):
    """Stats closure aggregating the relay worker fleet for /bench-stats:
    client/delivery totals summed across workers, per-process CPU seconds
    kept per worker so benches can prove CPU stays flat vs watcher count."""

    def stats():
        from ..utils.metrics import metrics

        per_worker = relay.worker_stats()
        frames = metrics.counter(
            "relay_frames_published_total", {"kind": kind}
        )
        return {
            "relay_port": relay.port,
            "workers": len(per_worker),
            "frames_published": frames,
            "clients": sum(w.get("clients", 0) for w in per_worker),
            "hollow": sum(w.get("hollow", 0) for w in per_worker),
            "delivered": sum(w.get("delivered", 0) for w in per_worker),
            "evicted_slow": sum(w.get("evicted_slow", 0) for w in per_worker),
            "shed": sum(w.get("shed", 0) for w in per_worker),
            "worker_cpu_s": [w.get("cpu_s", 0.0) for w in per_worker],
            "per_worker": per_worker,
        }

    return stats


def run_frontend(
    primary: str,
    port: int,
    hollow_watchers: int,
    watch_kind: str,
    relay_workers: int = 0,
    relay_port: int = 0,
    relay_hollow: int = 0,
    tls_cert: str = "",
    tls_key: str = "",
) -> None:
    from ..apiserver.frontend import serve_frontend

    srv, bound, _client = serve_frontend(
        primary,
        port=port,
        bookmark_period_s=0.5,
        relay_workers=relay_workers,
        relay_port=relay_port,
        relay_kinds=(watch_kind,),
        relay_hollow_clients=relay_hollow,
        tls_cert=tls_cert or None,
        tls_key=tls_key or None,
    )
    stats_port = 0
    if hollow_watchers:
        stats_fn = _hollow_watcher_pool(
            srv.cacher, watch_kind, hollow_watchers
        )
        stats_port = _serve_stats(stats_fn)
    elif srv.relay is not None:
        stats_port = _serve_stats(_relay_fleet_stats(srv.relay, watch_kind))
    rport = srv.relay.port if srv.relay is not None else 0
    # trailing tokens are ignored by pre-relay READY parsers
    print(f"READY frontend {bound} {stats_port} {rport}", flush=True)
    threading.Event().wait()


def run_follower(
    primary: str,
    repl_host: str,
    repl_port: int,
    port: int,
    node_id: int,
    hollow_watchers: int,
    watch_kind: str,
) -> None:
    from ..apiserver.frontend import serve_follower_frontend
    from ..runtime.replication import Follower

    follower = Follower((repl_host, repl_port), node_id=node_id).start()
    if not follower.wait_synced(15.0):
        raise SystemExit("follower never synced")
    srv, bound, _store = serve_follower_frontend(
        follower, primary, port=port, bookmark_period_s=0.5
    )
    stats_port = 0
    if hollow_watchers:
        stats_fn = _hollow_watcher_pool(
            srv.cacher, watch_kind, hollow_watchers
        )
        stats_port = _serve_stats(stats_fn)
    print(f"READY follower {bound} {stats_port}", flush=True)
    threading.Event().wait()


# -- scheduler child ---------------------------------------------------------


class _DebugHandler(BaseHTTPRequestHandler):
    server_version = "netchaos-scheduler-debug"

    def log_message(self, *args):
        pass

    def _json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.startswith("/traces"):
            # this replica's trace ring (slowest-N / by-id): the test's
            # window into which process actually minted a given id
            from urllib.parse import parse_qs, urlparse

            from ..utils.debugserver import traces_payload

            u = urlparse(self.path)
            q = {k: v[-1] for k, v in parse_qs(u.query).items()}
            code, payload = traces_payload(q)
            return self._json(code, payload)
        if self.path != "/status":
            return self._json(404, {"error": "unknown path"})
        from ..utils.metrics import metrics

        rep = self.server.replica
        fenced = sum(
            v
            for k, v in metrics.dump().items()
            if k.startswith("scheduler_ha_fenced_binds_total")
        )
        self._json(
            200,
            {
                "identity": rep.identity,
                "leader": rep.elector.is_leader,
                "promoted": rep.promoted.is_set(),
                "deposed": rep.deposed.is_set(),
                "fenced_binds": fenced,
                "pending_binds": rep.sched._ridethrough.depth,
            },
        )

    def do_POST(self):
        if self.path != "/bind":
            return self._json(404, {"error": "unknown path"})
        from ..api.objects import Binding
        from ..client.apiserver import LeaderFenced
        from ..utils.tracing import tracer

        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length) or b"{}")
        binding = Binding(
            pod_name=body["name"],
            pod_namespace=body.get("namespace", "default"),
            pod_uid=body.get("uid", ""),
            target_node=body["node"],
        )
        # mint a trace for the forced bind so the cross-process trace
        # assertion holds for the FENCED path too: the ledger record the
        # store writes must carry this id
        trace_id = tracer.start(
            "pod", f"{binding.pod_namespace}/{binding.pod_name}"
        )
        # the replica's OWN fence-attaching seam: exactly the write a
        # zombie's late wave would issue — including the wave path's
        # fence handling (_on_fenced_binds drops the placement and counts
        # under the transport label)
        sched = self.server.replica.sched
        try:
            errs = sched._bind_pods_fenced([binding])
        except LeaderFenced as e:
            from ..api.objects import ObjectMeta, Pod
            from ..scheduler.queue.scheduling_queue import QueuedPodInfo

            sched._on_fenced_binds(
                [
                    QueuedPodInfo(
                        pod=Pod(
                            metadata=ObjectMeta(
                                name=binding.pod_name,
                                namespace=binding.pod_namespace,
                                uid=binding.pod_uid,
                            )
                        ),
                        trace_id=trace_id,
                    )
                ]
            )
            tracer.finish(trace_id, outcome="fenced")
            return self._json(
                200,
                {"result": "LeaderFenced", "message": str(e),
                 "trace": trace_id},
            )
        except Exception as e:
            tracer.finish(trace_id, outcome=type(e).__name__)
            return self._json(
                200,
                {"result": type(e).__name__, "message": str(e),
                 "trace": trace_id},
            )
        err = errs[0] if errs else None
        if err is None:
            tracer.finish(trace_id, outcome="bound")
            return self._json(200, {"result": "ok", "trace": trace_id})
        tracer.finish(trace_id, outcome=type(err).__name__)
        return self._json(
            200,
            {"result": type(err).__name__, "message": str(err),
             "trace": trace_id},
        )


class _Replica:
    """One scheduler replica over REST: standby first, the election
    winner promotes with the fence (the cmd/scheduler.py wiring)."""

    def __init__(self, server_url: str, identity: str, lease_cfg,
                 zombie_hold: bool):
        from ..apiserver.client import RESTClient
        from ..client.leaderelection import LeaderElector
        from ..scheduler import KubeSchedulerConfiguration, Scheduler

        self.identity = identity
        self.client = RESTClient(server_url, timeout=5.0)
        cfg = KubeSchedulerConfiguration(use_device=False)
        self.sched = Scheduler(self.client, cfg)
        self.sched.start_standby(identity=identity)
        self.promoted = threading.Event()
        self.deposed = threading.Event()

        def on_started():
            self.sched.promote(fence=self.elector.fence())
            self.promoted.set()

        def on_stopped():
            self.deposed.set()
            if zombie_hold:
                # the misbehaving replica: keeps scheduling with its
                # stale fence — the store must stop it, not its manners
                logger.error(
                    "%s deposed; ZOMBIE-HOLD: scheduling loops stay up",
                    identity,
                )
                return
            logger.error("%s deposed; stopping scheduling", identity)
            self.sched.stop()

        self.elector = LeaderElector(
            self.client,
            lease_cfg,
            on_started_leading=on_started,
            on_stopped_leading=on_stopped,
        )
        self._thread = threading.Thread(
            target=self.elector.run, daemon=True, name=f"elector-{identity}"
        )
        self._thread.start()


def run_scheduler(
    server_url: str,
    identity: str,
    debug_port: int,
    lease_duration: float,
    renew_deadline: float,
    retry_period: float,
    zombie_hold: bool,
) -> None:
    from ..client.leaderelection import LeaderElectionConfig

    lease_cfg = LeaderElectionConfig(
        identity=identity,
        lease_duration=lease_duration,
        renew_deadline=renew_deadline,
        retry_period=retry_period,
    )
    replica = _Replica(server_url, identity, lease_cfg, zombie_hold)
    dbg = ThreadingHTTPServer(("127.0.0.1", debug_port), _DebugHandler)
    dbg.daemon_threads = True
    dbg.replica = replica
    threading.Thread(target=dbg.serve_forever, daemon=True).start()
    print(f"READY scheduler {identity} {dbg.server_address[1]}", flush=True)
    threading.Event().wait()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="netchaos-procs")
    sub = parser.add_subparsers(dest="role", required=True)
    ap = sub.add_parser("apiserver")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--ledger", required=True)
    ap.add_argument("--repl-port", type=int, default=0)
    ap.add_argument("--cluster-size", type=int, default=0)
    fr = sub.add_parser("frontend")
    fr.add_argument("--primary", required=True)
    fr.add_argument("--port", type=int, default=0)
    fr.add_argument("--hollow-watchers", type=int, default=0)
    fr.add_argument("--watch-kind", default="pods")
    fr.add_argument("--relay-workers", type=int, default=0)
    fr.add_argument("--relay-port", type=int, default=0)
    fr.add_argument("--relay-hollow", type=int, default=0)
    fr.add_argument("--tls-cert", default="")
    fr.add_argument("--tls-key", default="")
    fo = sub.add_parser("follower")
    fo.add_argument("--primary", required=True)
    fo.add_argument("--repl-host", default="127.0.0.1")
    fo.add_argument("--repl-port", type=int, required=True)
    fo.add_argument("--port", type=int, default=0)
    fo.add_argument("--node-id", type=int, default=1)
    fo.add_argument("--hollow-watchers", type=int, default=0)
    fo.add_argument("--watch-kind", default="pods")
    sp = sub.add_parser("scheduler")
    sp.add_argument("--server", required=True)
    sp.add_argument("--identity", required=True)
    sp.add_argument("--debug-port", type=int, default=0)
    sp.add_argument("--lease-duration", type=float, default=1.5)
    sp.add_argument("--renew-deadline", type=float, default=1.0)
    sp.add_argument("--retry-period", type=float, default=0.2)
    sp.add_argument("--zombie-hold", action="store_true", default=False)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.role == "apiserver":
        run_apiserver(
            args.port, args.ledger, args.repl_port, args.cluster_size
        )
    elif args.role == "frontend":
        run_frontend(
            args.primary,
            args.port,
            args.hollow_watchers,
            args.watch_kind,
            relay_workers=args.relay_workers,
            relay_port=args.relay_port,
            relay_hollow=args.relay_hollow,
            tls_cert=args.tls_cert,
            tls_key=args.tls_key,
        )
    elif args.role == "follower":
        run_follower(
            args.primary,
            args.repl_host,
            args.repl_port,
            args.port,
            args.node_id,
            args.hollow_watchers,
            args.watch_kind,
        )
    else:
        run_scheduler(
            args.server,
            args.identity,
            args.debug_port,
            args.lease_duration,
            args.renew_deadline,
            args.retry_period,
            args.zombie_hold,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
