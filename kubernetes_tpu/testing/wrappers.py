"""Fluent test builders: PodWrapper / NodeWrapper.

Reference: pkg/scheduler/testing/wrappers.go:136,361 — table-driven tests
build specs with chained wrappers instead of struct literals. Same shape
here; `.obj()` yields the real API object.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..api import objects as v1
from ..api.selectors import LabelSelector


class PodWrapper:
    def __init__(self, name: str = "pod", namespace: str = "default"):
        self._pod = v1.Pod(
            metadata=v1.ObjectMeta(name=name, namespace=namespace),
            spec=v1.PodSpec(containers=[v1.Container()]),
        )

    def obj(self) -> v1.Pod:
        return self._pod

    # -- metadata ------------------------------------------------------------

    def namespace(self, ns: str) -> "PodWrapper":
        self._pod.metadata.namespace = ns
        return self

    def label(self, key: str, value: str) -> "PodWrapper":
        self._pod.metadata.labels[key] = value
        return self

    def labels(self, labels: Dict[str, str]) -> "PodWrapper":
        self._pod.metadata.labels.update(labels)
        return self

    def annotation(self, key: str, value: str) -> "PodWrapper":
        self._pod.metadata.annotations[key] = value
        return self

    def owner(self, kind: str, name: str, controller: bool = True) -> "PodWrapper":
        self._pod.metadata.owner_references.append(
            v1.OwnerReference(kind=kind, name=name, controller=controller)
        )
        return self

    # -- spec ----------------------------------------------------------------

    def req(self, **resources) -> "PodWrapper":
        """`.req(cpu="500m", memory="1Gi")`"""
        self._pod.spec.containers[0].requests.update(resources)
        return self

    def container_image(self, image: str) -> "PodWrapper":
        self._pod.spec.containers[0].image = image
        return self

    def host_port(self, port: int, protocol: str = "TCP") -> "PodWrapper":
        self._pod.spec.containers[0].ports.append(
            v1.ContainerPort(container_port=port, host_port=port, protocol=protocol)
        )
        return self

    def node(self, name: str) -> "PodWrapper":
        self._pod.spec.node_name = name
        return self

    def node_selector(self, sel: Dict[str, str]) -> "PodWrapper":
        self._pod.spec.node_selector.update(sel)
        return self

    def priority(self, value: int) -> "PodWrapper":
        self._pod.spec.priority = value
        return self

    def scheduler_name(self, name: str) -> "PodWrapper":
        self._pod.spec.scheduler_name = name
        return self

    def toleration(
        self, key: str, operator: str = "Exists", value: str = "", effect: str = ""
    ) -> "PodWrapper":
        self._pod.spec.tolerations.append(
            v1.Toleration(key=key, operator=operator, value=value, effect=effect)
        )
        return self

    def _affinity(self) -> dict:
        a = self._pod.spec.affinity
        return {
            "node": a.node_affinity if a else None,
            "pod": a.pod_affinity if a else None,
            "anti": a.pod_anti_affinity if a else None,
        }

    def _set_affinity(self, node=None, pod=None, anti=None) -> None:
        cur = self._affinity()
        self._pod.spec.affinity = v1.Affinity(
            node_affinity=node or cur["node"],
            pod_affinity=pod or cur["pod"],
            pod_anti_affinity=anti or cur["anti"],
        )

    def pod_affinity(
        self, topology_key: str, match_labels: Dict[str, str]
    ) -> "PodWrapper":
        term = v1.PodAffinityTerm(
            label_selector=LabelSelector.make(match_labels=match_labels),
            topology_key=topology_key,
        )
        cur = self._affinity()["pod"]
        required = (cur.required if cur else ()) + (term,)
        self._set_affinity(
            pod=v1.PodAffinity(
                required=required, preferred=cur.preferred if cur else ()
            )
        )
        return self

    def pod_anti_affinity(
        self, topology_key: str, match_labels: Dict[str, str]
    ) -> "PodWrapper":
        term = v1.PodAffinityTerm(
            label_selector=LabelSelector.make(match_labels=match_labels),
            topology_key=topology_key,
        )
        cur = self._affinity()["anti"]
        required = (cur.required if cur else ()) + (term,)
        self._set_affinity(
            anti=v1.PodAntiAffinity(
                required=required, preferred=cur.preferred if cur else ()
            )
        )
        return self

    def spread_constraint(
        self,
        max_skew: int,
        topology_key: str,
        when_unsatisfiable: str = v1.DO_NOT_SCHEDULE,
        match_labels: Optional[Dict[str, str]] = None,
    ) -> "PodWrapper":
        self._pod.spec.topology_spread_constraints.append(
            v1.TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=topology_key,
                when_unsatisfiable=when_unsatisfiable,
                label_selector=(
                    LabelSelector.make(match_labels=match_labels)
                    if match_labels
                    else None
                ),
            )
        )
        return self

    def pvc(self, claim_name: str) -> "PodWrapper":
        self._pod.spec.volumes.append(
            v1.Volume(name=claim_name, persistent_volume_claim=claim_name)
        )
        return self

    # -- status --------------------------------------------------------------

    def phase(self, phase: str) -> "PodWrapper":
        self._pod.status.phase = phase
        return self

    def ip(self, pod_ip: str) -> "PodWrapper":
        self._pod.status.pod_ip = pod_ip
        return self


class NodeWrapper:
    def __init__(self, name: str = "node"):
        self._node = v1.Node(
            metadata=v1.ObjectMeta(name=name),
            spec=v1.NodeSpec(),
            status=v1.NodeStatus(
                allocatable={"cpu": "8", "memory": "32Gi", "pods": 110}
            ),
        )

    def obj(self) -> v1.Node:
        return self._node

    def label(self, key: str, value: str) -> "NodeWrapper":
        self._node.metadata.labels[key] = value
        return self

    def zone(self, zone: str) -> "NodeWrapper":
        return self.label("zone", zone)

    def capacity(self, **resources) -> "NodeWrapper":
        """`.capacity(cpu="4", memory="16Gi", pods=64)`"""
        self._node.status.allocatable.update(resources)
        return self

    def taint(
        self, key: str, value: str = "", effect: str = v1.TAINT_NO_SCHEDULE
    ) -> "NodeWrapper":
        self._node.spec.taints.append(v1.Taint(key, value, effect))
        return self

    def unschedulable(self, flag: bool = True) -> "NodeWrapper":
        self._node.spec.unschedulable = flag
        return self

    def image(self, name: str, size_bytes: int) -> "NodeWrapper":
        self._node.status.images.append(
            v1.ContainerImage(names=[name], size_bytes=size_bytes)
        )
        return self
