"""Lock-order watchdog: graftlint's runtime companion.

The static passes prove donation sites hold a generation lease and
dispatch loops never block — they cannot prove the LOCKS THEMSELVES are
acquired in a consistent global order. The PR-4 deadlock class (a
donating wave launch under the since-retired ``device_lock`` racing the
audit's gather under the cache lock) is an ordering property: it only
fires under the right interleaving, which a chaos run may never hit even
while the inversion sits in the code.

This module wraps the named production locks (the encoder's generation
bookkeeping lock ``encoder.gen_lock``, the scheduler cache lock, the
store lock, the watch cache's per-kind locks — each created through
:func:`named_lock`) so that, when the
watchdog is ENABLED, every successful acquisition records
``held → acquired`` edges into one process-wide lock-order graph. A new
edge that closes a cycle is a lock-order inversion — two code paths that
take the same pair of locks in opposite orders — and is recorded as a
violation immediately, even though the run did not deadlock. The chaos
suites (``make chaos-device``, ``make chaos-readpath``) enable the
watchdog for the whole module and assert the final graph is acyclic.

Disabled (production default) the wrapper costs one attribute load and
one boolean test per acquire/release. Locks of the same NAME share graph
nodes — per-kind cache locks all record as ``cacher.kind`` — which keeps
the graph readable and still catches cross-class inversions; a
same-name, cross-instance ABBA pair would be reported as a self-edge-
free cycle of length 2 only if some path orders the two names, which is
exactly the conservative behavior a watchdog wants.

Not thread-exhaustive: edges only exist for orders actually executed.
That is the point — it converts "the chaos suite happened not to
deadlock" into "no executed path can deadlock on these locks".
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

_enabled = False
_epoch = 0  # bumped by enable(): stale per-thread state self-invalidates
_graph_lock = threading.Lock()  # leaf lock: never held while acquiring others
_edges: Dict[str, Set[str]] = {}
_edge_sites: Dict[Tuple[str, str], int] = {}
_violations: List[List[str]] = []
_acquires: Dict[str, int] = {}
_tls = threading.local()


def _held() -> List[str]:
    """This thread's held-name stack for the CURRENT watchdog epoch.

    release() records nothing while disabled, so a thread that acquired
    under epoch N and releases after disable() would keep the name on
    its stack forever — and fabricate `stale -> X` edges (possibly a
    false cycle) in the next enabled suite in the same process. Epoch
    tagging drops such leftovers: losing a genuinely-still-held entry
    only costs a missed edge (false negative), never a false cycle."""
    if getattr(_tls, "epoch", None) != _epoch:
        _tls.epoch = _epoch
        _tls.held = []
    return _tls.held


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> dst in the edge graph (caller holds _graph_lock)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_acquire(name: str) -> None:
    with _graph_lock:
        _acquires[name] = _acquires.get(name, 0) + 1
    held = _held()
    if name in held:
        held.append(name)  # re-entrant: balance the stack, no new edges
        return
    uniq = []
    for h in held:
        if h != name and h not in uniq:
            uniq.append(h)
    if uniq:
        with _graph_lock:
            for h in uniq:
                if name in _edges.get(h, ()):
                    _edge_sites[(h, name)] += 1
                    continue
                # NEW edge h -> name: closing a cycle means some other
                # path already orders name before h — an inversion
                back = _find_path(name, h)
                _edges.setdefault(h, set()).add(name)
                _edge_sites[(h, name)] = 1
                if back is not None:
                    _violations.append(back + [name])
    held.append(name)


def _record_release(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class NamedLock:
    """A lock wrapper that reports acquisitions to the watchdog.

    Wraps an RLock by default. Compatible with ``threading.Condition``
    (delegates ``_release_save``/``_acquire_restore``/``_is_owned``
    straight through: a thread parked in ``wait()`` records nothing, and
    its thread-local held stack stays consistent because a blocked
    thread acquires nothing)."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner=None):
        self.name = name
        self._inner = inner if inner is not None else threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok and _enabled:
            _record_acquire(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        if _enabled:
            _record_release(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition compatibility: full release around wait() and silent
    # re-acquire on wake, both invisible to the order graph (see class
    # docstring)
    def _release_save(self):
        return self._inner._release_save()

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"NamedLock({self.name!r}, {self._inner!r})"


def named_lock(name: str, inner=None) -> NamedLock:
    """The factory production modules call where they used to call
    ``threading.RLock()`` directly. Always returns the wrapper — the
    enable flag is checked per acquisition, so chaos suites can flip the
    watchdog on for locks created long before."""
    return NamedLock(name, inner)


# -- watchdog control (chaos suites) -----------------------------------------


def enable() -> None:
    global _enabled, _epoch
    reset()
    _epoch += 1  # invalidate every thread's held stack from prior runs
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    with _graph_lock:
        _edges.clear()
        _edge_sites.clear()
        _violations.clear()
        _acquires.clear()


def edges() -> Dict[str, Set[str]]:
    with _graph_lock:
        return {k: set(v) for k, v in _edges.items()}


def edge_count() -> int:
    with _graph_lock:
        return sum(len(v) for v in _edges.values())


def acquire_count() -> int:
    """Total named-lock acquisitions observed while enabled — the
    instrumentation-is-alive signal (a suite can legitimately record
    zero EDGES when its locks never nest; it cannot record zero
    acquisitions)."""
    with _graph_lock:
        return sum(_acquires.values())


def acquires_by_name() -> Dict[str, int]:
    with _graph_lock:
        return dict(_acquires)


def violations() -> List[List[str]]:
    with _graph_lock:
        return [list(v) for v in _violations]


def find_cycle() -> Optional[List[str]]:
    """Any cycle in the full graph (independent of insert-time capture)."""
    with _graph_lock:
        color: Dict[str, int] = {}

        def dfs(node: str, path: List[str]) -> Optional[List[str]]:
            color[node] = 1
            for nxt in _edges.get(node, ()):
                if color.get(nxt, 0) == 1:
                    return path[path.index(nxt) :] + [nxt] if nxt in path else [nxt, node, nxt]
                if color.get(nxt, 0) == 0:
                    found = dfs(nxt, path + [nxt])
                    if found:
                        return found
            color[node] = 2
            return None

        for node in list(_edges):
            if color.get(node, 0) == 0:
                found = dfs(node, [node])
                if found:
                    return found
    return None


def assert_acyclic() -> None:
    """Fail loudly on any recorded inversion OR any cycle in the final
    graph. The edge list in the message is the repro: each edge names a
    lock order some real code path executed."""
    vio = violations()
    cyc = find_cycle()
    if vio or cyc:
        lines = ["lock-order watchdog: ORDER INVERSION DETECTED"]
        for v in vio:
            lines.append("  inversion: " + " -> ".join(v))
        if cyc and not vio:
            lines.append("  cycle: " + " -> ".join(cyc))
        with _graph_lock:
            for (a, b), n in sorted(_edge_sites.items()):
                lines.append(f"  edge {a} -> {b} (seen {n}x)")
        raise AssertionError("\n".join(lines))
