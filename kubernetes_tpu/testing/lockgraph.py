"""Lock-order watchdog: graftlint's runtime companion.

The static passes prove donation sites hold a generation lease and
dispatch loops never block — they cannot prove the LOCKS THEMSELVES are
acquired in a consistent global order. The PR-4 deadlock class (a
donating wave launch under the since-retired ``device_lock`` racing the
audit's gather under the cache lock) is an ordering property: it only
fires under the right interleaving, which a chaos run may never hit even
while the inversion sits in the code.

This module wraps the named production locks (the encoder's generation
bookkeeping lock ``encoder.gen_lock``, the scheduler cache lock, the
store lock, the watch cache's per-kind locks — each created through
:func:`named_lock`) so that, when the
watchdog is ENABLED, every successful acquisition records
``held → acquired`` edges into one process-wide lock-order graph. A new
edge that closes a cycle is a lock-order inversion — two code paths that
take the same pair of locks in opposite orders — and is recorded as a
violation immediately, even though the run did not deadlock. The chaos
suites (``make chaos-device``, ``make chaos-readpath``) enable the
watchdog for the whole module and assert the final graph is acyclic.

Disabled (production default) the wrapper costs one attribute load and
one boolean test per acquire/release. Locks of the same NAME share graph
nodes — per-kind cache locks all record as ``cacher.kind`` — which keeps
the graph readable and still catches cross-class inversions; a
same-name, cross-instance ABBA pair would be reported as a self-edge-
free cycle of length 2 only if some path orders the two names, which is
exactly the conservative behavior a watchdog wants.

Not thread-exhaustive: edges only exist for orders actually executed.
That is the point — it converts "the chaos suite happened not to
deadlock" into "no executed path can deadlock on these locks".

**Eraser mode** (the lockset sanitizer, ISSUE 12) is the watchdog's
second runtime check: where the order graph proves the locks are taken
in one order, Eraser mode proves shared attributes are taken under a
lock AT ALL. Production classes register their shared mutable
attributes with :func:`track_attrs`; each becomes a data descriptor
that, while the sanitizer is enabled, records the classic Eraser state
machine per ``(class, attr)``:

  * exclusive to the first accessing thread → nothing tracked (init
    writes are free);
  * a second thread arrives → the candidate lockset C(v) starts as the
    named locks held at that access and is intersected at every
    subsequent access from any thread;
  * C(v) empty once the attribute is shared → a RACE is recorded:
    attribute, both stack tips (the previous access and the one that
    emptied the set), and both locksets. Tracked attributes are exactly
    the ones graftlint's guarded-by pass proved lock-guarded, so a
    lock-free READ is as much a contract violation as a write — no
    write requirement, unlike classic Eraser. ``lockgraph_races_total``
    counts each distinct racy attribute once per epoch.

The same autouse fixtures that assert the order graph is acyclic assert
zero races (``assert_clean``), so `make chaos-device`, `chaos-readpath`
and `chaos-ha` now machine-check the guarded-by contract graftlint
pass 6 infers statically. Disabled (the production default) a tracked
attribute costs one descriptor indirection and one boolean test per
access; untracked attributes cost nothing.
"""

from __future__ import annotations

import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_enabled = False
_eraser = False
_epoch = 0  # bumped by enable(): stale per-thread state self-invalidates
_graph_lock = threading.Lock()  # leaf lock: never held while acquiring others
_edges: Dict[str, Set[str]] = {}
_edge_sites: Dict[Tuple[str, str], int] = {}
_violations: List[List[str]] = []
_acquires: Dict[str, int] = {}
# Eraser state lives ON each tracked instance (__dict__[_STATE_SLOT],
# attr -> _AttrState): the exclusive-to-one-thread phase (constructor
# writes) is an INSTANCE property, and a global map keyed by id() would
# let a freed object's shared state bleed into a new instance reusing
# the same address — the long multi-suite chaos runs hit exactly that.
_STATE_SLOT = "_lockgraph_attr_state"
_attr_accesses = 0
_races: List[dict] = []
_tls = threading.local()


def _held() -> List[str]:
    """This thread's held-name stack for the CURRENT watchdog epoch.

    release() records nothing while disabled, so a thread that acquired
    under epoch N and releases after disable() would keep the name on
    its stack forever — and fabricate `stale -> X` edges (possibly a
    false cycle) in the next enabled suite in the same process. Epoch
    tagging drops such leftovers: losing a genuinely-still-held entry
    only costs a missed edge (false negative), never a false cycle."""
    if getattr(_tls, "epoch", None) != _epoch:
        _tls.epoch = _epoch
        _tls.held = []
    return _tls.held


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> dst in the edge graph (caller holds _graph_lock)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_acquire(name: str) -> None:
    with _graph_lock:
        _acquires[name] = _acquires.get(name, 0) + 1
    held = _held()
    if name in held:
        held.append(name)  # re-entrant: balance the stack, no new edges
        return
    uniq = []
    for h in held:
        if h != name and h not in uniq:
            uniq.append(h)
    if uniq:
        with _graph_lock:
            for h in uniq:
                if name in _edges.get(h, ()):
                    _edge_sites[(h, name)] += 1
                    continue
                # NEW edge h -> name: closing a cycle means some other
                # path already orders name before h — an inversion
                back = _find_path(name, h)
                _edges.setdefault(h, set()).add(name)
                _edge_sites[(h, name)] = 1
                if back is not None:
                    _violations.append(back + [name])
    held.append(name)


def _record_release(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


# -- Eraser mode: the lockset sanitizer ---------------------------------------


class _AttrState:
    __slots__ = (
        "epoch",
        "first_thread",
        "shared",
        "lockset",
        "reported",
        "last_site",
        "last_lockset",
        "last_thread",
        "last_write",
    )

    def __init__(self, epoch: int, tid: object):
        self.epoch = epoch
        self.first_thread = tid
        self.shared = False
        self.lockset: Optional[Set[str]] = None
        self.reported = False
        self.last_site = "?"
        self.last_lockset: Set[str] = set()
        self.last_thread = tid
        self.last_write = False


def _thread_token() -> object:
    """Identity of the calling thread with thread-LIFETIME scope: the OS
    recycles `threading.get_ident()` values after a thread exits, so a
    raw ident could make a later thread look like the object's exclusive
    first thread and silently disarm the lockset machine. A per-thread
    sentinel object dies with the thread (thread-local storage), so it
    can never collide with a live one."""
    tok = getattr(_tls, "token", None)
    if tok is None:
        tok = _tls.token = object()
    return tok


def _attr_access(cls_name: str, attr: str, is_write: bool, obj) -> None:
    """One tracked-attribute access while the sanitizer is enabled.
    Callers are the descriptor's __get__/__set__ (stack depth 2 below
    the production access)."""
    global _attr_accesses
    tid = _thread_token()
    held = set(_held())
    frame = sys._getframe(2)
    site = f"{frame.f_code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno}"
    states = obj.__dict__.setdefault(_STATE_SLOT, {})
    raced = None
    with _graph_lock:
        _attr_accesses += 1
        st = states.get(attr)
        if st is None or st.epoch != _epoch:
            st = states[attr] = _AttrState(_epoch, tid)
        if not st.shared and tid != st.first_thread:
            st.shared = True
        if st.shared:
            st.lockset = (
                set(held) if st.lockset is None else (st.lockset & held)
            )
            if not st.lockset and not st.reported:
                st.reported = True
                raced = f"{cls_name}.{attr}"
                _races.append(
                    {
                        "attr": raced,
                        "site": site,
                        "lockset": sorted(held),
                        "write": is_write,
                        "prev_site": st.last_site,
                        "prev_lockset": sorted(st.last_lockset),
                        "prev_write": st.last_write,
                    }
                )
        st.last_site = site
        st.last_lockset = held
        st.last_thread = tid
        st.last_write = is_write
    if raced is not None:
        # outside _graph_lock: it is a leaf lock, and metrics.inc takes
        # the metrics registry lock — counting inside would stack a
        # foreign lock under the leaf
        _count_race(raced)


def _count_race(attr: str) -> None:
    try:  # metrics are observability, never a sanitizer failure mode
        from ..utils.metrics import metrics

        metrics.inc("lockgraph_races_total", {"attr": attr})
    except Exception:  # pragma: no cover - import cycles in exotic embeds
        pass


class guarded:
    """Data descriptor wrapping one shared attribute for the sanitizer.

    The value lives in the instance ``__dict__`` under a mangled slot;
    disabled, an access costs the descriptor call plus one boolean test.
    Install with :func:`track_attrs` (after the class body) or declare
    ``attr = guarded("attr")`` in the class."""

    __slots__ = ("name", "slot", "cls_name")

    def __init__(self, name: str, cls_name: Optional[str] = None):
        self.name = name
        self.slot = "_lockgraph_" + name
        self.cls_name = cls_name or "?"

    def __set_name__(self, owner, name):  # declarative form
        if self.cls_name == "?":
            self.cls_name = owner.__name__

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            val = obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(self.name) from None
        if _eraser:
            _attr_access(self.cls_name, self.name, False, obj)
        return val

    def __set__(self, obj, value):
        obj.__dict__[self.slot] = value
        if _eraser:
            _attr_access(self.cls_name, self.name, True, obj)

    def __delete__(self, obj):
        obj.__dict__.pop(self.slot, None)
        if _eraser:
            _attr_access(self.cls_name, self.name, True, obj)


def track_attrs(cls, *names: str) -> None:
    """Register shared mutable attributes of a production class with the
    lockset sanitizer. Call once, right after the class definition — the
    descriptors are permanent and idle-cheap; only enable(eraser=True)
    makes them record."""
    for name in names:
        setattr(cls, name, guarded(name, cls.__name__))


class NamedLock:
    """A lock wrapper that reports acquisitions to the watchdog.

    Wraps an RLock by default. Compatible with ``threading.Condition``
    (delegates ``_release_save``/``_acquire_restore``/``_is_owned``
    straight through: a thread parked in ``wait()`` records nothing, and
    its thread-local held stack stays consistent because a blocked
    thread acquires nothing)."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner=None):
        self.name = name
        self._inner = inner if inner is not None else threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok and _enabled:
            _record_acquire(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        if _enabled:
            _record_release(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition compatibility: full release around wait() and silent
    # re-acquire on wake, both invisible to the order graph (see class
    # docstring)
    def _release_save(self):
        return self._inner._release_save()

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"NamedLock({self.name!r}, {self._inner!r})"


def named_lock(name: str, inner=None) -> NamedLock:
    """The factory production modules call where they used to call
    ``threading.RLock()`` directly. Always returns the wrapper — the
    enable flag is checked per acquisition, so chaos suites can flip the
    watchdog on for locks created long before."""
    return NamedLock(name, inner)


# -- watchdog control (chaos suites) -----------------------------------------


def enable(eraser: bool = False) -> None:
    """Arm the watchdog (and, with eraser=True, the lockset sanitizer).
    Always starts a fresh epoch: edges, races, and every thread's held
    stack and per-attribute Eraser state from prior suites in the same
    process are invalidated."""
    global _enabled, _eraser, _epoch
    reset()
    _epoch += 1  # invalidate every thread's held stack from prior runs
    _enabled = True
    _eraser = eraser


def disable() -> None:
    global _enabled, _eraser
    _enabled = False
    _eraser = False


def reset() -> None:
    global _attr_accesses
    with _graph_lock:
        _edges.clear()
        _edge_sites.clear()
        _violations.clear()
        _acquires.clear()
        _attr_accesses = 0
        _races.clear()


def edges() -> Dict[str, Set[str]]:
    with _graph_lock:
        return {k: set(v) for k, v in _edges.items()}


def edge_count() -> int:
    with _graph_lock:
        return sum(len(v) for v in _edges.values())


def acquire_count() -> int:
    """Total named-lock acquisitions observed while enabled — the
    instrumentation-is-alive signal (a suite can legitimately record
    zero EDGES when its locks never nest; it cannot record zero
    acquisitions)."""
    with _graph_lock:
        return sum(_acquires.values())


def acquires_by_name() -> Dict[str, int]:
    with _graph_lock:
        return dict(_acquires)


def violations() -> List[List[str]]:
    with _graph_lock:
        return [list(v) for v in _violations]


def find_cycle() -> Optional[List[str]]:
    """Any cycle in the full graph (independent of insert-time capture)."""
    with _graph_lock:
        color: Dict[str, int] = {}

        def dfs(node: str, path: List[str]) -> Optional[List[str]]:
            color[node] = 1
            for nxt in _edges.get(node, ()):
                if color.get(nxt, 0) == 1:
                    return path[path.index(nxt) :] + [nxt] if nxt in path else [nxt, node, nxt]
                if color.get(nxt, 0) == 0:
                    found = dfs(nxt, path + [nxt])
                    if found:
                        return found
            color[node] = 2
            return None

        for node in list(_edges):
            if color.get(node, 0) == 0:
                found = dfs(node, [node])
                if found:
                    return found
    return None


def assert_acyclic() -> None:
    """Fail loudly on any recorded inversion OR any cycle in the final
    graph. The edge list in the message is the repro: each edge names a
    lock order some real code path executed."""
    vio = violations()
    cyc = find_cycle()
    if vio or cyc:
        lines = ["lock-order watchdog: ORDER INVERSION DETECTED"]
        for v in vio:
            lines.append("  inversion: " + " -> ".join(v))
        if cyc and not vio:
            lines.append("  cycle: " + " -> ".join(cyc))
        with _graph_lock:
            for (a, b), n in sorted(_edge_sites.items()):
                lines.append(f"  edge {a} -> {b} (seen {n}x)")
        raise AssertionError("\n".join(lines))


def races() -> List[dict]:
    """Empty-lockset race reports recorded by the sanitizer this epoch."""
    with _graph_lock:
        return [dict(r) for r in _races]


def tracked_access_count() -> int:
    """Tracked-attribute accesses observed this epoch — the
    sanitizer-is-alive signal (a suite can legitimately record zero
    RACES; with Eraser mode armed over the production classes it cannot
    record zero accesses)."""
    with _graph_lock:
        return _attr_accesses


def assert_no_races() -> None:
    """Fail loudly on any empty-lockset race: each report carries both
    stack tips and both locksets — the repro is in the message."""
    got = races()
    if got:
        lines = ["lockset sanitizer: EMPTY-LOCKSET RACE DETECTED"]
        for r in got:
            lines.append(
                f"  {r['attr']}: {r['prev_site']} "
                f"(locks {r['prev_lockset'] or ['-']}, "
                f"{'write' if r['prev_write'] else 'read'}) vs "
                f"{r['site']} (locks {r['lockset'] or ['-']}, "
                f"{'write' if r['write'] else 'read'})"
            )
        raise AssertionError("\n".join(lines))


def assert_clean() -> None:
    """The chaos-suite exit gate: zero lock-order cycles AND zero
    empty-lockset races."""
    assert_acyclic()
    assert_no_races()
