"""Test helpers: fluent object builders, a plugin-registration DSL, and a
fake cache (reference pkg/scheduler/testing + internal/cache/fake)."""

from .fake_cache import FakeCache  # noqa: F401
from .framework_helpers import (  # noqa: F401
    new_framework,
    register_bind,
    register_filter,
    register_plugin,
    register_pre_filter,
    register_queue_sort,
    register_score,
)
from .wrappers import NodeWrapper, PodWrapper  # noqa: F401
