"""Test helpers: fluent object builders, a plugin-registration DSL, a
fake cache (reference pkg/scheduler/testing + internal/cache/fake), and
the lock-order watchdog (lockgraph).

Submodule imports are LAZY (PEP 562): production modules import
``testing.lockgraph`` (named locks feed the watchdog), and an eager
``from .fake_cache import FakeCache`` here would close an import cycle
back through scheduler → client.apiserver → testing.
"""

_EXPORTS = {
    "FakeCache": ("fake_cache", "FakeCache"),
    "new_framework": ("framework_helpers", "new_framework"),
    "register_bind": ("framework_helpers", "register_bind"),
    "register_filter": ("framework_helpers", "register_filter"),
    "register_plugin": ("framework_helpers", "register_plugin"),
    "register_pre_filter": ("framework_helpers", "register_pre_filter"),
    "register_queue_sort": ("framework_helpers", "register_queue_sort"),
    "register_score": ("framework_helpers", "register_score"),
    "NodeWrapper": ("wrappers", "NodeWrapper"),
    "PodWrapper": ("wrappers", "PodWrapper"),
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    try:
        mod_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    mod = importlib.import_module("." + mod_name, __name__)
    return getattr(mod, attr)
