"""No-op scheduler cache for unit tests.

Reference: pkg/scheduler/internal/cache/fake/fake_cache.go — a Cache
implementation whose mutations do nothing and whose assume hooks invoke a
test-provided callback, so queue/cycle logic can be tested without cache
bookkeeping."""

from __future__ import annotations

from typing import Callable, Optional

from ..scheduler.cache.nodeinfo import Snapshot


class FakeCache:
    def __init__(
        self,
        assume_func: Optional[Callable] = None,
        snapshot: Optional[Snapshot] = None,
    ):
        self.assume_func = assume_func
        self._snapshot = snapshot or Snapshot([])
        self.assumed = []  # (pod_key, node_name) log

    # -- mutations: recorded, never applied ---------------------------------

    def assume_pod(self, pod, node_name: str, **_kw) -> None:
        self.assumed.append((pod.metadata.key, node_name))
        if self.assume_func:
            self.assume_func(pod, node_name)

    def assume_pods_bulk(self, items) -> list:
        """Mirror SchedulerCache's wave-bind entry point."""
        for pod, node_name, _band, _proto in items:
            self.assume_pod(pod, node_name)
        return [None] * len(items)

    def finish_binding(self, pod) -> None:
        pass

    def forget_pod(self, pod) -> None:
        self.assumed = [
            (k, n) for (k, n) in self.assumed if k != pod.metadata.key
        ]

    def add_pod(self, pod) -> None:
        pass

    def update_pod(self, pod) -> None:
        pass

    def remove_pod(self, pod) -> None:
        pass

    def add_node(self, node) -> None:
        pass

    def update_node(self, node) -> None:
        pass

    def remove_node(self, node_name: str) -> None:
        pass

    # -- views ---------------------------------------------------------------

    def update_snapshot(self) -> Snapshot:
        return self._snapshot

    def is_assumed(self, pod_key: str) -> bool:
        return any(k == pod_key for k, _ in self.assumed)

    @property
    def node_count(self) -> int:
        return len(self._snapshot)

    def pod_count(self) -> int:
        return len(self._snapshot.list_pods())
