"""Self-signed TLS material for tests, chaos suites, and the bench.

The container has no ``cryptography`` wheel, but it does ship an
``openssl`` binary — certificates are minted by shelling out, exactly
once per process, into a tempdir that lives for the interpreter's
lifetime. Every caller that needs "TLS on the frontend hop" (relay
workers, REST servers, the serving bench) shares the same keypair so
the handshake cost is realistic and the SAN list covers loopback.

Import-light: stdlib only (subprocess + tempfile), safe for chaos
child processes.
"""

from __future__ import annotations

import atexit
import os
import shutil
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

_lock = threading.Lock()
_cached: Optional[Tuple[str, str]] = None
_tmpdir: Optional[str] = None

# loopback identities the cert must cover: relay workers and frontends
# all bind 127.0.0.1 in tests and the bench
_SAN = "subjectAltName=IP:127.0.0.1,DNS:localhost"


def openssl_available() -> bool:
    return shutil.which("openssl") is not None


def ensure_self_signed(common_name: str = "kubernetes-tpu-test") -> Tuple[str, str]:
    """(cert_path, key_path) for a process-cached self-signed localhost
    cert. Raises RuntimeError when no openssl binary exists — callers
    gate TLS paths on :func:`openssl_available` and fall back to
    plaintext (the wire contract is identical either way)."""
    global _cached, _tmpdir
    with _lock:
        if _cached is not None:
            return _cached
        exe = shutil.which("openssl")
        if exe is None:
            raise RuntimeError("no openssl binary: cannot mint TLS material")
        _tmpdir = tempfile.mkdtemp(prefix="ktpu-tls-")
        atexit.register(shutil.rmtree, _tmpdir, True)
        cert = os.path.join(_tmpdir, "cert.pem")
        key = os.path.join(_tmpdir, "key.pem")
        subprocess.run(
            [
                exe, "req", "-x509", "-newkey", "rsa:2048", "-nodes",
                "-keyout", key, "-out", cert, "-days", "2",
                "-subj", f"/CN={common_name}",
                "-addext", _SAN,
            ],
            check=True,
            capture_output=True,
        )
        _cached = (cert, key)
        return _cached
