"""Deterministic data-plane fault injection for chaos tests.

The control-plane chaos suites inject faults at the store boundary
(ChaosStore); this module is the data-plane sibling — it corrupts the
three trust surfaces the scheduler's self-defense subsystem watches:

  * **snapshot rows** (`corrupt_device_rows`): flip columns of the
    HBM-resident DeviceSnapshot WITHOUT touching the host masters — the
    drift the anti-entropy auditor must detect and repair;
  * **kernel outputs** (`DeviceFaultInjector.nan_scores_on_readbacks`,
    `wild_rows_on_readbacks`): poison the read-back result arrays (NaN
    scores / out-of-range chosen rows) — what the batch guards must
    quarantine;
  * **launch/readback failures** (`fail_launches`, `fail_readbacks`):
    raise DeviceLossError on the Nth wave launch or readback — what the
    device-loss ride-through must retry, reshard, or ride out to the
    host path.

Everything is counter-indexed (0-based call ordinals), never random —
a chaos scenario is a statement, not a dice roll.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.sharded import DeviceLossError

__all__ = [
    "DeviceLossError",
    "DeviceFaultInjector",
    "corrupt_device_rows",
]


def corrupt_device_rows(
    encoder,
    rows: Iterable[int],
    field: str = "requested",
    mutate=None,
) -> None:
    """Flip the given rows of one DeviceSnapshot field IN DEVICE STATE
    only (host masters untouched): the exact shape of a scatter-drift or
    bit-flip bug. Default mutation adds a large constant so every
    resource column visibly diverges. Preserves the encoder's sharding
    placement so a mesh-sharded snapshot stays valid. Runs under a
    generation pin (the read must not observe buffers a wave launch
    donates mid-gather) and installs the corrupted snapshot as a new
    generation that shares its untouched buffers with the pinned one."""
    with encoder.pin_generation() as lease:
        dev = lease.snap
        if dev is None:
            raise RuntimeError("no device snapshot to corrupt (flush first)")
        arr = np.array(jax.device_get(getattr(dev, field)))
        idx = list(rows)
        if mutate is None:
            if arr.dtype.kind == "b":
                arr[idx] = ~arr[idx]
            else:
                arr[idx] = arr[idx] + np.asarray(7919, arr.dtype)
        else:
            arr[idx] = mutate(arr[idx])
        sharding = None
        if encoder._snap_shardings is not None:
            sharding = getattr(encoder._snap_shardings, field)
        new = (
            jax.device_put(arr, sharding)
            if sharding is not None
            else jax.device_put(jnp.asarray(arr))
        )
        encoder.swap_live_snapshot(dev._replace(**{field: new}))


class DeviceFaultInjector:
    """Wraps one Scheduler's device seams (_launch_wave_kernel /
    _fetch_wave_results / _fetch_wave_index / _fetch_wave_bulk /
    _run_serial_kernel). Ordinals count calls made AFTER install().

    Split-phase mapping: the fast index fetch shares the readback
    ordinal space with the legacy combined fetch — `fail_readbacks` and
    `wild_rows_on_readbacks` land there (the chosen-row payload rides
    the fast path). The score tensor only exists on the TRAILING bulk
    fetch in split mode, so `nan_scores_on_readbacks` ordinals index
    bulk calls there, and `fail_trailing_readbacks` kills the trailing
    fetch itself — the exact late-disagreement the unwind machinery
    must catch after the fast payload already drove assumes."""

    def __init__(
        self,
        fail_launches: Iterable[int] = (),
        fail_all_launches: bool = False,
        fail_readbacks: Iterable[int] = (),
        nan_scores_on_readbacks: Iterable[int] = (),
        wild_rows_on_readbacks: Iterable[int] = (),
        fail_all_serials: bool = False,
        fail_trailing_readbacks: Iterable[int] = (),
    ):
        self.fail_launches = set(fail_launches)
        self.fail_all_launches = fail_all_launches
        self.fail_readbacks = set(fail_readbacks)
        self.nan_scores_on_readbacks = set(nan_scores_on_readbacks)
        self.wild_rows_on_readbacks = set(wild_rows_on_readbacks)
        self.fail_all_serials = fail_all_serials
        self.fail_trailing_readbacks = set(fail_trailing_readbacks)
        self.launch_calls = 0
        self.readback_calls = 0
        self.bulk_calls = 0
        self.serial_calls = 0
        self.injected = []  # (kind, ordinal) audit trail for assertions
        self._lock = threading.Lock()
        self._sched = None

    # -- installation --------------------------------------------------------

    def install(self, sched) -> "DeviceFaultInjector":
        self._sched = sched
        self._real_launch = sched._launch_wave_kernel
        self._real_fetch = sched._fetch_wave_results
        self._real_fetch_index = sched._fetch_wave_index
        self._real_fetch_bulk = sched._fetch_wave_bulk
        self._real_serial = sched._run_serial_kernel
        sched._launch_wave_kernel = self._launch
        sched._fetch_wave_results = self._fetch
        sched._fetch_wave_index = self._fetch_index
        sched._fetch_wave_bulk = self._fetch_bulk
        sched._run_serial_kernel = self._serial
        return self

    def uninstall(self) -> None:
        if self._sched is not None:
            self._sched._launch_wave_kernel = self._real_launch
            self._sched._fetch_wave_results = self._real_fetch
            self._sched._fetch_wave_index = self._real_fetch_index
            self._sched._fetch_wave_bulk = self._real_fetch_bulk
            self._sched._run_serial_kernel = self._real_serial
            self._sched = None

    # -- seams ---------------------------------------------------------------

    def _launch(self, kern, snap, batch, ptab, weights, key):
        with self._lock:
            n = self.launch_calls
            self.launch_calls += 1
            boom = self.fail_all_launches or n in self.fail_launches
            if boom:
                self.injected.append(("launch_loss", n))
        if boom:
            raise DeviceLossError(
                f"injected: device lost on launch #{n}"
            )
        return self._real_launch(kern, snap, batch, ptab, weights, key)

    def _serial(self, kern, snap, batch, key, weights=None):
        with self._lock:
            n = self.serial_calls
            self.serial_calls += 1
            boom = self.fail_all_serials
            if boom:
                self.injected.append(("serial_loss", n))
        if boom:
            raise DeviceLossError(
                f"injected: device lost on serial kernel call #{n}"
            )
        return self._real_serial(kern, snap, batch, key, weights)

    def _fetch(self, batches):
        with self._lock:
            n = self.readback_calls
            self.readback_calls += 1
            boom = n in self.fail_readbacks
            nan = n in self.nan_scores_on_readbacks
            wild = n in self.wild_rows_on_readbacks
        if boom:
            self.injected.append(("readback_loss", n))
            raise DeviceLossError(
                f"injected: device lost on readback #{n}"
            )
        fetched = self._real_fetch(batches)
        out = []
        for chosen, placed, deferred, score in fetched:
            chosen = np.array(chosen)
            placed = np.array(placed)
            score = np.array(score)
            if nan and placed.any():
                score = score.copy()
                score[np.nonzero(placed)[0][0]] = np.nan
                self.injected.append(("nan_score", n))
            if wild and placed.any():
                chosen = chosen.copy()
                chosen[np.nonzero(placed)[0][0]] = 2**30
                self.injected.append(("wild_row", n))
            out.append((chosen, placed, deferred, score))
        return out

    def _fetch_index(self, batches):
        """Split-phase FAST seam: index payload only. Shares the
        readback ordinal space with the legacy combined fetch."""
        with self._lock:
            n = self.readback_calls
            self.readback_calls += 1
            boom = n in self.fail_readbacks
            wild = n in self.wild_rows_on_readbacks
        if boom:
            self.injected.append(("readback_loss", n))
            raise DeviceLossError(
                f"injected: device lost on readback #{n}"
            )
        fetched = self._real_fetch_index(batches)
        out = []
        for chosen, placed, deferred in fetched:
            chosen = np.array(chosen)
            placed = np.array(placed)
            if wild and placed.any():
                chosen = chosen.copy()
                chosen[np.nonzero(placed)[0][0]] = 2**30
                self.injected.append(("wild_row", n))
            out.append((chosen, placed, deferred))
        return out

    def _fetch_bulk(self, entries):
        """Split-phase TRAILING seam: the bulk score payload, fetched
        after the fast payload's placements were already acted on."""
        with self._lock:
            n = self.bulk_calls
            self.bulk_calls += 1
            boom = n in self.fail_trailing_readbacks
            nan = n in self.nan_scores_on_readbacks
        if boom:
            self.injected.append(("trailing_loss", n))
            raise DeviceLossError(
                f"injected: device lost on trailing readback #{n}"
            )
        scores = self._real_fetch_bulk(entries)
        out = []
        for e, score in zip(entries, scores):
            score = np.array(score)
            placed = np.asarray(e.placed, dtype=bool)
            if nan and placed.any():
                score = score.copy()
                score[np.nonzero(placed)[0][0]] = np.nan
                self.injected.append(("nan_score", n))
            out.append(score)
        return out
