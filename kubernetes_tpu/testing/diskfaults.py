"""Deterministic disk-fault injection for the durability chaos suite.

The data-plane sibling (`device_faults.py`) corrupts device memory; this
module corrupts the STORAGE trust surface — the write-ahead log's sink —
which is what the disk-fault ride-through machinery defends:

  * **fsync failures** (`fail_fsyncs`, `fail_all_fsyncs`): raise EIO on
    the Nth fsync — the fsyncgate scenario. The WAL must fail-stop
    (poison permanently), never retry-and-pretend;
  * **write failures** (`fail_writes`): raise EIO on the Nth sink write
    — same fail-stop contract, caught one syscall earlier;
  * **ENOSPC** (`enospc_writes`, `enospc_after_bytes`): raise ENOSPC on
    chosen writes, or on every write once a cumulative byte budget is
    exhausted (a filling disk). `free_space()` simulates reclaim — the
    store must ride through: degrade to read-only, repair the log tail,
    and auto-reopen once retries find space again;
  * **slow fsync** (`slow_fsyncs` + `fsync_delay_s`): sleep before the
    real fsync — what the fsync stall watchdog must flag;
  * **torn writes** (`torn_writes`): persist only a prefix of the data,
    then raise EIO — a torn tail recovery must truncate, never replay.

Everything is counter-indexed (0-based call ordinals counted AFTER
`install()`), never random — a chaos scenario is a statement, not a
dice roll. Module helpers (`bit_flip_record`, `truncate_log_at`,
`chop_log_tail`) mutate the log FILE between process lifetimes for
crash-point and bit-rot scenarios.
"""

from __future__ import annotations

import errno
import threading
import time
import zlib
from typing import Iterable, Optional

from ..runtime.wal import FRAME_PREFIX

__all__ = [
    "DiskFaultInjector",
    "bit_flip_record",
    "truncate_log_at",
    "chop_log_tail",
]


class DiskFaultInjector:
    """Wraps one WriteAheadLog's sink seams (_sink_write / _sink_fsync).
    Only meaningful on the Python sink — construct the WAL with
    ``native=False`` so the seams are actually on the write path.

    Ordinals count calls made AFTER install(). The injector survives the
    WAL's own ENOSPC repair (close/reopen) because it patches the
    instance attributes, and the original bound methods read the live
    file handle at call time.
    """

    def __init__(
        self,
        fail_writes: Iterable[int] = (),
        fail_fsyncs: Iterable[int] = (),
        fail_all_fsyncs: bool = False,
        enospc_writes: Iterable[int] = (),
        enospc_after_bytes: Optional[int] = None,
        slow_fsyncs: Iterable[int] = (),
        fsync_delay_s: float = 0.0,
        torn_writes: Iterable[int] = (),
    ):
        self.fail_writes = set(fail_writes)
        self.fail_fsyncs = set(fail_fsyncs)
        self.fail_all_fsyncs = fail_all_fsyncs
        self.enospc_writes = set(enospc_writes)
        self.enospc_after_bytes = enospc_after_bytes
        self.slow_fsyncs = set(slow_fsyncs)
        self.fsync_delay_s = fsync_delay_s
        self.torn_writes = set(torn_writes)
        self.write_calls = 0
        self.fsync_calls = 0
        self.bytes_written = 0
        self.injected = []  # (kind, ordinal) audit trail for assertions
        self._lock = threading.Lock()
        self._wal = None

    # -- installation --------------------------------------------------------

    def install(self, wal) -> "DiskFaultInjector":
        if getattr(wal, "_native", None) is not None:
            raise RuntimeError(
                "DiskFaultInjector needs the Python sink: construct the "
                "WAL with native=False"
            )
        self._wal = wal
        self._real_write = wal._sink_write
        self._real_fsync = wal._sink_fsync
        wal._sink_write = self._write
        wal._sink_fsync = self._fsync
        return self

    def uninstall(self) -> None:
        if self._wal is not None:
            self._wal._sink_write = self._real_write
            self._wal._sink_fsync = self._real_fsync
            self._wal = None

    def free_space(self) -> None:
        """Simulate reclaim: lift the cumulative-bytes ENOSPC budget so
        the next retried write succeeds (the disk-pressure ride-through
        exit path)."""
        with self._lock:
            self.enospc_after_bytes = None

    # -- seams ---------------------------------------------------------------

    def _write(self, data: str) -> None:
        nbytes = len(data.encode("utf-8"))
        with self._lock:
            n = self.write_calls
            self.write_calls += 1
            eio = n in self.fail_writes
            torn = n in self.torn_writes
            enospc = n in self.enospc_writes or (
                self.enospc_after_bytes is not None
                and self.bytes_written + nbytes > self.enospc_after_bytes
            )
            if eio:
                self.injected.append(("write_eio", n))
            elif torn:
                self.injected.append(("torn_write", n))
            elif enospc:
                self.injected.append(("write_enospc", n))
            else:
                self.bytes_written += nbytes
        if eio:
            raise OSError(errno.EIO, f"injected: I/O error on write #{n}")
        if torn:
            # persist a prefix, then fail — the shape of a crash landing
            # mid-write(2). Recovery must classify the partial record as
            # a torn tail and truncate it.
            self._real_write(data[: max(1, len(data) // 2)])
            raise OSError(errno.EIO, f"injected: torn write #{n}")
        if enospc:
            raise OSError(
                errno.ENOSPC,
                f"injected: no space left on device (write #{n})",
            )
        self._real_write(data)

    def _fsync(self) -> None:
        with self._lock:
            n = self.fsync_calls
            self.fsync_calls += 1
            boom = self.fail_all_fsyncs or n in self.fail_fsyncs
            slow = n in self.slow_fsyncs
            if boom:
                self.injected.append(("fsync_eio", n))
            elif slow:
                self.injected.append(("fsync_stall", n))
        if boom:
            raise OSError(errno.EIO, f"injected: I/O error on fsync #{n}")
        if slow:
            time.sleep(self.fsync_delay_s)
        self._real_fsync()


# -- between-lifetimes file mutators ------------------------------------------


def bit_flip_record(log_path: str, ordinal: int, bit: int = 3) -> int:
    """Flip one bit inside the JSON payload of the Nth (0-based) record
    of a v2-framed log — bit-rot that the per-record CRC must catch even
    when the flipped byte still yields parseable JSON. Returns the
    absolute byte offset that was flipped."""
    with open(log_path, "rb") as f:
        raw = f.read()
    offset = 0
    seen = -1
    for line in raw.splitlines(keepends=True):
        body = line.rstrip(b"\n")
        if body.startswith(FRAME_PREFIX.encode()):
            seen += 1
            if seen == ordinal:
                # flip inside the payload (after "K2 " + 8 hex + " "),
                # mid-record so JSON usually still parses — proving the
                # CRC, not the JSON parser, is what catches bit-rot
                frame_len = len(FRAME_PREFIX) + 9
                payload_len = len(body) - frame_len
                target = offset + frame_len + payload_len // 2
                target = min(target, offset + len(body) - 2)
                mutated = bytearray(raw)
                mutated[target] ^= 1 << bit
                with open(log_path, "wb") as f:
                    f.write(bytes(mutated))
                return target
        offset += len(line)
    raise IndexError(
        f"log {log_path!r} has only {seen + 1} framed records, "
        f"wanted ordinal {ordinal}"
    )


def truncate_log_at(log_path: str, nbytes: int) -> None:
    """Truncate the log FILE to exactly nbytes — the crash-point
    property test sweeps this over every byte of the final record."""
    with open(log_path, "rb+") as f:
        f.truncate(nbytes)


def chop_log_tail(log_path: str, nbytes: int) -> int:
    """Chop nbytes off the end of the log (a torn final write). Returns
    the resulting file size."""
    with open(log_path, "rb+") as f:
        size = f.seek(0, 2)
        new = max(0, size - nbytes)
        f.truncate(new)
    return new


def _crc_ok(line: bytes) -> bool:
    """True when a v2-framed line's CRC matches its payload (test
    helper: lets assertions distinguish 'flipped payload' from 'flipped
    frame')."""
    body = line.rstrip(b"\n")
    if not body.startswith(FRAME_PREFIX.encode()):
        return False
    rest = body[len(FRAME_PREFIX):]
    try:
        want = int(rest[:8], 16)
    except ValueError:
        return False
    return zlib.crc32(rest[9:]) & 0xFFFFFFFF == want
