"""Device data plane: columnar snapshot encoding + the filter/score lattice kernels."""

from .encoding import (  # noqa: F401
    EncodingConfig,
    SnapshotEncoder,
    DeviceSnapshot,
    PodBatch,
    Vocab,
)
