"""Template-deduplicated pod batches.

Real scheduling bursts are template-shaped: a Deployment/Job stamps out
thousands of pods differing only in name (the reference's scheduler_perf
configs generate exactly this). Encoding every pod separately wastes host
CPU and uplink bytes; instead the batch is (unique templates → full device
encoding) + (per-pod: template id, priority, pinned-node row). For a 5000-pod
burst of one Deployment this turns ~3 MB of per-pod tensors into a few KB.

The template fingerprint covers every spec field the device encoding reads;
pods whose fingerprint misses the cache fall back to fresh encoding (and the
cache is invalidated when the encoder's vocabularies grow, since interned ids
inside an encoded template would go stale)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import objects as v1
from .batch import EncodedBatch, encode_pod_batch
from .encoding import PodBatch, SnapshotEncoder


def _own_selector_matches(pod: v1.Pod) -> Tuple:
    """Whether each of the pod's OWN term selectors matches its own labels
    (the encodings' aff_self/spr_self bits), in deterministic term order."""
    labels = pod.metadata.labels
    out = []
    aff = pod.spec.affinity
    if aff is not None:
        for pa in (aff.pod_affinity, aff.pod_anti_affinity):
            if pa is None:
                continue
            for term in pa.required:
                sel = term.label_selector
                out.append(sel is not None and sel.matches(labels))
            for wt in pa.preferred:
                sel = wt.term.label_selector
                out.append(sel is not None and sel.matches(labels))
    for c in pod.spec.topology_spread_constraints:
        sel = c.label_selector
        out.append(sel is not None and sel.matches(labels))
    return tuple(out)


def _label_masks(encoder: SnapshotEncoder, ns: str, labels) -> Tuple:
    """(len_sel, len_eterm, sel_mask, eterm_mask): which interned
    predicates match these labels, stamped with the vocab lengths so
    growth never aliases masks across vocab versions. THE single source
    for both the direct and the memoized fingerprint paths."""
    sel_mask = 0
    for i, pred in enumerate(encoder.sel_vocab.items):
        if pred.matches(ns, labels):
            sel_mask |= 1 << i
    et_mask = 0
    for i, et in enumerate(encoder.eterm_vocab.items):
        if et.predicate.matches(ns, labels):
            et_mask |= 1 << i
    return (len(encoder.sel_vocab), len(encoder.eterm_vocab), sel_mask, et_mask)


def _label_effect_key(encoder: SnapshotEncoder, pod: v1.Pod) -> Tuple:
    """Labels as the ENCODING sees them: which interned predicates (selector
    vocab + existing-pod term vocab) match, plus the pod's own-term
    self-matches. Two pods whose labels differ only in ways no predicate
    observes — e.g. 300 gangs distinguished solely by a group-name label —
    collapse to one template instead of 300 (each extra template count is
    another XLA variant; a 15k-pod gang burst compiled per batch without
    this)."""
    return (
        ("enc",)
        + _label_masks(encoder, pod.metadata.namespace, pod.metadata.labels)
        + (_own_selector_matches(pod),)
    )


def pod_fingerprint(pod: v1.Pod, encoder: Optional[SnapshotEncoder] = None) -> Tuple:
    """Structural key over every field the device encoding depends on.

    Everything here is hashable: dataclasses in api/objects.py that feed the
    encoder are frozen, labels/node_selector collapse to frozensets. With an
    encoder, raw labels are replaced by their encoded effect (see
    _label_effect_key) so scheduling-irrelevant label diversity doesn't
    multiply templates."""
    spec = pod.spec
    containers = tuple(
        (
            tuple(sorted(c.requests.items())),
            c.image,
            tuple((p.host_ip, p.protocol, p.host_port) for p in c.ports),
        )
        for c in spec.containers
    )
    inits = tuple(tuple(sorted(c.requests.items())) for c in spec.init_containers)
    ctrl = next(
        (
            (r.kind, r.name)
            for r in pod.metadata.owner_references
            if r.controller
        ),
        None,
    )
    volumes = tuple(
        (
            v.persistent_volume_claim,
            v.gce_persistent_disk,
            v.aws_elastic_block_store,
            v.iscsi,
            v.rbd,
            v.azure_disk,
            v.cinder,
        )
        for v in spec.volumes
        if v.persistent_volume_claim
        or v.gce_persistent_disk
        or v.aws_elastic_block_store
        or v.iscsi
        or v.rbd
        or v.azure_disk
        or v.cinder
    )
    return (
        pod.metadata.namespace,
        (
            _label_effect_key(encoder, pod)
            if encoder is not None
            else frozenset(pod.metadata.labels.items())
        ),
        containers,
        inits,
        tuple(sorted(spec.overhead.items())),
        frozenset(spec.node_selector.items()),
        spec.affinity,
        tuple(spec.tolerations),
        tuple(spec.topology_spread_constraints),
        ctrl,
        spec.scheduler_name,
        volumes,
    )


class TemplateBatch(NamedTuple):
    """Device-side batch: template tensors + per-pod indirection."""

    tpl: PodBatch  # [TPL, ...] template encodings
    pod_tpl: jnp.ndarray  # [P] int32 template index (-1 = invalid row)
    pod_valid: jnp.ndarray  # [P] bool
    pod_name_row: jnp.ndarray  # [P] int32 pinned node row (-1 none, -2 unknown)
    pod_prio: jnp.ndarray  # [P] int32
    pod_band: jnp.ndarray  # [P] int32 priority band (prio_req commit target)


@dataclass
class EncodedTemplateBatch:
    batch: TemplateBatch
    pods: List[v1.Pod]
    fallback: np.ndarray  # [P] bool (template overflowed device buckets)
    num_templates: int
    tpl_np: Optional[PodBatch] = None  # host mirror of batch.tpl (no D2H)
    # host mirrors of per-pod arrays: failure paths read these, and a
    # device_get of host-originated data would pay a pointless tunnel RTT
    pod_tpl_np: Optional[np.ndarray] = None
    pod_prio_np: Optional[np.ndarray] = None
    pod_band_np: Optional[np.ndarray] = None


class TemplateCache:
    """fingerprint → row in a persistent template PodBatch.

    Encoded template rows embed interned vocabulary ids, so the cache is
    keyed to the encoder generation of its vocabularies: any growth in the
    relevant vocabularies invalidates (conservatively, any generation bump
    that changed vocab sizes)."""

    def __init__(self, encoder: SnapshotEncoder, max_templates: int = 64):
        self.encoder = encoder
        self.max_templates = max_templates
        # bumped by the scheduler when template-relevant state changes
        # WITHOUT growing a vocab (service delete/retarget: the match_svc
        # masks must rebuild even though fingerprints alone can't see it)
        self.extra_sig = 0
        self._rows: Dict[Tuple, int] = {}
        self._exemplars: List[v1.Pod] = []
        self._fallback: List[bool] = []
        # bumped whenever the fingerprint->row mapping changes (new
        # template, churn rebuild, vocab-growth clear): consumers caching
        # per-template-set derivations (the scheduler's pair table) key on
        # it so a DIFFERENT set with coincidentally equal count + vocab
        # sizes cannot alias a stale cache entry
        self.rows_gen = 0
        self._tpl_batch_np: Optional[PodBatch] = None
        self._vocab_sig = self._sig()
        self._label_memo: Dict[Tuple, Tuple] = {}
        self._label_memo_sig = (0, 0)
        # per-pod fingerprint memo: an unschedulable-storm batch re-encodes
        # the SAME pods every cycle (a full cluster retries thousands of
        # pending pods per event), and the per-pod tuple build in
        # pod_fingerprint was the dominant tpl-encode cost. (uid, rv)
        # uniquely identifies pod content (the API bumps rv on every
        # write); the epoch ties an entry to the vocab state its
        # fingerprint embedded.
        self._fp_memo: Dict[str, Tuple] = {}
        self._fp_epoch = 0
        self._fp_epoch_sig: Tuple = self._vocab_sig

    def _sig(self) -> Tuple:
        e = self.encoder
        return (
            len(e.key_vocab),
            len(e.val_vocab),
            len(e.sel_vocab),
            len(e.eterm_vocab),
            len(e.port_vocab),
            len(e.image_vocab),
            len(e.avoid_vocab),
            len(e.res_vocab),
            e.cfg,
            self.extra_sig,
        )

    def _fingerprint(self, pod: v1.Pod) -> Tuple:
        """pod_fingerprint with the label-effect masks memoized by
        (namespace, labels): a burst's pods repeat a handful of label sets
        thousands of times, and the per-pod vocab scans in
        _label_effect_key dominated tpl-encode."""
        key = (
            pod.metadata.namespace,
            tuple(sorted(pod.metadata.labels.items())),
        )
        memo = self._label_memo
        eff = memo.get(key)
        if eff is None:
            if len(memo) > 4096:
                memo.clear()  # unbounded label diversity: cap the memo
            eff = memo[key] = _label_masks(
                self.encoder, pod.metadata.namespace, pod.metadata.labels
            )
        fp = pod_fingerprint(pod, None)
        # splice the memoized effect key in place of the raw-labels slot
        # (index 1 — see pod_fingerprint's tuple layout)
        return (
            fp[0],
            ("enc",) + eff + (_own_selector_matches(pod),),
        ) + fp[2:]

    def _memo_valid(self) -> bool:
        return self._label_memo_sig == (
            len(self.encoder.sel_vocab),
            len(self.encoder.eterm_vocab),
        )

    def encode(
        self, pods: Sequence[v1.Pod], pad_to: Optional[int] = None
    ) -> EncodedTemplateBatch:
        P = pad_to or max(1, len(pods))
        assert len(pods) <= P
        # Fingerprint + encode to a FIXED POINT of the vocabularies:
        # encoding a batch's templates can intern new predicates (a pod's
        # own affinity terms), and fingerprints taken BEFORE that interning
        # may have collapsed pods the new predicate distinguishes — the
        # kernel would then see one pod wearing another's label masks.
        # Vocabs only grow and re-encoding the same exemplars interns
        # nothing new, so this converges in <= 2 extra passes.
        for _ in range(4):
            sig0 = self._sig()
            if not self._memo_valid():
                # vocab grew: every memoized mask is stale
                self._label_memo.clear()
                self._label_memo_sig = (
                    len(self.encoder.sel_vocab),
                    len(self.encoder.eterm_vocab),
                )
            if sig0 != self._fp_epoch_sig:
                self._fp_epoch += 1
                self._fp_epoch_sig = sig0
            memo, epoch = self._fp_memo, self._fp_epoch
            fps = []
            for p in pods:
                uid = p.metadata.uid
                ent = memo.get(uid) if uid else None
                if (
                    ent is not None
                    and ent[0] == p.metadata.resource_version
                    and ent[1] == epoch
                ):
                    fps.append(ent[2])
                    continue
                fp = self._fingerprint(p)
                if uid:
                    if len(memo) > 65536:
                        memo.clear()
                    memo[uid] = (p.metadata.resource_version, epoch, fp)
                fps.append(fp)
            changed = False
            for pod, fp in zip(pods, fps):
                if fp not in self._rows:
                    self._rows[fp] = len(self._exemplars)
                    self._exemplars.append(pod)
                    changed = True
            if len(self._exemplars) > self.max_templates:
                # template churn: rebuild the cache from this batch's
                # templates only (rare; steady workloads have a stable set)
                first_by_fp: Dict[Tuple, v1.Pod] = {}
                for pod, fp in zip(pods, fps):
                    first_by_fp.setdefault(fp, pod)
                uniq = list(first_by_fp)
                self._rows = {fp: i for i, fp in enumerate(uniq)}
                self._exemplars = [first_by_fp[fp] for fp in uniq]
                changed = True

            if changed:
                self.rows_gen += 1
            if self._sig() != self._vocab_sig or changed:
                # (re-)encode every template with current vocabularies
                eb = encode_pod_batch(
                    self.encoder,
                    self._exemplars,
                    pad_to=self._pad(len(self._exemplars)),
                )
                self._vocab_sig = self._sig()
                self._tpl_batch = eb.batch
                self._tpl_batch_np = eb.batch_np
                self._fallback = list(eb.fallback[: len(self._exemplars)])
            if self._sig() == sig0:
                break  # no interning this pass: fingerprints are current
            # interning happened: vocab lengths are embedded in every
            # fingerprint, so EVERY cached row is now dead weight — drop
            # them and rebuild from this batch (other batches' templates
            # re-register on their next encode)
            self._rows = {}
            self._exemplars = []
            self._fallback = []
            self.rows_gen += 1

        pod_tpl = np.full(P, -1, np.int32)
        pod_valid = np.zeros(P, np.bool_)
        pod_name_row = np.full(P, -1, np.int32)
        pod_prio = np.zeros(P, np.int32)
        pod_band = np.zeros(P, np.int32)
        fallback = np.zeros(P, np.bool_)
        for i, (pod, fp) in enumerate(zip(pods, fps)):
            t = self._rows[fp]
            fb = self._fallback[t] if t < len(self._fallback) else False
            pod_tpl[i] = t
            # fallback pods run the host path; they must be INVALID to the
            # kernel, else its finalize commits their occupancy on-device
            # for a placement the host will make differently (device drift)
            pod_valid[i] = not fb
            pod_prio[i] = pod.priority
            pod_band[i] = self.encoder._band_of(pod.priority)
            if pod.spec.node_name:
                row = self.encoder.row_of(pod.spec.node_name)
                pod_name_row[i] = row if row >= 0 else -2
            fallback[i] = fb
        # per-pod arrays stay numpy: they ride the kernel DISPATCH as its
        # host->device transfer instead of paying a separate device_put
        # exchange on the tunnel (one less sync point per cycle)
        batch = TemplateBatch(
            tpl=self._tpl_batch,
            pod_tpl=pod_tpl,
            pod_valid=pod_valid,
            pod_name_row=pod_name_row,
            pod_prio=pod_prio,
            pod_band=pod_band,
        )
        return EncodedTemplateBatch(
            batch=batch,
            pods=list(pods),
            fallback=fallback,
            num_templates=len(self._exemplars),
            tpl_np=self._tpl_batch_np,
            pod_tpl_np=pod_tpl,
            pod_prio_np=pod_prio,
            pod_band_np=pod_band,
        )

    @staticmethod
    def _pad(n: int) -> int:
        p = 4
        while p < n:
            p *= 2
        return p

    def match_sel_row(self, pod_index_in_batch_tpl: int) -> np.ndarray:
        """Host mirror of a template's predicate match vector (for assume)."""
        return np.asarray(self._tpl_batch_np.match_sel[pod_index_in_batch_tpl])


class PairTable(NamedTuple):
    """Topology (predicate, key) pairs referenced by a batch.

    A "pair" is one (count column, topology key) combination the kernel needs
    domain sums for: spread constraints, incoming required/preferred
    (anti-)affinity terms (column = interned predicate sid), and existing-pod
    anti-affinity terms matched by batch pods (column = eterm id). Domain sums
    are computed ONCE per pair per batch instead of once per pod — the key
    restructuring that removes the per-pod segment-sum cost.
    """

    is_eterm: jnp.ndarray  # [J] bool (column indexes eterm_w vs sel_counts)
    col: jnp.ndarray  # [J] int32 column id, -1 pad
    key: jnp.ndarray  # [J] int32 topology key id
    elig_tpl: jnp.ndarray  # [J] int32 template whose node-affinity gates
    #                        eligibility (spread), -1 = all valid nodes
    kind: jnp.ndarray  # [J] int32 eterm kind or -1 for sid pairs
    contrib: jnp.ndarray  # [TPL, J] f32 contribution of a template pod
    # per-template pair references (-1 = unused slot)
    spr_pair: jnp.ndarray  # [TPL, C]
    spr_skew: jnp.ndarray  # [TPL, C] f32
    spr_hard: jnp.ndarray  # [TPL, C] bool
    spr_self: jnp.ndarray  # [TPL, C] bool
    aff_pair: jnp.ndarray  # [TPL, A]
    aff_self: jnp.ndarray  # [TPL, A] bool
    anti_pair: jnp.ndarray  # [TPL, B]
    pref_pair: jnp.ndarray  # [TPL, PW]
    pref_w: jnp.ndarray  # [TPL, PW] f32
    etm_match: jnp.ndarray  # [TPL, J] bool — template pod matches pair's
    #                         eterm predicate (filter/scoring vs existing pods)


def build_pair_table(
    enc: SnapshotEncoder, tpl_batch: PodBatch, num_templates: int, j_cap: int = 32
) -> Tuple[PairTable, bool]:
    """Host-side pair dedup over a template batch. Returns (table, overflow).

    `tpl_batch` must be the host (numpy) mirror — passing device arrays here
    would pay a tunnel round trip per field."""
    b = jax.tree.map(np.asarray, tpl_batch)
    TPL = b.spread_sid.shape[0]
    pairs: Dict[Tuple, int] = {}

    def intern(is_et: bool, col: int, key: int, elig: int, kind: int) -> int:
        k = (is_et, col, key, elig)
        j = pairs.get(k)
        if j is None:
            j = len(pairs)
            pairs[k] = j
        return j

    C = b.spread_sid.shape[1]
    A = b.paff_sid.shape[1]
    B = b.panti_sid.shape[1]
    PW = b.ppref_sid.shape[1]
    spr_pair = np.full((TPL, C), -1, np.int32)
    aff_pair = np.full((TPL, A), -1, np.int32)
    anti_pair = np.full((TPL, B), -1, np.int32)
    pref_pair = np.full((TPL, PW), -1, np.int32)
    overflow = False

    eterm_pairs: List[Tuple[int, int]] = []  # (tid, j)
    for t in range(num_templates):
        for c in range(C):
            sid, key = int(b.spread_sid[t, c]), int(b.spread_key[t, c])
            if key >= 0 and sid >= 0:
                spr_pair[t, c] = intern(False, sid, key, t, -1)
        for a in range(A):
            sid, key = int(b.paff_sid[t, a]), int(b.paff_key[t, a])
            if sid >= 0:
                aff_pair[t, a] = intern(False, sid, key, -1, -1)
        for bb in range(B):
            sid, key = int(b.panti_sid[t, bb]), int(b.panti_key[t, bb])
            if sid >= 0:
                anti_pair[t, bb] = intern(False, sid, key, -1, -1)
        for w in range(PW):
            sid, key = int(b.ppref_sid[t, w]), int(b.ppref_key[t, w])
            if sid >= 0:
                pref_pair[t, w] = intern(False, sid, key, -1, -1)
        for tid in range(len(enc.eterm_vocab)):
            if b.match_eterm[t, tid]:
                et = enc.eterm_vocab.items[tid]
                j = intern(True, tid, et.topo_key_id, -1, et.kind)
                eterm_pairs.append((tid, j))

    J = len(pairs)
    if J > j_cap:
        overflow = True
        j_cap = 1
        while j_cap < J:
            j_cap *= 2
    is_eterm = np.zeros(j_cap, np.bool_)
    col = np.full(j_cap, -1, np.int32)
    key_arr = np.zeros(j_cap, np.int32)
    elig = np.full(j_cap, -1, np.int32)
    kind = np.full(j_cap, -1, np.int32)
    for (et, c, k, e), j in pairs.items():
        is_eterm[j] = et
        col[j] = c
        key_arr[j] = k
        elig[j] = e
        # kind recorded below for eterm pairs
    for (et, c, k, e), j in pairs.items():
        if et:
            kind[j] = enc.eterm_vocab.items[c].kind

    contrib = np.zeros((TPL, j_cap), np.float32)
    etm_match = np.zeros((TPL, j_cap), np.bool_)
    for t in range(num_templates):
        for (et, c, k, e), j in pairs.items():
            if et:
                etm_match[t, j] = bool(b.match_eterm[t, c])
                contrib[t, j] = float(b.eterm_add[t, c])
            else:
                if c < b.match_sel.shape[1]:
                    contrib[t, j] = 1.0 if b.match_sel[t, c] else 0.0

    table = PairTable(
        is_eterm=jnp.asarray(is_eterm),
        col=jnp.asarray(col),
        key=jnp.asarray(key_arr),
        elig_tpl=jnp.asarray(elig),
        kind=jnp.asarray(kind),
        contrib=jnp.asarray(contrib),
        spr_pair=jnp.asarray(spr_pair),
        spr_skew=jnp.asarray(b.spread_skew.astype(np.float32)),
        spr_hard=jnp.asarray(b.spread_hard),
        spr_self=jnp.asarray(b.spread_self),
        aff_pair=jnp.asarray(aff_pair),
        aff_self=jnp.asarray(b.paff_self),
        anti_pair=jnp.asarray(anti_pair),
        pref_pair=jnp.asarray(pref_pair),
        pref_w=jnp.asarray(b.ppref_w),
        etm_match=jnp.asarray(etm_match),
    )
    return table, overflow
