"""Columnar snapshot encoding: the host↔device contract.

This is the TPU-native replacement for the reference's per-cycle Snapshot of
NodeInfo structs (pkg/scheduler/internal/cache/snapshot.go:31,
nodeinfo/node_info.go:48). Instead of a list of structs walked by 16
goroutines, cluster state is maintained as a set of fixed-capacity device
tensors, updated incrementally (the analogue of the cache's generation-based
UpdateSnapshot delta protocol, cache.go:203), so a scheduling batch launches
with zero host→device snapshot traffic beyond the pod batch itself.

Key design moves (SURVEY.md §7 stage 2):

* **Dictionary encoding.** Label keys, label values, resource names, host
  ports, images, and controller-refs are interned into growable vocabularies;
  node labels become a dense [N, K] int32 matrix of value-ids (-1 = absent),
  so selector matching is integer compares/gathers on the VPU.

* **Interned pod-predicates.** Every distinct (namespaces, label-selector)
  pair referenced by a PodTopologySpread constraint or InterPodAffinity term
  is interned to a selector id `sid`; the device holds `sel_counts[N, S]` =
  number of pods on node n matching predicate s, maintained incrementally on
  pod add/remove. The reference's O(all-nodes × pods-per-node) PreFilter scan
  (interpodaffinity/filtering.go:212,256) becomes a column gather + one
  segment-sum per term over topology domains.

* **Existing-pod terms ("eterms").** Anti-affinity/affinity terms *of pods
  already placed* are interned as (namespaces, selector, topology_key, kind);
  `eterm_w[N, T]` holds the per-node count (required terms) or weight-sum
  (preferred terms) of pods carrying each term. An incoming pod is matched
  against the small set of eterm predicates on the host (O(T) string work),
  yielding a boolean vector the kernel combines with domain segment-sums —
  this is the "incrementally-maintained device-side count structure" that
  replaces the existing-pods half of InterPodAffinity's PreFilter.

* **Generational double-buffering (pin → donate → retire).** The device
  snapshot is a sequence of immutable *generations*. Readers (the
  anti-entropy audit's row gather, the autoscaler's what-if overlay, the
  chaos fault injector) take a `pin_generation()` lease on the current
  generation; writers (the wave launch's donating kernel, flush's row
  scatters) advance it through a `donation_lease()`: the lease seals the
  live generation, and — when a reader holds a pin, or the generation
  shares buffers with a pinned ancestor — hands the donating program a
  fresh COPY instead, so the pinned buffers stay intact until their pin
  count drains and the generation retires. This replaces the old
  process-wide `device_lock`: a gather no longer serializes against a
  wave launch (the round-8 donation/audit deadlock shape is now legal
  concurrency), multiple waves pipeline in flight, and — because a
  donating program can never alias buffers a reader observes — the
  persistent JAX compilation cache is safe to enable everywhere.

Units: cpu in millicores, memory/ephemeral-storage quantised to KiB
(requests ceil, allocatable floor — conservative), pods/extended raw counts;
all int32. Nodes with >2 TiB of a single resource clamp to int32 max.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from dataclasses import dataclass, replace
from typing import Any, Dict, FrozenSet, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("kubernetes_tpu.ops.encoding")

from ..api import objects as v1
from ..api.resources import CPU, EPHEMERAL_STORAGE, MEMORY, PODS, ResourceList
from ..testing.lockgraph import named_lock, track_attrs
from ..utils.metrics import metrics
from ..api.selectors import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    LabelSelector,
)

# Node-selector operator codes used by the kernel.
ENC_OP_IN = 0
ENC_OP_NOT_IN = 1
ENC_OP_EXISTS = 2
ENC_OP_NOT_EXISTS = 3
ENC_OP_GT = 4
ENC_OP_LT = 5
_OP_CODES = {
    OP_IN: ENC_OP_IN,
    OP_NOT_IN: ENC_OP_NOT_IN,
    OP_EXISTS: ENC_OP_EXISTS,
    OP_DOES_NOT_EXIST: ENC_OP_NOT_EXISTS,
    OP_GT: ENC_OP_GT,
    OP_LT: ENC_OP_LT,
}

# Taint effects.
EFFECT_NO_SCHEDULE = 0
EFFECT_PREFER_NO_SCHEDULE = 1
EFFECT_NO_EXECUTE = 2
_EFFECT_CODES = {
    v1.TAINT_NO_SCHEDULE: EFFECT_NO_SCHEDULE,
    v1.TAINT_PREFER_NO_SCHEDULE: EFFECT_PREFER_NO_SCHEDULE,
    v1.TAINT_NO_EXECUTE: EFFECT_NO_EXECUTE,
}

# eterm kinds (terms carried by existing pods, matched against incoming pods)
ETERM_ANTI_REQ = 0  # existing pod's required anti-affinity -> filter
ETERM_ANTI_PREF = 1  # preferred anti-affinity -> negative score
ETERM_AFF_PREF = 2  # preferred affinity -> positive score
ETERM_AFF_REQ = 3  # required affinity -> score × hardPodAffinityWeight

# Base resource columns (fixed order); extended resources follow.
RES_CPU = 0
RES_MEM = 1
RES_STORAGE = 2
RES_PODS = 3
N_BASE_RES = 4

# Heterogeneity/cost column family: per-node economics fed from node
# labels (the autoscaler's NodeGroup templates stamp them; operators may
# label real fleets the same way). Costs/energy are encoded in MILLI
# units (int32) so a $2.4/h node is 2400 — float labels parse once at
# encode time, the kernel sees integers. Unlabeled nodes read 0
# (= free/no-data); score components normalize within the feasible set,
# so an all-unlabeled cluster scores flat and the policy is inert.
LABEL_COST_PER_HOUR = "kubernetes-tpu.io/cost-per-hour"
LABEL_ACCELERATOR_CLASS = "kubernetes-tpu.io/accelerator-class"
LABEL_ENERGY_WATTS = "kubernetes-tpu.io/energy-watts"


def _milli_of_label(labels: Dict[str, str], key: str) -> int:
    """Parse a float-valued node label into int32 milli-units (0 when
    absent or malformed — a bad label must not fail node encode)."""
    raw = labels.get(key)
    if not raw:
        return 0
    try:
        return int(min(max(float(raw), 0.0) * 1000.0, float(I32_MAX)))
    except (TypeError, ValueError):
        return 0

_KIB = 1024
I32_MAX = np.int32(2**31 - 1)

# -- snapshot generation lifecycle metrics (pin → donate → retire) ----------
GAUGE_GEN_CURRENT = "snapshot_generation_current"
GAUGE_GEN_PINNED = "snapshot_generation_pinned_readers"
GAUGE_GEN_RETIRING = "snapshot_generation_retiring"
COUNTER_GEN_RETIRED = "snapshot_generation_retired_total"
COUNTER_GEN_COPY_ON_PIN = "snapshot_generation_copy_on_pin_total"
COUNTER_GEN_RETIRE_STALLS = "snapshot_generation_retire_stalls_total"
HIST_GEN_RETIRE_LATENCY = "snapshot_generation_retire_latency_seconds"
# the histogram above serves /metrics quantiles; this gauge mirrors the
# most recent retirement's latency into the SIGUSR2 dataplane dump
# (which renders gauges/counters, not histograms)
GAUGE_GEN_LAST_RETIRE_LATENCY = "snapshot_generation_last_retire_latency_seconds"

# a superseded-but-still-pinned generation older than this is a stuck pin
# (a reader leaked its lease): reported once per generation, observable in
# /metrics and the SIGUSR2 dataplane dump instead of silently holding HBM
RETIRE_STALL_AFTER_S = 30.0


class SnapshotGeneration:
    """One immutable HBM buffer set of the double-buffered snapshot.

    ``pins`` counts readers holding a :class:`GenerationLease`; ``sealed``
    marks a donor mid-advance (new pins and new donors wait the few µs
    until the successor installs); ``shared_parent`` points at a still-
    live predecessor whose buffers this generation reuses (the reshape-
    merge upload keeps unreshaped fields) — donation must treat the pair
    as one pin scope. ``superseded_at`` stamps retirement latency."""

    __slots__ = (
        "gen_id", "snap", "pins", "sealed", "shared_parent",
        "superseded_at", "stall_reported",
    )

    def __init__(self, gen_id: int, snap: DeviceSnapshot, shared_parent=None):
        self.gen_id = gen_id
        self.snap = snap
        self.pins = 0
        self.sealed = False
        self.shared_parent = shared_parent
        self.superseded_at: Optional[float] = None
        self.stall_reported = False


class GenerationLease:
    """Reader pin on the current snapshot generation.

    While held, the pinned generation's buffers are never donated: a wave
    launch (or flush scatter) arriving mid-lease advances through a fresh
    copy instead (`snapshot_generation_copy_on_pin_total`). ``snap`` is
    None when no device snapshot exists yet."""

    __slots__ = ("_enc", "_gen", "gen_id", "snap")

    def __init__(self, enc: "SnapshotEncoder"):
        self._enc = enc
        self._gen = None
        self.gen_id = -1
        self.snap: Optional[DeviceSnapshot] = None

    def __enter__(self) -> "GenerationLease":
        enc = self._enc
        with enc._gen_lock:
            # a donor sealed the live generation and is mid-install
            # (microseconds — dispatch is async); bounded waits so a donor
            # that died mid-advance can never park readers forever
            while enc._gen is not None and enc._gen.sealed:
                enc._gen_lock.wait(timeout=0.05)
            gen = enc._gen
            if gen is None:
                return self
            gen.pins += 1
            self._gen = gen
            self.gen_id = gen.gen_id
            self.snap = gen.snap
            enc._check_retire_stalls_locked()
            enc._publish_gen_gauges_locked()
        return self

    def __exit__(self, *exc) -> None:
        gen, self._gen = self._gen, None
        self.snap = None
        if gen is not None:
            self._enc._unpin(gen)

    # Non-lexical hold (split-phase readback): the fast index payload's
    # source generation must stay pinned until the TRAILING bulk readback
    # lands, which happens in a later scheduling-loop iteration — a
    # with-block can't span that. acquire()/release() are __enter__/
    # __exit__ for holders that outlive their frame; release() is
    # idempotent-safe in the sense that the lease must be released
    # exactly once (the scheduler's trailing entry owns it).
    def acquire(self) -> "GenerationLease":
        return self.__enter__()

    def release(self) -> None:
        self.__exit__(None, None, None)


class DonationLease:
    """Writer-side generation advance: seal → dispatch → install.

    ``__enter__`` seals the live generation and yields ``.snap`` — the
    sealed buffers when nothing pins them, a fresh copy when a reader
    does (the double-buffer move: generation N keeps serving its pinned
    readers while the donor consumes a private copy that becomes N+1).
    The caller runs its donating (or alias-free, ``donating=False``)
    program and assigns ``.result``; ``__exit__`` installs the result as
    the next live generation and retires the predecessor once its pins
    drain. On a failed dispatch an in-place donation leaves the buffers
    unknowable, so the generation is dropped and the next flush re-uploads
    from the host masters; a copied/alias-free attempt just unseals."""

    __slots__ = (
        "_enc", "_base", "snap", "copied", "result", "donating", "shared",
    )

    def __init__(self, enc: "SnapshotEncoder", donating: bool = True):
        self._enc = enc
        self._base = None
        self.snap: Optional[DeviceSnapshot] = None
        self.copied = False
        self.result: Optional[DeviceSnapshot] = None
        self.donating = donating
        # caller sets True when .result reuses some of the base's buffers
        # (the reshape-merge upload): the installed generation then keeps
        # a shared-buffer tie to its pinned predecessor
        self.shared = False

    def __enter__(self) -> "DonationLease":
        enc = self._enc
        with enc._gen_lock:
            while enc._gen is not None and enc._gen.sealed:
                enc._gen_lock.wait(timeout=0.05)
            gen = enc._gen
            if gen is None:
                raise RuntimeError(
                    "no live snapshot generation to advance (flush first)"
                )
            gen.sealed = True
            self._base = gen
            try:
                enc._check_retire_stalls_locked()
                pinned = gen.pins > 0 or (
                    gen.shared_parent is not None
                    and gen.shared_parent.pins > 0
                )
                if self.donating and pinned:
                    # readers pin generation N: hand the donor a fresh copy
                    # so the pinned buffers survive until the pins drain
                    self.snap = _copy_snapshot(gen.snap)
                    self.copied = True
                    metrics.inc(COUNTER_GEN_COPY_ON_PIN)
                else:
                    self.snap = gen.snap
            except BaseException:
                # a failed post-seal step (e.g. the copy dispatch dying on
                # device loss) raises out of __enter__, so __exit__ never
                # runs — unseal HERE or every later pin/lease/install
                # waits on the sealed generation forever. The copy is
                # non-donating, so the sealed buffers are still intact.
                gen.sealed = False
                self._base = None
                enc._gen_lock.notify_all()
                raise
        return self

    def __exit__(self, et, ev, tb) -> bool:
        enc = self._enc
        with enc._gen_lock:
            base = self._base
            if et is not None or self.result is None:
                if self.donating and not self.copied:
                    # the donating program may have consumed the sealed
                    # buffers: content unknowable, force a full re-upload
                    if enc._gen is base:
                        enc._gen = None
                    enc._full_upload = True
                    enc._content_invalid = True
                elif base is not None:
                    base.sealed = False
                enc._gen_lock.notify_all()
                enc._publish_gen_gauges_locked()
                return False
            enc._install_locked(
                self.result,
                base,
                consumed=self.donating and not self.copied,
                shared_with_base=self.shared,
            )
        return False


def zpad(a: np.ndarray, n: int) -> np.ndarray:
    """Zero-pad a 1-D array to length n (np.resize repeats — never use it here)."""
    if len(a) >= n:
        return a[:n]
    out = np.zeros(n, a.dtype)
    out[: len(a)] = a
    return out


def _to_col_units(name: str, value: int, ceil: bool) -> int:
    if name in (MEMORY, EPHEMERAL_STORAGE):
        value = (value + _KIB - 1) // _KIB if ceil else value // _KIB
    return int(min(value, int(I32_MAX)))


@dataclass(frozen=True)
class EncodingConfig:
    """Static bucket capacities. All array shapes derive from these; growing
    any capacity doubles it and forces a device re-upload + kernel recompile
    (rare: vocabularies saturate quickly in steady state)."""

    n_cap: int = 128  # node rows
    k_cap: int = 32  # label keys
    v_cap: int = 256  # label values (also topology-domain segment count)
    r_cap: int = 6  # resource columns (4 base + extended)
    pb_cap: int = 8  # priority bands (distinct pod priorities; preempt what-if)
    s_cap: int = 8  # interned pod-predicates (sel_counts columns)
    t_cap: int = 8  # interned eterms
    pv_cap: int = 8  # interned (proto, port) host-port slots
    im_cap: int = 32  # interned images
    av_cap: int = 8  # interned avoid-controller refs
    taints_max: int = 8  # taints per node
    # pod-side buckets
    ns_max: int = 8  # nodeSelector entries per pod
    tol_max: int = 8  # tolerations per pod
    aff_terms: int = 4  # required node-affinity terms (OR)
    aff_exprs: int = 6  # expressions per term (AND)
    aff_vals: int = 8  # values per expression
    pref_terms: int = 4  # preferred node-affinity terms
    spread_max: int = 4  # topology-spread constraints per pod
    pod_aff_max: int = 4  # incoming required affinity terms
    pod_anti_max: int = 4  # incoming required anti-affinity terms
    pod_pref_max: int = 4  # incoming preferred (anti-)affinity terms (signed w)
    images_max: int = 8  # images per pod

    @classmethod
    def for_cluster(cls, num_nodes: int, **overrides) -> "EncodingConfig":
        """Capacities pre-sized for a cluster of ~num_nodes so steady-state
        runs never grow (growth = device re-upload + kernel recompile; an
        observed 14.5s recompile mid-benchmark wrecks p99). v_cap dominates:
        hostname-like labels contribute one value per node."""

        def pow2(n: int, floor: int) -> int:
            p = floor
            while p < n:
                p *= 2
            return p

        # 25% slack for churn (nodes come and go; rows are not reused until
        # compaction), plus a flat allowance for non-hostname label values.
        n_cap = pow2(int(num_nodes * 1.25) + 1, 128)
        v_cap = pow2(int(num_nodes * 1.25) + 512, 256)
        base = dict(
            n_cap=n_cap,
            v_cap=v_cap,
            # pod-side vocab headroom: at real-cluster scale the first
            # burst's pods intern label keys / selector predicates /
            # affinity eterms / host ports past the tiny defaults, and
            # every growth is a mid-window field re-upload PLUS a
            # multi-second kernel recompile (shapes change). Start wide
            # enough that steady state never grows; the extra columns ride
            # the one pre-window upload (~a few MB at 5k nodes).
            k_cap=128,
            s_cap=64,
            t_cap=64,
            pv_cap=32,
            im_cap=64,
            av_cap=16,
        )
        base.update(overrides)
        return cls(**base)


class Vocab:
    """Growable string->id intern table."""

    def __init__(self) -> None:
        self._ids: Dict[Any, int] = {}
        self.items: List[Any] = []

    def intern(self, item: Any) -> int:
        i = self._ids.get(item)
        if i is None:
            i = len(self.items)
            self._ids[item] = i
            self.items.append(item)
        return i

    def get(self, item: Any) -> int:
        """-1 if unknown (lookup without interning)."""
        return self._ids.get(item, -1)

    def __len__(self) -> int:
        return len(self.items)


class PodPredicate(NamedTuple):
    """Interned match unit: pod matches iff namespace ∈ namespaces and labels
    match selector. Namespaces resolved at intern time (term.namespaces or
    the owning pod's namespace, PodAffinityTerm semantics)."""

    namespaces: FrozenSet[str]
    selector: LabelSelector

    def matches(self, namespace: str, labels: Dict[str, str]) -> bool:
        return namespace in self.namespaces and self.selector.matches(labels)


class ETerm(NamedTuple):
    predicate: PodPredicate
    topo_key_id: int
    kind: int


class DeviceSnapshot(NamedTuple):
    """The HBM-resident cluster state the lattice kernel reads. All shapes are
    capacity-padded; `valid` masks live rows. This is a pytree (NamedTuple of
    arrays) so it flows through jit/pjit and can be donated across updates."""

    valid: Any  # [N] bool
    unschedulable: Any  # [N] bool (node.spec.unschedulable)
    allocatable: Any  # [N, R] int32
    requested: Any  # [N, R] int32 (sum of pod requests; PODS col = pod count)
    nonzero_req: Any  # [N, R] int32 (requests with scoring defaults applied)
    label_vals: Any  # [N, K] int32 value-id per key, -1 absent
    label_numvals: Any  # [N, K] int32 numeric value for Gt/Lt, INT_MIN sentinel
    taint_key: Any  # [N, TA] int32 key-id, -1 empty
    taint_val: Any  # [N, TA] int32
    taint_effect: Any  # [N, TA] int32
    sel_counts: Any  # [N, S] int32 pods-matching-predicate counts
    eterm_w: Any  # [N, T] float32 count/weight-sum of existing-pod terms
    eterm_topo_key: Any  # [T] int32 key-id of each eterm's topology key
    eterm_kind: Any  # [T] int32 ETERM_*
    port_counts: Any  # [N, PV] int32 host-port usage counts
    image_bytes: Any  # [N, I] float32 image size if present else 0
    avoid: Any  # [N, AV] bool node-avoids-controller flags
    # priority-banded requested resources: the preemption what-if kernel
    # reads "how much could be freed by evicting pods below priority p" as
    # a masked band sum (SURVEY §7.6 batched masked what-if)
    prio_req: Any  # [N, PB, R] int32 requested by pods in priority band b
    band_prio: Any  # [PB] int32 priority of band b (I32_MAX = empty band)
    # PDB budget column (ops/preemptlattice.py): count of pods in band b
    # on node n whose eviction would violate a PodDisruptionBudget at the
    # disruption controller's CURRENT published budgets (a pod matching
    # any PDB with disruptions_allowed <= 0 counts). Refreshed host-side
    # from PDB events (update_pdb_blocked); the victim-selection kernel
    # uses it to DEPRIORITIZE nodes whose minimal victim prefix spends
    # disruption budget — the exact per-victim countdown stays host-side
    # in the reprieve loop, so this is a ranking column, never an oracle.
    pdb_blocked: Any  # [N, PB] int32
    # heterogeneity/cost columns (node-static, from the labels above):
    cost_milli: Any  # [N] int32 cost-per-hour in milli-units
    accel_class: Any  # [N] int32 interned accelerator-class value id, -1 none
    energy_milli: Any  # [N] int32 energy proxy (watts) in milli-units


class PodBatch(NamedTuple):
    """A batch of P pods encoded for the kernel (built per scheduling cycle)."""

    valid: Any  # [P] bool
    req: Any  # [P, R] int32
    nonzero_req: Any  # [P, R] int32
    node_name_row: Any  # [P] int32 row of spec.nodeName, -1 unset, -2 unknown node
    tolerates_unschedulable: Any  # [P] bool
    # node selector (AND of exprs) — metadata.name matchFields folded to rows
    ns_key: Any  # [P, E] int32
    ns_op: Any  # [P, E] int32
    ns_vals: Any  # [P, E, V] int32
    ns_num: Any  # [P, E] int32
    # required node-affinity terms (OR of terms, AND of exprs)
    aff_has: Any  # [P] bool — has required node-affinity terms
    aff_key: Any  # [P, T, E] int32
    aff_op: Any  # [P, T, E] int32
    aff_vals: Any  # [P, T, E, V] int32
    aff_num: Any  # [P, T, E] int32
    aff_term_valid: Any  # [P, T] bool
    aff_match_name_row: Any  # [P, T] int32: matchFields metadata.name row (-1 none)
    # preferred node-affinity
    pref_key: Any  # [P, PT, E] int32
    pref_op: Any  # [P, PT, E] int32
    pref_vals: Any  # [P, PT, E, V] int32
    pref_num: Any  # [P, PT, E] int32
    pref_weight: Any  # [P, PT] float32 (0 = slot empty)
    pref_term_valid: Any  # [P, PT] bool
    # tolerations
    tol_key: Any  # [P, TO] int32 (-2 empty slot, -1 wildcard key)
    tol_op: Any  # [P, TO] int32 (0 Equal, 1 Exists)
    tol_val: Any  # [P, TO] int32
    tol_effect: Any  # [P, TO] int32 (-1 all effects)
    # topology spread constraints
    spread_key: Any  # [P, C] int32 topo key-id, -1 empty
    spread_sid: Any  # [P, C] int32 predicate id
    spread_skew: Any  # [P, C] int32 max skew
    spread_hard: Any  # [P, C] bool (DoNotSchedule)
    spread_self: Any  # [P, C] bool pod matches its own constraint selector
    # incoming interpod affinity
    paff_sid: Any  # [P, A] int32 (-1 empty)
    paff_key: Any  # [P, A] int32 topo key-id
    paff_self: Any  # [P, A] bool pod matches own selector (carve-out)
    panti_sid: Any  # [P, B] int32
    panti_key: Any  # [P, B] int32
    ppref_sid: Any  # [P, W] int32 preferred terms of incoming pod
    ppref_key: Any  # [P, W] int32
    ppref_w: Any  # [P, W] float32 signed weight (negative = anti)
    # cross-match tensors
    match_sel: Any  # [P, S] bool pod matches interned predicate s
    match_svc: Any  # [P, S] bool — match_sel restricted to SERVICE-derived
    # predicates (encoder.service_sids): the SelectorSpread score's count
    # columns (same-service pods via snap.sel_counts)
    match_eterm: Any  # [P, T] bool pod matches eterm t's predicate
    eterm_add: Any  # [P, T] float32 pod's own term contributions if placed
    port_mask: Any  # [P, PV] bool host ports the pod occupies
    image_ids: Any  # [P, IM] int32 -1 empty
    image_total: Any  # [P] float32 total bytes of pod images
    ctrl_id: Any  # [P] int32 avoid-controller id, -1 none
    priority: Any  # [P] int32


# --------------------------------------------------------------------------
# Host-side master state
# --------------------------------------------------------------------------


@dataclass
class _PodEntry:
    namespace: str
    labels: Dict[str, str]
    req: np.ndarray  # [R] request columns at add time
    nonzero: np.ndarray
    eterm_ids: List[int]
    eterm_ws: List[float]
    port_ids: List[int]
    match_cache_len: int  # sids evaluated so far (== len(sel vocab) at update)
    match_vec: np.ndarray  # [<=S] bool
    prio_band: int = 0  # priority band this pod's requests landed in


class SnapshotEncoder:
    """Maintains host numpy masters + vocabularies; emits DeviceSnapshot.

    Driven by the scheduler cache (add/update/remove node, add/remove pod on
    node). `flush()` returns an up-to-date DeviceSnapshot, applying
    incremental row scatters when capacities are unchanged, mirroring the
    reference's generation-diff UpdateSnapshot (cache.go:203-303).
    """

    def __init__(self, config: Optional[EncodingConfig] = None):
        self.cfg = config or EncodingConfig()
        self.key_vocab = Vocab()
        self.val_vocab = Vocab()
        self.res_vocab = Vocab()  # extended resource name -> idx-N_BASE_RES
        self.sel_vocab = Vocab()  # PodPredicate -> sid
        self.eterm_vocab = Vocab()  # ETerm -> tid
        self.port_vocab = Vocab()  # (proto, port) -> pid
        self.image_vocab = Vocab()
        self.avoid_vocab = Vocab()  # controller-ref "kind/name" -> aid
        # sids interned FROM SERVICE selectors (register_service_predicate):
        # the SelectorSpread device score counts same-service pods through
        # exactly these sel_counts columns and no others
        self.service_sids: set = set()

        self.row_names: List[Optional[str]] = []
        self._row_by_name: Dict[str, int] = {}
        self._free_rows: List[int] = []
        self._pods: Dict[int, Dict[str, _PodEntry]] = {}  # row -> pod-key -> entry
        # True iff the last update_pdb_blocked pass saw any exhausted
        # budget: lets the no-PDB-pressure common case skip the per-row
        # recompute entirely (it runs under the cache lock per failed
        # batch)
        self._pdb_any_blocked = False

        self._alloc_masters()
        # generation bookkeeping lock: guards ONLY the pin/seal/install
        # protocol (a few integer fields + list membership), never held
        # across a blocking device readback. LEAF lock — never acquire any
        # other lock while holding it (the cache lock, when needed, is
        # taken FIRST). Named so the lock-order watchdog
        # (testing/lockgraph.py) sees every acquisition during chaos runs;
        # a Condition so sealed-generation waits are event-driven.
        self._gen_lock = threading.Condition(named_lock("encoder.gen_lock"))
        self._gen: Optional[SnapshotGeneration] = None  # live generation
        self._retiring: List[SnapshotGeneration] = []  # superseded, pinned
        self._next_gen_id = 1
        self._dirty_rows: set = set()
        # rows a failure path could not keep host/device convergent on
        # (e.g. a mid-wave encoder exception after the kernel committed):
        # the anti-entropy auditor audits these FIRST, every pass
        self.suspect_rows: set = set()
        self._full_upload = True
        # device CONTENT unknowable (readback failure, kernel exception,
        # resharding): forces a true full re-upload. _full_upload alone now
        # means "shapes may have grown" and flush re-uploads per-field.
        self._content_invalid = True
        self._globals_dirty = False  # non-row fields (band_prio, eterm meta)
        # multi-chip placement: snapshot sharding pytree + replicated spec
        # (set by the scheduler when it owns a device mesh; None = one chip)
        self._snap_shardings: Optional[DeviceSnapshot] = None
        self._rep_sharding = None
        self.generation = 0  # host-mutation counter, bumped on every change

    # -- generation table (pin → donate → retire) ----------------------------

    @property
    def _device(self) -> Optional[DeviceSnapshot]:
        """The live generation's snapshot (compat read surface: tests and
        diagnostics check `enc._device is None` / diff its fields)."""
        gen = self._gen  # graftlint: unguarded(atomic ref read; diagnostics tolerate a stale generation)
        return None if gen is None else gen.snap

    @property
    def device_generation(self) -> int:
        """Monotonic id of the live device generation (-1 before first
        upload)."""
        gen = self._gen  # graftlint: unguarded(atomic ref read; diagnostics tolerate a stale generation)
        return -1 if gen is None else gen.gen_id

    def pin_generation(self) -> GenerationLease:
        """Reader lease on the current generation: while held, a wave
        launch cannot donate (consume) the pinned buffers — it advances
        through a copy instead. The lease-scoped snapshot is therefore
        safe to gather from concurrently with donating launches."""
        return GenerationLease(self)

    def donation_lease(self, donating: bool = True) -> DonationLease:
        """Writer lease that advances the generation; see
        :class:`DonationLease`. Every donating dispatch in the tree must
        sit lexically inside one of these blocks (graftlint's donation
        pass enforces it — the successor of the retired `device_lock`
        discipline)."""
        return DonationLease(self, donating=donating)

    def _unpin(self, gen: SnapshotGeneration) -> None:
        with self._gen_lock:
            gen.pins -= 1
            if gen.pins <= 0 and gen is not self._gen:
                try:
                    self._retiring.remove(gen)
                except ValueError:
                    pass
                self._retire_locked(gen)
                self._gen_lock.notify_all()
            self._publish_gen_gauges_locked()

    def _install_locked(
        self,
        snap: DeviceSnapshot,
        base: Optional[SnapshotGeneration],
        consumed: bool,
        shared_with_base: bool = False,
    ) -> None:
        """Install `snap` as the next live generation (caller holds
        `_gen_lock`). `consumed`: base's buffers were donated in place —
        the generation object is dead on arrival (seal guarantees it had
        zero pins). `shared_with_base`: the new generation reuses some of
        base's buffers (reshape-merge), so donation treats the pair as
        one pin scope until base retires."""
        now = time.monotonic()
        parent = None
        if base is not None:
            base.superseded_at = now
            if shared_with_base:
                if base.pins > 0:
                    parent = base
                elif (
                    base.shared_parent is not None
                    and base.shared_parent.pins > 0
                ):
                    # the tie must survive CHAINED sharing: base reuses a
                    # still-pinned grandparent's buffers (two capacity
                    # growths while one reader pins), so the new
                    # generation's kept fields are the grandparent's —
                    # dropping the tie here would let a later donation
                    # consume buffers that pinned reader still gathers
                    parent = base.shared_parent
            if consumed or base.pins <= 0:
                self._retire_locked(base)
            else:
                self._retiring.append(base)
        self._gen = SnapshotGeneration(
            self._next_gen_id, snap, shared_parent=parent
        )
        self._next_gen_id += 1
        self._gen_lock.notify_all()
        self._publish_gen_gauges_locked()

    def _install_generation(
        self, snap: DeviceSnapshot, shared_with_base: bool = False
    ) -> None:
        """Install a freshly-uploaded snapshot (device_put — fresh
        buffers unless shared_with_base) as the live generation."""
        with self._gen_lock:
            while self._gen is not None and self._gen.sealed:
                self._gen_lock.wait(timeout=0.05)
            self._install_locked(
                snap, self._gen, consumed=False,
                shared_with_base=shared_with_base,
            )

    def _retire_locked(self, gen: SnapshotGeneration) -> None:
        """Buffer set leaves service: count it, stamp retirement latency,
        re-point any child's shared-buffer tie PAST it. The tie must
        propagate, not sever: with chained sharing (reader R1 pins A, a
        reshape installs B sharing A, reader R2 pins B, a reshape
        installs C sharing B), R2's unpin retires intermediate B while
        C's kept fields are still A's buffers — C inherits the tie to
        the still-pinned A, or a later donation on C would consume the
        buffers R1's gather reads."""
        if gen.superseded_at is not None:
            latency = max(0.0, time.monotonic() - gen.superseded_at)
            metrics.observe(HIST_GEN_RETIRE_LATENCY, latency)
            metrics.set_gauge(GAUGE_GEN_LAST_RETIRE_LATENCY, latency)
        metrics.inc(COUNTER_GEN_RETIRED)
        parent = gen.shared_parent
        if parent is not None and parent.pins <= 0:
            # unpinned ancestors are already retired (or about to be):
            # dropping the reference keeps no dead buffer set reachable
            parent = None
        children = list(self._retiring)
        if self._gen is not None:
            children.append(self._gen)
        for child in children:
            if child.shared_parent is gen:
                child.shared_parent = parent

    def check_retire_stalls(self) -> None:
        """Stall-watchdog sweep for periodic callers (the anti-entropy
        pass, the SIGUSR2 dataplane dump). The lease-entry checks fire
        only on new pin/donation traffic, so without this a leaked
        reader pin on an otherwise idle encoder would hold its HBM
        generation invisibly until the next lease happened to arrive."""
        with self._gen_lock:
            self._check_retire_stalls_locked()
            self._publish_gen_gauges_locked()

    def _check_retire_stalls_locked(self) -> None:
        now = time.monotonic()
        for gen in self._retiring:
            if gen.stall_reported or gen.superseded_at is None:
                continue
            if now - gen.superseded_at > RETIRE_STALL_AFTER_S:
                gen.stall_reported = True
                metrics.inc(COUNTER_GEN_RETIRE_STALLS)
                logger.error(
                    "snapshot generation %d superseded %.1f s ago still "
                    "holds %d reader pin(s): a lease leaked — its HBM "
                    "buffers cannot retire",
                    gen.gen_id, now - gen.superseded_at, gen.pins,
                )

    def _publish_gen_gauges_locked(self) -> None:
        gen = self._gen
        pins = sum(g.pins for g in self._retiring)
        if gen is not None:
            pins += gen.pins
            metrics.set_gauge(GAUGE_GEN_CURRENT, float(gen.gen_id))
        metrics.set_gauge(GAUGE_GEN_PINNED, float(pins))
        metrics.set_gauge(GAUGE_GEN_RETIRING, float(len(self._retiring)))

    # -- master allocation / growth ---------------------------------------

    def _alloc_masters(self) -> None:
        c = self.cfg
        n = c.n_cap
        self.m_valid = np.zeros(n, np.bool_)
        self.m_unsched = np.zeros(n, np.bool_)
        self.m_alloc = np.zeros((n, c.r_cap), np.int32)
        self.m_req = np.zeros((n, c.r_cap), np.int32)
        self.m_nonzero = np.zeros((n, c.r_cap), np.int32)
        self.m_label_vals = np.full((n, c.k_cap), -1, np.int32)
        self.m_label_num = np.full((n, c.k_cap), np.iinfo(np.int32).min, np.int32)
        self.m_taint_key = np.full((n, c.taints_max), -1, np.int32)
        self.m_taint_val = np.zeros((n, c.taints_max), np.int32)
        self.m_taint_eff = np.zeros((n, c.taints_max), np.int32)
        self.m_sel_counts = np.zeros((n, c.s_cap), np.int32)
        self.m_eterm_w = np.zeros((n, c.t_cap), np.float32)
        self.m_eterm_topo = np.full(c.t_cap, -1, np.int32)
        self.m_eterm_kind = np.full(c.t_cap, -1, np.int32)
        self.m_port_counts = np.zeros((n, c.pv_cap), np.int32)
        self.m_image_bytes = np.zeros((n, c.im_cap), np.float32)
        self.m_avoid = np.zeros((n, c.av_cap), np.bool_)
        self.m_prio_req = np.zeros((n, c.pb_cap, c.r_cap), np.int32)
        self.m_band_prio = np.full(c.pb_cap, I32_MAX, np.int32)
        self.m_pdb_blocked = np.zeros((n, c.pb_cap), np.int32)
        self.m_cost = np.zeros(n, np.int32)
        self.m_accel = np.full(n, -1, np.int32)
        self.m_energy = np.zeros(n, np.int32)

    def _grow(self, **caps: int) -> None:
        """Grow one or more capacities; copies masters, forces full upload."""
        old = {
            "m_valid": self.m_valid,
            "m_unsched": self.m_unsched,
            "m_alloc": self.m_alloc,
            "m_req": self.m_req,
            "m_nonzero": self.m_nonzero,
            "m_label_vals": self.m_label_vals,
            "m_label_num": self.m_label_num,
            "m_taint_key": self.m_taint_key,
            "m_taint_val": self.m_taint_val,
            "m_taint_eff": self.m_taint_eff,
            "m_sel_counts": self.m_sel_counts,
            "m_eterm_w": self.m_eterm_w,
            "m_eterm_topo": self.m_eterm_topo,
            "m_eterm_kind": self.m_eterm_kind,
            "m_port_counts": self.m_port_counts,
            "m_image_bytes": self.m_image_bytes,
            "m_avoid": self.m_avoid,
            "m_prio_req": self.m_prio_req,
            "m_band_prio": self.m_band_prio,
            "m_pdb_blocked": self.m_pdb_blocked,
            "m_cost": self.m_cost,
            "m_accel": self.m_accel,
            "m_energy": self.m_energy,
        }
        self.cfg = replace(self.cfg, **caps)
        self._alloc_masters()
        for name, arr in old.items():
            dst = getattr(self, name)
            sl = tuple(slice(0, s) for s in arr.shape)
            dst[sl] = arr
        # shape growth, not content loss: flush re-uploads only the fields
        # whose shape changed (a mid-burst t_cap bump cost a ~2 s full
        # 5k-row re-upload through the tunnel before this distinction)
        self._full_upload = True

    def presize_for_cluster(self, num_nodes: int) -> None:
        """Grow n_cap/v_cap ahead of a known cluster scale (see
        EncodingConfig.for_cluster). Cheap before the first flush; later it
        costs the same single re-upload a demand-grow would."""
        want = EncodingConfig.for_cluster(num_nodes)
        grown = {}
        for cap in (
            "n_cap", "v_cap", "k_cap", "s_cap", "t_cap", "pv_cap",
            "im_cap", "av_cap",
        ):
            cur, target = getattr(self.cfg, cap), getattr(want, cap)
            if target > cur:
                new = cur
                while new < target:
                    new *= 2
                grown[cap] = new
        if grown:
            self._grow(**grown)  # ONE reallocate-and-copy pass for all caps

    def _ensure_cap(self, attr: str, needed: int) -> None:
        cur = getattr(self.cfg, attr)
        if needed <= cur:
            return
        new = cur
        while new < needed:
            new *= 2
        self._grow(**{attr: new})

    # -- vocab helpers ------------------------------------------------------

    def intern_key(self, key: str) -> int:
        i = self.key_vocab.intern(key)
        self._ensure_cap("k_cap", len(self.key_vocab))
        return i

    def intern_val(self, val: str) -> int:
        i = self.val_vocab.intern(val)
        self._ensure_cap("v_cap", len(self.val_vocab))
        return i

    def intern_resource(self, name: str) -> int:
        """Resource name -> column index (base resources fixed)."""
        base = {CPU: RES_CPU, MEMORY: RES_MEM, EPHEMERAL_STORAGE: RES_STORAGE, PODS: RES_PODS}
        if name in base:
            return base[name]
        i = N_BASE_RES + self.res_vocab.intern(name)
        self._ensure_cap("r_cap", N_BASE_RES + len(self.res_vocab))
        return i

    def intern_predicate(self, namespaces: FrozenSet[str], sel: LabelSelector) -> int:
        pred = PodPredicate(namespaces, sel)
        known = self.sel_vocab.get(pred)
        if known >= 0:
            return known
        sid = self.sel_vocab.intern(pred)
        self._ensure_cap("s_cap", len(self.sel_vocab))
        # back-fill counts for already-placed pods (one host scan, amortised)
        for row, pods in self._pods.items():
            cnt = sum(
                1 for e in pods.values() if pred.matches(e.namespace, e.labels)
            )
            if cnt:
                self.m_sel_counts[row, sid] = cnt
                self._dirty_rows.add(row)
        self.generation += 1
        return sid

    def register_service_predicate(self, namespace: str, selector: LabelSelector) -> int:
        """Intern a Service's selector as a pod predicate and mark its sid
        service-derived (the DefaultPodTopologySpread device score reads
        sel_counts through service sids only). Idempotent; called from the
        scheduler's service event handlers so a new Service grows the vocab
        and thereby invalidates cached templates (their fingerprints embed
        vocab lengths)."""
        sid = self.intern_predicate(frozenset({namespace}), selector)
        self.service_sids.add(sid)
        return sid

    def service_sid_mask(self) -> np.ndarray:
        """[s_cap] bool — which predicate columns are service-derived."""
        mask = np.zeros(self.cfg.s_cap, np.bool_)
        for sid in self.service_sids:
            if sid < mask.shape[0]:
                mask[sid] = True
        return mask

    def intern_eterm(self, pred: PodPredicate, topo_key: str, kind: int) -> int:
        key_id = self.intern_key(topo_key)
        et = ETerm(pred, key_id, kind)
        known = self.eterm_vocab.get(et)
        if known >= 0:
            return known
        tid = self.eterm_vocab.intern(et)
        self._ensure_cap("t_cap", len(self.eterm_vocab))
        self.m_eterm_topo[tid] = key_id
        self.m_eterm_kind[tid] = kind
        self._globals_dirty = True
        self.generation += 1
        return tid

    def intern_port(self, proto: str, port: int) -> int:
        i = self.port_vocab.intern((proto, port))
        self._ensure_cap("pv_cap", len(self.port_vocab))
        return i

    def intern_image(self, name: str) -> int:
        i = self.image_vocab.intern(name)
        self._ensure_cap("im_cap", len(self.image_vocab))
        return i

    def intern_avoid(self, ref: str) -> int:
        i = self.avoid_vocab.intern(ref)
        self._ensure_cap("av_cap", len(self.avoid_vocab))
        return i

    def _band_of(self, priority: int) -> int:
        """Priority band index. Distinct priorities get their own band; once
        bands are exhausted, fall back to the band with the largest priority
        <= the pod's (else the lowest band). The fallback overstates what a
        higher-priority preemptor could free — the what-if mask must stay
        OPTIMISTIC (no false negatives vs the host reprieve loop, which does
        the exact check on surviving candidates)."""
        bands = self.m_band_prio
        exact = np.nonzero(bands == priority)[0]
        if exact.size:
            return int(exact[0])
        empty = np.nonzero(bands == I32_MAX)[0]
        if empty.size:
            b = int(empty[0])
            bands[b] = priority
            self._globals_dirty = True
            self.generation += 1
            return b
        lower = np.nonzero(bands <= priority)[0]
        if lower.size:
            return int(lower[np.argmax(bands[lower])])
        # every band sits above this pod: adopt the lowest band and relabel
        # it DOWN to this priority. Lowering a band's label is optimistic for
        # the band's existing pods (they appear removable to lower-priority
        # preemptors), never pessimistic — the invariant holds.
        b = int(np.argmin(bands))
        bands[b] = priority
        self._globals_dirty = True
        self.generation += 1
        return b

    # -- resource encoding ---------------------------------------------------

    def encode_resources(self, rl: ResourceList, ceil: bool) -> np.ndarray:
        cols = []
        for name, val in rl.items():
            col = self.intern_resource(name)  # may grow r_cap
            if name in (CPU, PODS):
                u = int(min(val, int(I32_MAX)))
            else:
                u = _to_col_units(name, val, ceil)
            cols.append((col, u))
        out = np.zeros(self.cfg.r_cap, np.int32)
        for col, u in cols:
            out[col] = u
        return out

    # -- node lifecycle ------------------------------------------------------

    def row_of(self, node_name: str) -> int:
        return self._row_by_name.get(node_name, -1)

    def add_node(self, node: v1.Node) -> int:
        name = node.metadata.name
        if name in self._row_by_name:
            return self.update_node(node)
        if self._free_rows:
            row = self._free_rows.pop()
        else:
            row = len(self.row_names)
            self.row_names.append(None)
            self._ensure_cap("n_cap", len(self.row_names))
        self.row_names[row] = name
        self._row_by_name[name] = row
        self._pods.setdefault(row, {})
        self._write_node_row(row, node)
        return row

    def update_node(self, node: v1.Node) -> int:
        row = self._row_by_name[node.metadata.name]
        self._write_node_row(row, node)
        return row

    def encode_node_row_values(self, node: v1.Node) -> Dict[str, np.ndarray]:
        """Encode one node's NODE-STATIC columns (no pod aggregates) into a
        standalone row-values dict keyed by DeviceSnapshot field name. This
        is the single row encoding shared by `_write_node_row` (live
        masters) and the autoscaler's what-if overlay (virtual candidate
        rows appended to a COPY of the snapshot — the values never touch
        the live masters there). Interning happens first so every capacity
        is final before the row arrays are allocated (a mid-encode `_grow`
        would otherwise orphan the half-filled arrays)."""
        alloc = self.encode_resources(node.allocatable(), ceil=False)
        # labels — metadata.name is matchable as a field selector; expose it
        # as a pseudo-label so matchFields shares the label path.
        labels = dict(node.metadata.labels)
        labels.setdefault("kubernetes.io/hostname", node.metadata.name)
        lab = [
            (self.intern_key(k), self.intern_val(v), v)
            for k, v in labels.items()
        ]
        taints = [
            (
                self.intern_key(t.key),
                self.intern_val(t.value),
                _EFFECT_CODES.get(t.effect, EFFECT_NO_SCHEDULE),
            )
            for t in node.spec.taints[: self.cfg.taints_max]
        ]
        images = [
            (self.intern_image(nm), float(img.size_bytes))
            for img in node.status.images
            for nm in img.names
        ]
        # avoid-pods annotation: comma-separated "Kind/name" controller refs
        # (simplified AvoidPods encoding; reference uses a JSON annotation,
        # v1helper.GetAvoidPodsFromNodeAnnotations).
        ann = node.metadata.annotations.get(
            "scheduler.alpha.kubernetes.io/preferAvoidPods", ""
        )
        avoids = [
            self.intern_avoid(ref)
            for ref in filter(None, (r.strip() for r in ann.split(",")))
        ]
        c = self.cfg  # re-read: interning above may have grown capacities
        label_vals = np.full(c.k_cap, -1, np.int32)
        label_num = np.full(c.k_cap, np.iinfo(np.int32).min, np.int32)
        for ki, vi, raw in lab:
            label_vals[ki] = vi
            try:
                label_num[ki] = int(raw)
            except ValueError:
                pass
        taint_key = np.full(c.taints_max, -1, np.int32)
        taint_val = np.zeros(c.taints_max, np.int32)
        taint_eff = np.zeros(c.taints_max, np.int32)
        for i, (ki, vi, eff) in enumerate(taints):
            taint_key[i] = ki
            taint_val[i] = vi
            taint_eff[i] = eff
        image_bytes = np.zeros(c.im_cap, np.float32)
        for ii, sz in images:
            image_bytes[ii] = sz
        avoid = np.zeros(c.av_cap, np.bool_)
        for ai in avoids:
            avoid[ai] = True
        accel_raw = labels.get(LABEL_ACCELERATOR_CLASS)
        return {
            "valid": np.bool_(True),
            "unschedulable": np.bool_(node.spec.unschedulable),
            "allocatable": zpad(alloc, c.r_cap),
            "label_vals": label_vals,
            "label_numvals": label_num,
            "taint_key": taint_key,
            "taint_val": taint_val,
            "taint_effect": taint_eff,
            "image_bytes": image_bytes,
            "avoid": avoid,
            # heterogeneity/cost columns (already interned above via the
            # generic label path; accel re-interns idempotently)
            "cost_milli": np.int32(_milli_of_label(labels, LABEL_COST_PER_HOUR)),
            "accel_class": np.int32(
                self.intern_val(accel_raw) if accel_raw else -1
            ),
            "energy_milli": np.int32(_milli_of_label(labels, LABEL_ENERGY_WATTS)),
        }

    def _write_node_row(self, row: int, node: v1.Node) -> None:
        vals = self.encode_node_row_values(node)
        # masters re-fetched AFTER the encode: interning can _grow (which
        # reallocates every master array)
        self.m_valid[row] = vals["valid"]
        self.m_unsched[row] = vals["unschedulable"]
        self.m_alloc[row, :] = vals["allocatable"]
        self.m_label_vals[row, :] = vals["label_vals"]
        self.m_label_num[row, :] = vals["label_numvals"]
        self.m_taint_key[row, :] = vals["taint_key"]
        self.m_taint_val[row, :] = vals["taint_val"]
        self.m_taint_eff[row, :] = vals["taint_effect"]
        self.m_image_bytes[row, :] = vals["image_bytes"]
        self.m_avoid[row, :] = vals["avoid"]
        self.m_cost[row] = vals["cost_milli"]
        self.m_accel[row] = vals["accel_class"]
        self.m_energy[row] = vals["energy_milli"]
        self._dirty_rows.add(row)
        self.generation += 1

    def remove_node(self, node_name: str) -> None:
        row = self._row_by_name.pop(node_name, None)
        if row is None:
            return
        self.row_names[row] = None
        self._free_rows.append(row)
        self._pods[row] = {}
        self.m_valid[row] = False
        self.m_sel_counts[row, :] = 0
        self.m_eterm_w[row, :] = 0
        self.m_req[row, :] = 0
        self.m_nonzero[row, :] = 0
        self.m_port_counts[row, :] = 0
        self.m_prio_req[row, :, :] = 0
        self.m_pdb_blocked[row, :] = 0
        self._dirty_rows.add(row)
        self.generation += 1

    # -- pod lifecycle -------------------------------------------------------

    def _pod_eterms(self, pod: v1.Pod) -> Tuple[List[int], List[float]]:
        """Intern the anti/affinity terms *carried by* this pod."""
        ids: List[int] = []
        ws: List[float] = []
        aff = pod.spec.affinity
        ns = pod.metadata.namespace
        if aff is None:
            return ids, ws

        def pred_of(term: v1.PodAffinityTerm) -> PodPredicate:
            nss = frozenset(term.namespaces) if term.namespaces else frozenset({ns})
            return PodPredicate(nss, term.label_selector or LabelSelector())

        if aff.pod_anti_affinity:
            for term in aff.pod_anti_affinity.required:
                ids.append(self.intern_eterm(pred_of(term), term.topology_key, ETERM_ANTI_REQ))
                ws.append(1.0)
            for wt in aff.pod_anti_affinity.preferred:
                ids.append(
                    self.intern_eterm(pred_of(wt.term), wt.term.topology_key, ETERM_ANTI_PREF)
                )
                ws.append(float(wt.weight))
        if aff.pod_affinity:
            for term in aff.pod_affinity.required:
                ids.append(self.intern_eterm(pred_of(term), term.topology_key, ETERM_AFF_REQ))
                ws.append(1.0)
            for wt in aff.pod_affinity.preferred:
                ids.append(
                    self.intern_eterm(pred_of(wt.term), wt.term.topology_key, ETERM_AFF_PREF)
                )
                ws.append(float(wt.weight))
        return ids, ws

    def pod_proto(self, pod: v1.Pod) -> tuple:
        """Shared encoding of everything add_pod derives from the SPEC
        (requests, carried terms, ports, label match vector): pods of one
        scheduling template produce identical protos, so a bulk bind
        computes this once per template instead of once per pod. Valid
        only at the current vocab state — add_pod revalidates."""
        from ..api.objects import compute_pod_resource_request, pod_host_ports

        req = self.encode_resources(compute_pod_resource_request(pod), ceil=True)
        nz = self.encode_resources(
            compute_pod_resource_request(pod, non_zero=True), ceil=True
        )
        req = zpad(req, self.cfg.r_cap)
        nz = zpad(nz, self.cfg.r_cap)
        req[RES_PODS] = 1
        nz[RES_PODS] = 1
        eids, ews = self._pod_eterms(pod)
        pids = [
            self.intern_port(proto, port)
            for (_, proto, port) in pod_host_ports(pod)
        ]
        mv = self._match_vec(pod.metadata.namespace, pod.metadata.labels)
        return (req, nz, eids, ews, pids, mv, len(self.sel_vocab))

    def add_pod(
        self,
        node_name: str,
        pod: v1.Pod,
        device_synced: bool = False,
        prio_band: Optional[int] = None,
        proto: Optional[tuple] = None,
    ) -> None:
        """device_synced=True: the wave kernel already committed this pod's
        occupancy (requested/nonzero/sel_counts/eterm_w/ports/prio_req) into
        the device snapshot it returned (wavelattice finalize), so replaying
        it here must update the host masters WITHOUT marking the row dirty —
        a dirty mark would re-upload values the device already holds, and at
        ~65 ms tunnel RTT per transfer those redundant scatters were the
        1-2 s encode spikes in the round-2 bench.

        proto: a pod_proto() result from a template sibling — reused
        (arrays treated as immutable) unless the vocab grew since."""
        row = self._row_by_name.get(node_name)
        if row is None:
            raise KeyError(f"unknown node {node_name}")
        if proto is not None and proto[6] == len(self.sel_vocab):
            req, nz, eids, ews, pids, mv, _ = proto
        else:
            req, nz, eids, ews, pids, mv, _ = self.pod_proto(pod)
        # device_synced replay must land in the band the kernel committed
        # prio_req under (captured at encode time); recomputing could pick a
        # different band after a relabel, silently diverging host vs device
        band = prio_band if prio_band is not None else self._band_of(pod.priority)
        entry = _PodEntry(
            namespace=pod.metadata.namespace,
            labels=dict(pod.metadata.labels),
            req=req,
            nonzero=nz,
            eterm_ids=eids,
            eterm_ws=ews,
            port_ids=pids,
            match_cache_len=len(self.sel_vocab),
            match_vec=mv,
            prio_band=band,
        )
        self._pods[row][pod.metadata.key] = entry
        self.m_req[row, : len(req)] += req
        self.m_nonzero[row, : len(nz)] += nz
        self.m_prio_req[row, band, : len(req)] += req
        for i, m in enumerate(entry.match_vec):
            if m:
                self.m_sel_counts[row, i] += 1
        for tid, w in zip(eids, ews):
            self.m_eterm_w[row, tid] += w
        for pid in pids:
            self.m_port_counts[row, pid] += 1
        if not device_synced:
            self._dirty_rows.add(row)
        self.generation += 1

    def add_pods_bulk(self, items: list) -> None:
        """Vectorized add_pod for a wave of device-synced placements:
        items = [(node_name, pod, band, proto)] with proto from
        pod_proto() (None entries computed here). Master updates become
        one np.add.at scatter per (proto, band) group instead of python
        loops per pod — the 50k pods/s target cannot afford ~0.1 ms of
        per-pod host bookkeeping on the bind path."""
        # pass 1 — resolve + (re)compute protos. All raising checks and all
        # vocab interning (which can GROW capacities) happen here, BEFORE
        # any entry insert or master scatter: an exception must leave the
        # masters untouched, or later removals would drive them negative
        resolved: list = []  # (row, pod, band, proto)
        for node_name, pod, band, proto in items:
            row = self._row_by_name.get(node_name)
            if row is None:
                raise KeyError(f"unknown node {node_name}")
            if proto is None or proto[6] != len(self.sel_vocab):
                proto = self.pod_proto(pod)
            resolved.append((row, pod, band, proto))
        # pass 2 — pure writes; nothing below interns or raises
        groups: dict = {}  # (id(proto), band) -> (proto, rows)
        for row, pod, band, proto in resolved:
            req, nz, eids, ews, pids, mv, _ = proto
            self._pods[row][pod.metadata.key] = _PodEntry(
                namespace=pod.metadata.namespace,
                labels=dict(pod.metadata.labels),
                req=req,
                nonzero=nz,
                eterm_ids=eids,
                eterm_ws=ews,
                port_ids=pids,
                match_cache_len=len(self.sel_vocab),
                match_vec=mv,
                prio_band=band,
            )
            key = (id(proto), band)
            g = groups.get(key)
            if g is None:
                groups[key] = (proto, [row])
            else:
                g[1].append(row)
        for (_, band), (proto, rows) in groups.items():
            req, nz, eids, ews, pids, mv, _ = proto
            r = np.asarray(rows, np.int64)
            # column-sliced like add_pod: a proto narrower than the
            # current r_cap (capacity grew after it was built) still lands
            np.add.at(self.m_req[:, : len(req)], r, req)
            np.add.at(self.m_nonzero[:, : len(nz)], r, nz)
            np.add.at(self.m_prio_req[:, band, : len(req)], r, req)
            if mv.any():
                np.add.at(
                    self.m_sel_counts[:, : len(mv)], r, mv.astype(np.int32)
                )
            for tid, w in zip(eids, ews):
                np.add.at(self.m_eterm_w[:, tid], r, w)
            for pid in pids:
                np.add.at(self.m_port_counts[:, pid], r, 1)
        self.generation += len(items)

    def remove_pod(self, node_name: str, pod_key: str) -> None:
        row = self._row_by_name.get(node_name)
        if row is None:
            return
        entry = self._pods[row].pop(pod_key, None)
        if entry is None:
            return
        r = zpad(entry.req, self.cfg.r_cap)
        z = zpad(entry.nonzero, self.cfg.r_cap)
        self.m_req[row, :] -= r
        self.m_nonzero[row, :] -= z
        self.m_prio_req[row, entry.prio_band, :] -= r
        for i, mv in enumerate(entry.match_vec):
            if mv:
                self.m_sel_counts[row, i] -= 1
        # predicates interned after this pod was added were back-filled by
        # intern_predicate's scan, which saw this pod — account for them too.
        for sid in range(entry.match_cache_len, len(self.sel_vocab)):
            if self.sel_vocab.items[sid].matches(entry.namespace, entry.labels):
                self.m_sel_counts[row, sid] -= 1
        for tid, w in zip(entry.eterm_ids, entry.eterm_ws):
            self.m_eterm_w[row, tid] -= w
        for pid in entry.port_ids:
            self.m_port_counts[row, pid] -= 1
        self._dirty_rows.add(row)
        self.generation += 1

    def _match_vec(self, namespace: str, labels: Dict[str, str]) -> np.ndarray:
        out = np.zeros(len(self.sel_vocab), np.bool_)
        for i, pred in enumerate(self.sel_vocab.items):
            out[i] = pred.matches(namespace, labels)
        return out

    def update_pdb_blocked(self, pdbs: List["v1.PodDisruptionBudget"]) -> int:
        """Recompute the PDB budget column family (`pdb_blocked[N, PB]`)
        from the disruption controller's CURRENT published budgets: a
        placed pod counts as blocked when it matches any PDB whose
        status.disruptions_allowed is already spent (<= 0). This is the
        vectorized victim-selection kernel's node-DEPRIORITIZER, not the
        oracle — the per-victim budget countdown (list-order consumption
        across overlapping PDBs) stays in the host reprieve loop that
        validates every candidate before eviction. Caller holds the cache
        lock. Returns the number of rows whose column changed (each is
        marked dirty for the next flush)."""
        from ..api.selectors import match_labels as _match_labels

        blocked = [
            (pdb.metadata.namespace, pdb.spec.selector)
            for pdb in pdbs
            if pdb.status.disruptions_allowed <= 0
        ]
        if not blocked and not self._pdb_any_blocked:
            # common case (no exhausted budgets, column already clear):
            # skip the per-row matching entirely — this runs under the
            # cache lock on every failed batch
            return 0
        changed = 0
        for row, pods in self._pods.items():
            want = np.zeros(self.cfg.pb_cap, np.int32)
            if blocked:
                for e in pods.values():
                    for ns, sel in blocked:
                        if ns == e.namespace and _match_labels(sel, e.labels):
                            want[e.prio_band] += 1
                            break
            if not np.array_equal(self.m_pdb_blocked[row], want):
                self.m_pdb_blocked[row] = want
                self._dirty_rows.add(row)
                changed += 1
        self._pdb_any_blocked = bool(blocked)
        if changed:
            self.generation += 1
        return changed

    # -- anti-entropy hooks (scheduler/antientropy.py) -----------------------
    #
    # The pod-aggregate columns are maintained INCREMENTALLY (add/remove
    # deltas), which is exactly where a drift bug or a half-applied update
    # accumulates silently. These hooks let the auditor re-derive a row's
    # expected aggregates from the per-pod entries (the host source of
    # truth) and repair the masters and/or the device row in place.

    # row-major pod-aggregate fields re-derivable from _PodEntry records
    AGGREGATE_FIELDS = (
        "requested", "nonzero_req", "prio_req", "sel_counts", "eterm_w",
        "port_counts",
    )
    # every row-major (per-node) DeviceSnapshot field, for device-vs-master
    # audits; globals (band_prio, eterm metadata) are compared wholesale
    ROW_FIELDS = tuple(
        f for f in DeviceSnapshot._fields
        if f not in ("eterm_topo_key", "eterm_kind", "band_prio")
    )

    def _master_of(self, field: str) -> np.ndarray:
        return {
            "valid": self.m_valid,
            "unschedulable": self.m_unsched,
            "allocatable": self.m_alloc,
            "requested": self.m_req,
            "nonzero_req": self.m_nonzero,
            "label_vals": self.m_label_vals,
            "label_numvals": self.m_label_num,
            "taint_key": self.m_taint_key,
            "taint_val": self.m_taint_val,
            "taint_effect": self.m_taint_eff,
            "sel_counts": self.m_sel_counts,
            "eterm_w": self.m_eterm_w,
            "port_counts": self.m_port_counts,
            "image_bytes": self.m_image_bytes,
            "avoid": self.m_avoid,
            "prio_req": self.m_prio_req,
            "pdb_blocked": self.m_pdb_blocked,
            "cost_milli": self.m_cost,
            "accel_class": self.m_accel,
            "energy_milli": self.m_energy,
        }[field]

    def expected_row_aggregates(self, row: int) -> Dict[str, np.ndarray]:
        """Re-encode the pod-aggregate columns of one row from its
        _PodEntry records — what the masters MUST say if every
        incremental add/remove landed exactly once."""
        c = self.cfg
        req = np.zeros(c.r_cap, np.int32)
        nz = np.zeros(c.r_cap, np.int32)
        prio = np.zeros((c.pb_cap, c.r_cap), np.int32)
        sel = np.zeros(c.s_cap, np.int32)
        et = np.zeros(c.t_cap, np.float32)
        ports = np.zeros(c.pv_cap, np.int32)
        for e in self._pods.get(row, {}).values():
            req[: len(e.req)] += e.req
            nz[: len(e.nonzero)] += e.nonzero
            prio[e.prio_band, : len(e.req)] += e.req
            mv = e.match_vec
            sel[: len(mv)] += mv.astype(np.int32)
            # predicates interned after this pod was added were back-filled
            # by intern_predicate's scan (same rule remove_pod applies)
            for sid in range(e.match_cache_len, len(self.sel_vocab)):
                if self.sel_vocab.items[sid].matches(e.namespace, e.labels):
                    sel[sid] += 1
            for tid, w in zip(e.eterm_ids, e.eterm_ws):
                et[tid] += w
            for pid in e.port_ids:
                ports[pid] += 1
        return {
            "requested": req,
            "nonzero_req": nz,
            "prio_req": prio,
            "sel_counts": sel,
            "eterm_w": et,
            "port_counts": ports,
        }

    def verify_row_aggregates(self, row: int, repair: bool = False) -> List[str]:
        """Column names whose master row diverges from the entry-derived
        expectation; repair=True rewrites the masters and marks the row
        dirty so the next flush re-scatters it to the device."""
        expected = self.expected_row_aggregates(row)
        bad: List[str] = []
        for field, want in expected.items():
            m = self._master_of(field)
            if not np.array_equal(m[row], want):
                bad.append(field)
                if repair:
                    m[row] = want
        if bad and repair:
            self._dirty_rows.add(row)
            self.generation += 1
        return bad

    def drop_pod_entry(self, node_name: str, pod_key: str) -> bool:
        """Remove a pod's entry WITHOUT subtracting its aggregates — for
        unwinding a half-applied add_pod whose master increments may be
        partial (subtracting would double the damage). The caller must
        follow with repair_row()."""
        row = self._row_by_name.get(node_name)
        if row is None:
            return False
        return self._pods.get(row, {}).pop(pod_key, None) is not None

    def repair_row(self, node_name: str) -> List[str]:
        """Rebuild one row's aggregate masters from its entries, mark it
        dirty (next flush overwrites the device row), and flag it suspect
        for the anti-entropy auditor's next pass. Returns the repaired
        column names."""
        row = self._row_by_name.get(node_name)
        if row is None:
            return []
        bad = self.verify_row_aggregates(row, repair=True)
        # even when the masters were consistent, the DEVICE row may hold
        # occupancy the masters never saw (kernel-committed, replay
        # failed): force the re-scatter regardless
        self._dirty_rows.add(row)
        self.suspect_rows.add(row)
        return bad

    def fetch_device_rows(self, rows: List[int]) -> Optional[Dict[str, np.ndarray]]:
        """Gather the sampled rows of every row-major device field to host
        in ONE transfer (the audit's read side). None when no device
        snapshot exists yet.

        Runs under a generation pin, NOT a lock: the pinned generation's
        buffers cannot be donated while the lease is held (a concurrent
        wave launch advances through a copy), so this gather may overlap
        a donating launch freely — the exact round-8 interleaving that
        used to deadlock the CPU client is now legal.

        The gather index is padded to the scatter program sizes (16/1024,
        chunking larger sets): a distinct XLA program per sample size
        would compile on nearly every audit pass (the round-robin window
        tail and the suspect set both vary), each compile seconds of
        cache-lock hold."""
        if not rows:
            return None
        out: Dict[str, np.ndarray] = {}
        with self.pin_generation() as lease:
            if lease.snap is None:
                return None
            # barrier before reading: the pinned generation may be the
            # output of a scatter still in flight; waiting on the pinned
            # buffers (ours by lease — no aliasing possible) keeps the
            # audit's confirm fetch ordered after the repair it confirms
            jax.block_until_ready(lease.snap)
            for i in range(0, len(rows), _SCATTER_PAD_BIG):
                chunk = rows[i : i + _SCATTER_PAD_BIG]
                pad = (
                    _SCATTER_PAD_SMALL
                    if len(chunk) <= _SCATTER_PAD_SMALL
                    else _SCATTER_PAD_BIG
                )
                # pad rows repeat row 0 (cheap, in range); sliced off below
                idx = np.zeros(pad, np.int32)
                idx[: len(chunk)] = chunk
                gathered = jax.device_get(_gather_rows(lease.snap, idx))
                for name, arr in gathered.items():
                    arr = np.asarray(arr)[: len(chunk)]
                    out[name] = (
                        arr
                        if name not in out
                        else np.concatenate([out[name], arr])
                    )
        return out

    # -- device sync ---------------------------------------------------------

    def _masters(self) -> DeviceSnapshot:
        return DeviceSnapshot(
            valid=self.m_valid,
            unschedulable=self.m_unsched,
            allocatable=self.m_alloc,
            requested=self.m_req,
            nonzero_req=self.m_nonzero,
            label_vals=self.m_label_vals,
            label_numvals=self.m_label_num,
            taint_key=self.m_taint_key,
            taint_val=self.m_taint_val,
            taint_effect=self.m_taint_eff,
            sel_counts=self.m_sel_counts,
            eterm_w=self.m_eterm_w,
            eterm_topo_key=self.m_eterm_topo,
            eterm_kind=self.m_eterm_kind,
            port_counts=self.m_port_counts,
            image_bytes=self.m_image_bytes,
            avoid=self.m_avoid,
            prio_req=self.m_prio_req,
            band_prio=self.m_band_prio,
            pdb_blocked=self.m_pdb_blocked,
            cost_milli=self.m_cost,
            accel_class=self.m_accel,
            energy_milli=self.m_energy,
        )

    def flush(self, donate: bool = True) -> DeviceSnapshot:
        """Return the device snapshot, applying pending row deltas.

        Dirty-row scatter indices are padded to the next power of FOUR so
        only O(log₄ N) distinct update programs are ever compiled — each
        distinct pad size is an XLA compile that costs seconds through the
        tunnel; out-of-range pad indices are dropped by the scatter.
        Capacity growth or first use forces a full upload (the cold-start
        path, SURVEY.md §5 failure recovery: device memory is a rebuildable
        cache). Global (non-row) fields changed without any dirty row
        (band allocation, eterm interning) refresh via a row-less scatter.

        Every device write advances the snapshot generation through a
        donation lease (seal → dispatch → install); concurrent readers
        keep gathering from their pinned (previous) generation throughout.
        `donate=False` routes row scatters through the alias-free variant
        (`_scatter_rows_safe`) — the anti-entropy audit uses it so a repair
        can never be corrupted by the in-place update path it is auditing.
        """
        t0 = time.monotonic()
        self._flush_what = None
        try:
            return self._flush_inner(donate=donate)
        finally:
            dt = time.monotonic() - t0
            if dt > 0.2:
                logger.warning(
                    "slow flush %.0f ms: %s", dt * 1e3, self._flush_what
                )

    def _flush_inner(self, donate: bool = True) -> DeviceSnapshot:
        masters = self._masters()
        if self._gen is None or self._content_invalid:  # graftlint: unguarded(gen rebinds only happen on flush paths, serialized by the cache lock this runs under)
            self._flush_what = "full upload (first use or content invalid)"
            if self._snap_shardings is not None:
                snap = jax.device_put(masters, self._snap_shardings)
            else:
                snap = jax.device_put(jax.tree.map(jnp.asarray, masters))
            self._full_upload = False
            self._content_invalid = False
            self._globals_dirty = False
            self._dirty_rows.clear()
            self._install_generation(snap)
            return snap
        if self._full_upload:
            # capacity growth (_grow): device content is still valid, only
            # some field SHAPES changed. Re-upload exactly those fields from
            # the (grown, content-preserving) masters and keep the rest —
            # a t_cap bump mid-burst then costs one [N, t_cap] transfer, not
            # the full ~2 s snapshot re-upload. Dirty rows stay pending: the
            # scatter below applies them to the kept fields (for re-uploaded
            # fields it rewrites values already present — harmless). The
            # merged generation SHARES the kept buffers with its
            # predecessor, so it installs shared_with_base: donation
            # treats the pair as one pin scope until the predecessor
            # retires.
            with self.donation_lease(donating=False) as dl:
                merged = {}
                reshaped = []
                for name in DeviceSnapshot._fields:
                    m = getattr(masters, name)
                    d = getattr(dl.snap, name)
                    if tuple(d.shape) != m.shape:
                        reshaped.append(name)
                        if self._snap_shardings is not None:
                            merged[name] = jax.device_put(
                                m, getattr(self._snap_shardings, name)
                            )
                        else:
                            merged[name] = jax.device_put(jnp.asarray(m))
                    else:
                        merged[name] = d
                dl.result = DeviceSnapshot(**merged)
                dl.shared = True  # kept fields are the base's own buffers
            self._full_upload = False
            self._flush_what = f"reshape upload of {reshaped}"
        if not self._dirty_rows:
            if not self._globals_dirty:
                return self._device
            rows = []
        else:
            rows = sorted(self._dirty_rows)
            self._dirty_rows.clear()
        self._globals_dirty = False
        # exactly TWO scatter program sizes (16 / 1024), chunking larger
        # sets: every distinct pad is an XLA compile that costs 1.5-2 s
        # through the tunnel, and the old O(log4 N) pad ladder put those
        # compiles in the measured window the first time each size
        # appeared. Both variants are warmable at startup
        # (warm_scatter_programs). Chunk dispatches pipeline (async) so a
        # large set still costs ~one tunnel exchange.
        self._flush_what = (
            f"{(self._flush_what + ' + ') if self._flush_what else ''}"
            f"scatter of {len(rows)} dirty rows"
        )
        with self.donation_lease(donating=donate) as dl:
            snap = dl.snap
            first = True
            i = 0
            while first or i < len(rows):
                first = False
                chunk = rows[i : i + _SCATTER_PAD_BIG]
                i += _SCATTER_PAD_BIG
                snap = self._scatter_chunk(
                    snap, masters, chunk, donate=donate
                )
            dl.result = snap
        return snap

    def _scatter_chunk(  # graftlint: holds-generation-lease
        self,
        snap: DeviceSnapshot,
        masters: DeviceSnapshot,
        rows: list,
        pad: Optional[int] = None,
        donate: bool = True,
    ) -> DeviceSnapshot:
        # callers hold a donation lease (enforced by graftlint's donation
        # pass at every call site): `snap` is lease-scoped — the sealed
        # live buffers, or the lease's private copy when readers pin them
        if pad is None:
            pad = (
                _SCATTER_PAD_SMALL
                if len(rows) <= _SCATTER_PAD_SMALL
                else _SCATTER_PAD_BIG
            )
        n_cap = self.cfg.n_cap
        idx = np.full(pad, n_cap, np.int32)  # OOB pad rows -> dropped
        idx[: len(rows)] = rows
        sel = idx.clip(0, n_cap - 1)

        updates = DeviceSnapshot(
            **{
                name: (
                    getattr(masters, name)
                    if name in _GLOBAL_FIELDS
                    else np.ascontiguousarray(getattr(masters, name)[sel])
                )
                for name in DeviceSnapshot._fields
            }
        )
        # one device_put for the whole update pytree: transfers pipeline in
        # a single tunnel exchange instead of one ~65 ms RTT per field
        if self._rep_sharding is not None:
            sh = jax.tree.map(lambda _: self._rep_sharding, (idx, updates))
            idx_d, updates_d = jax.device_put((idx, updates), sh)
        else:
            idx_d, updates_d = jax.device_put((idx, updates))
        scatter = _scatter_rows if donate else _scatter_rows_safe
        return scatter(snap, idx_d, updates_d)

    def warm_scatter_programs(self) -> None:
        """Compile the scatter pad variants out-of-window (no-op scatters:
        all indices OOB-dropped), donating AND alias-free, plus the two
        padded audit gather programs and the copy-on-pin program — 7
        compiles at bring-up instead of mid-burst (or mid-audit under the
        cache lock: the first audit pass would otherwise pay the gather
        compiles while holding it). Call at component start, after the
        snapshot exists."""
        if self._gen is None:  # graftlint: unguarded(bring-up check: atomic ref read before any concurrent writer exists)
            self.flush()
        masters = self._masters()
        for donate in (True, False):
            with self.donation_lease(donating=donate) as dl:
                snap = self._scatter_chunk(
                    dl.snap, masters, [], pad=_SCATTER_PAD_SMALL,
                    donate=donate,
                )
                dl.result = self._scatter_chunk(
                    snap, masters, [], pad=_SCATTER_PAD_BIG, donate=donate
                )
        with self.pin_generation() as lease:
            if lease.snap is not None:
                for pad in (_SCATTER_PAD_SMALL, _SCATTER_PAD_BIG):
                    _gather_rows(lease.snap, np.zeros(pad, np.int32))
                # the copy program backs copy-on-pin donation: compile it
                # here, not the first time a reader overlaps a wave launch
                _copy_snapshot(lease.snap)

    def set_sharding(self, snap_shardings, replicated_sharding) -> None:
        """Adopt multi-chip placement (parallel/mesh.snapshot_shardings):
        row-major tensors shard over the mesh's node axis, update scatters
        replicate. Forces a fresh (sharded) upload."""
        self._snap_shardings = snap_shardings
        self._rep_sharding = replicated_sharding
        self.invalidate_device()

    @property
    def has_pending_updates(self) -> bool:
        """True when the host masters have diverged from an EXISTING device
        snapshot (flush would scatter or re-upload). Before the first flush
        there is no device state to be stale, so nothing is pending."""
        if self._device is None:
            return False
        return (
            # graftlint: unguarded(lock-free dirty peek by design: callers re-check under the cache lock before acting)
            bool(self._dirty_rows)
            or self._globals_dirty
            or self._full_upload
            or self._content_invalid
        )

    def mark_row_dirty(self, node_name: str) -> None:
        """Force a re-upload of one node row from the host masters. Used when
        a kernel-committed placement could NOT be replayed host-side (e.g.
        duplicate assume): the device row then holds occupancy the masters
        don't, and the next flush must overwrite it."""
        row = self._row_by_name.get(node_name)
        if row is not None:
            self._dirty_rows.add(row)

    def invalidate_device(self) -> None:
        """Device content unknowable (readback/kernel failure, resharding):
        the next flush re-uploads everything from the host masters."""
        self._full_upload = True
        self._content_invalid = True

    def swap_live_snapshot(self, snap: DeviceSnapshot) -> None:
        """Testing/fault-injection hook: install `snap` — typically the
        live snapshot with one field replaced — as a new generation that
        SHARES the remaining buffers with its predecessor (so a donating
        advance copies while any pin on the predecessor drains). The
        production write paths never call this; kernel outputs install
        through the wave launch's donation lease.

        (Design note, kept from the old `set_device_snapshot`: the wave
        kernel donates the input snapshot and returns it with batch
        commits applied; the scheduler replays the same commits into the
        host masters via cache assume → add_pod, so a subsequent row-set
        flush writes identical values — device and host stay convergent
        without a delta-add protocol, as long as replay happens before
        the next flush.)"""
        self._install_generation(snap, shared_with_base=True)

    # -- utilization / stranding columns (descheduler + tuner) ---------------

    def utilization_stats(self) -> "UtilizationStats":
        """Per-row utilization and stranded-capacity columns from the host
        masters — the fragmentation-score inputs (tuner/scoring.
        fragmentation_score) and the descheduler's candidate signal, read
        straight off the same aggregates the kernel's resource columns
        are scattered from (no second bookkeeping to drift). Pure numpy
        over the masters; caller holds the cache lock."""
        alloc = self.m_alloc.astype(np.int64)
        req = self.m_req.astype(np.int64)
        safe_alloc = np.maximum(alloc, 1)
        free = alloc - req
        # per-row utilization: max over resources of requested/allocatable
        # (the CA's node-utilization measure, matching the autoscaler's
        # host-side _utilization up to encoding quantization)
        util = np.where(alloc > 0, req / safe_alloc, 0.0).max(
            axis=1, initial=0.0
        )
        return UtilizationStats(
            valid=np.asarray(self.m_valid, bool).copy(),
            unschedulable=np.asarray(self.m_unsched, bool).copy(),
            used_any=(req > 0).any(axis=1) & np.asarray(self.m_valid, bool),
            util=np.asarray(util, np.float64),
            free_frac=np.clip(free / safe_alloc, 0.0, 1.0).mean(axis=1),
            cost_milli=self.m_cost.astype(np.int64).copy(),
        )

    # -- what-if simulation overlay (autoscaler) -----------------------------

    def free_row_indices(self) -> List[int]:
        """Row indices holding no live node (freed or never allocated), in
        ascending order — the rows a what-if overlay may claim for virtual
        candidate nodes without perturbing any live row."""
        used = {r for r, n in enumerate(self.row_names) if n is not None}
        return [r for r in range(self.cfg.n_cap) if r not in used]

    def whatif_overlay(
        self,
        virtual_nodes: List[v1.Node],
        mask_rows: Optional[List[int]] = None,
    ) -> Optional[Tuple[DeviceSnapshot, List[int]]]:
        """Copy-on-append simulation view of the snapshot: K VIRTUAL node
        rows (candidate machine shapes from the autoscaler's NodeGroup
        catalog) written into currently-free rows of a COPY of the live
        snapshot, plus `mask_rows` (scale-down drain what-if) flipped
        invalid. Returns (overlay_snapshot, rows) with rows[i] the row
        index assigned to virtual_nodes[i]; None when n_cap has no room
        for K more rows (the caller falls back to skipping the pass —
        growing n_cap here would recompile every kernel variant mid-run).

        Isolation contract (the generational successor of the PR-4
        donation discipline): the live snapshot is never mutated and
        never donated — the overlay is produced by the alias-free
        `_scatter_rows_safe` program, so every buffer of the returned
        snapshot is fresh; the overlay is never installed as a live
        generation and must never be handed to a donating program. The
        device section holds a generation PIN, not a lock: the scatter
        READS the pinned generation's buffers, which a concurrent wave
        launch cannot donate (it advances through a copy instead), so a
        what-if pass may overlap wave launches freely.

        Caller must hold the cache lock (vocab interning + the masters
        read must be consistent with row_names)."""
        mask_rows = list(mask_rows or [])
        free = self.free_row_indices()
        if len(virtual_nodes) > len(free):
            return None
        rows = free[: len(virtual_nodes)]
        # intern first: virtual labels/taints can grow capacities (shapes
        # change), which must settle before the base snapshot is chosen
        encoded = [self.encode_node_row_values(n) for n in virtual_nodes]
        masters = self._masters()
        with self.pin_generation() as lease:
            if lease.snap is not None and not self.has_pending_updates:
                # steady state: the live snapshot is current — the overlay
                # costs one padded row scatter, not a full upload. (When a
                # wave pipeline is in flight the device may additionally
                # hold kernel commits the masters haven't replayed yet;
                # the device view is then the MORE current base.)
                base = lease.snap
            elif self._snap_shardings is not None:
                base = jax.device_put(masters, self._snap_shardings)
            else:
                base = jax.device_put(jax.tree.map(jnp.asarray, masters))
            all_rows = rows + mask_rows
            out = base
            for i0 in range(0, max(len(all_rows), 1), _SCATTER_PAD_BIG):
                chunk = all_rows[i0 : i0 + _SCATTER_PAD_BIG]
                pad = (
                    _SCATTER_PAD_SMALL
                    if len(chunk) <= _SCATTER_PAD_SMALL
                    else _SCATTER_PAD_BIG
                )
                idx = np.full(pad, self.cfg.n_cap, np.int32)  # OOB dropped
                idx[: len(chunk)] = chunk
                upd = {}
                for name in DeviceSnapshot._fields:
                    m = getattr(masters, name)
                    if name in _GLOBAL_FIELDS:
                        upd[name] = m
                        continue
                    arr = np.zeros((pad,) + m.shape[1:], m.dtype)
                    for j, row in enumerate(chunk):
                        vi = i0 + j
                        if vi < len(rows):
                            # virtual row: node-static encoded values; the
                            # pod-aggregate columns stay zero (empty node)
                            v = encoded[vi].get(name)
                            if v is not None:
                                arr[j] = v
                        else:
                            # masked row: live values with valid cleared
                            arr[j] = m[row]
                            if name == "valid":
                                arr[j] = False
                    upd[name] = arr
                updates = DeviceSnapshot(**upd)
                if self._rep_sharding is not None:
                    sh = jax.tree.map(
                        lambda _: self._rep_sharding, (idx, updates)
                    )
                    idx_d, updates_d = jax.device_put((idx, updates), sh)
                else:
                    idx_d, updates_d = jax.device_put((idx, updates))
                out = _scatter_rows_safe(out, idx_d, updates_d)
        return out, rows


class UtilizationStats(NamedTuple):
    """Per-row utilization/stranding columns (SnapshotEncoder.
    utilization_stats): [N]-aligned with row_names. free_frac is the
    mean free/allocatable fraction per row — the stranded-capacity unit
    the fragmentation score sums; util is the CA-style max-over-resources
    requested/allocatable the descheduler thresholds candidates on."""

    valid: np.ndarray  # [N] bool — row holds a live node
    unschedulable: np.ndarray  # [N] bool — cordoned
    used_any: np.ndarray  # [N] bool — valid and hosting any request
    util: np.ndarray  # [N] float — max req/alloc over resources
    free_frac: np.ndarray  # [N] float — mean free/alloc over resources
    cost_milli: np.ndarray  # [N] int64 — $/h * 1000 (0 unlabeled)


# Fields of DeviceSnapshot that are NOT [N, ...] row-major (global metadata
# columns, replaced wholesale on flush instead of row-scattered).
_GLOBAL_FIELDS = frozenset({"eterm_topo_key", "eterm_kind", "band_prio"})

# The only two dirty-row scatter program sizes (see flush): small for the
# low-load trickle, big for storm/churn sets; larger sets chunk by big.
_SCATTER_PAD_SMALL = 16
_SCATTER_PAD_BIG = 1024


@jax.jit
def _gather_rows(snap: DeviceSnapshot, idx) -> dict:
    """Row gather of every row-major field (the anti-entropy audit's read
    side). idx is padded to one of the two scatter program sizes, so at
    most two gather programs ever compile."""
    return {
        name: jnp.take(getattr(snap, name), idx, axis=0)
        for name in SnapshotEncoder.ROW_FIELDS
    }


def _scatter_rows_impl(
    snap: DeviceSnapshot, idx, updates: DeviceSnapshot
) -> DeviceSnapshot:
    out = {}
    for name in DeviceSnapshot._fields:
        dst = getattr(snap, name)
        src = getattr(updates, name)
        if name in _GLOBAL_FIELDS:
            out[name] = src
        else:
            out[name] = dst.at[idx].set(src, mode="drop")
    return DeviceSnapshot(**out)


# hot path: donation lets XLA update the snapshot in place (no O(snapshot)
# copy per flush — the wave cadence depends on it)
_scatter_rows = functools.partial(jax.jit, donate_argnums=(0,))(_scatter_rows_impl)

# repair path: NO donation. The anti-entropy auditor's settle/repair
# scatters go through this variant: the PR-4 corruption (a donating
# executable deserialized from a persistent compilation cache writing
# garbage into non-targeted rows on CPU) hit exactly when donation
# aliased buffers a concurrent reader observed — gone structurally now
# that donation only ever consumes lease-private buffers, but the
# repairer still must not use the in-place update path it is auditing,
# so it pays the copy and gets fresh, alias-free output buffers. The
# marker below is machine-checked: graftlint fails if a donation keyword
# ever lands on this definition.
_scatter_rows_safe = jax.jit(_scatter_rows_impl)  # graftlint: alias-safe


def _copy_snapshot_impl(snap: DeviceSnapshot) -> DeviceSnapshot:
    # arithmetic identities, not `lambda x: x`: a jitted identity can
    # alias output to input, and an aliased "copy" would hand the donor
    # the very buffers the pin protects. Real ops allocate fresh output
    # buffers (no donation on this program, enforced by the marker below).
    def cp(a):
        if a.dtype == jnp.bool_:
            return jnp.logical_or(a, jnp.zeros((), jnp.bool_))
        return a + jnp.zeros((), a.dtype)

    return jax.tree.map(cp, snap)


# copy-on-pin: when a reader pins generation N, a donating wave launch
# consumes a fresh copy instead of the pinned buffers (DonationLease).
# NOT donating by construction — the whole point is fresh output buffers.
_copy_snapshot = jax.jit(_copy_snapshot_impl)  # graftlint: alias-safe


# lockset sanitizer (testing/lockgraph.py Eraser mode): the encoder's
# host bookkeeping is guarded by the CALLER's `scheduler.cache` lock
# (graftlint pass 6 infers the map; `--list-guards` prints it) and the
# generation table by `encoder.gen_lock`. Deliberately NOT tracked:
# `_gen` and the dirty flags, whose lock-free peeks are pragma'd
# `unguarded` in place — tracking them would indict the documented
# atomic-read design, not a bug.
track_attrs(
    SnapshotEncoder,
    "_retiring",
    "_next_gen_id",
    "_free_rows",
    "_pods",
    "_row_by_name",
    "row_names",
    "suspect_rows",
    "_flush_what",
)
